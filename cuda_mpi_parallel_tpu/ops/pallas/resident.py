"""VMEM-resident CG: the entire Krylov solve in ONE pallas kernel.

The reference's defining performance pathology is host-synchronous
orchestration: 8 kernel launches + 2 blocking device->host scalar syncs +
1 ``cudaMalloc`` per CG iteration (``CUDACG.cu:269-352``).  The jitted
``lax.while_loop`` solver (``solver/cg.py``) already eliminates the host
from the loop, but XLA still materializes intermediates to HBM at fusion
boundaries - the matvec, each dot product, and each vector update are
separate fusions, so r/p/Ap cross HBM several times per iteration (the
measured ~18-20 us/iter at 1M unknowns on v5e is consistent with ~4 full
array passes of HBM traffic).

This kernel goes one step further down the memory hierarchy: for grids
whose whole CG working set (b, x, r, p, Ap - five f32 planes) fits in
VMEM, the ENTIRE solve is a single pallas kernel.  Vectors are pinned in
VMEM scratch for the life of the solve; per-iteration HBM traffic is
ZERO; the 5-point stencil is applied as in-register shifted adds; the
two inner products reduce to SMEM scalars on-chip.  One kernel launch
per solve - the logical endpoint of the launch-count argument against
the reference's 8-per-iteration.

Semantics match ``solver.cg`` with ``x0=0`` (the reference's init fast
path, ``CUDACG.cu:247-259``), no preconditioner, ``method="cg"``, and
``check_every``-blocked convergence checks on absolute ``||r|| < tol``
(quirk Q3) plus optional ``rtol``: iterates follow the same recurrence
(up to f32 reduction-order rounding), extra iterations past convergence
stay inside the current check block, and the reported iteration count
lands on a block boundary.  Breakdown freezing mirrors ``_safe_div``:
only the exact 0/0 (``rho == p.Ap == 0``, an exact solve) zeroes the
step; a genuine breakdown (``p.Ap == 0`` with ``rho != 0``) divides to
inf so the health predicate stops the solve and reports BREAKDOWN.

Capacity: 5 resident planes + Mosaic's temporaries for the shift chain
bound the footprint at ~12 plane-sizes; :func:`supports_resident_2d`
gates on that against the device VMEM budget (128 MiB on v4/v5/v6, so
1024x1024 f32 - the BASELINE config #2 grid - uses well under half).
Larger grids belong to the HBM-streaming slab kernel
(``ops/pallas/stencil.py``) under the general solver.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import df64 as df
from ..blas1 import _two_prod, _two_sum

_ENV_OVERRIDE = "CMP_RESIDENT_VMEM_BYTES"

# Usable VMEM by TPU generation (device_kind substring -> bytes).  v2/v3
# cores have 16 MiB; v4 onward 128 MiB.  Interpret/CPU runs have no real
# VMEM constraint - modelled as the v5 figure so support decisions made
# in tests match the hardware they model.
_VMEM_BY_GENERATION = (
    ("v6", 128 * 1024 * 1024),
    ("v5", 128 * 1024 * 1024),
    ("v4", 128 * 1024 * 1024),
    ("v3", 16 * 1024 * 1024),
    ("v2", 16 * 1024 * 1024),
    ("cpu", 128 * 1024 * 1024),
)
_VMEM_FALLBACK = 128 * 1024 * 1024

# Peak resident planes: 5 pinned (b, x, r, p, Ap) plus Mosaic transients.
# The round-5 on-chip probe (tools/capacity_probe_r05.json) compiled and
# ran the kernel on a 128 MiB v5e at every grid in the ladder up to
# 2048^2 f32 AND at boundary grids within 1% of this bound's admissible
# ceiling of 4.79M cells - 2048x2304, 2056x2304, and (290, 128, 128) 3D
# (4.75M cells) all compile and solve correctly - consistent with the
# ~4-plane direct measurement at 1024^2 (HW_WINDOW item 2).  So the
# ENTIRE range a 7-plane gate admits is evidence-backed (footprint
# grows monotonically with cells; the extremes passed), preserving the
# invariant that the gate never admits a grid the compiler then fails
# to allocate.  The old value of 12 was modeled, not measured, and
# routed every grid past 1448^2 to ~3x slower engines.
_PLANES_BOUND = 7


def vmem_bytes(device=None) -> int:
    """Per-device VMEM budget (bytes) for the resident solver.

    Resolution order mirrors ``spmv.max_x_bytes``: ``CMP_RESIDENT_VMEM_BYTES``
    env override, then the per-generation table, then a 128 MiB fallback.
    """
    env = os.environ.get(_ENV_OVERRIDE)
    if env:
        try:
            budget = int(env)
        except ValueError as e:
            raise ValueError(
                f"{_ENV_OVERRIDE}={env!r} is not an integer byte count"
            ) from e
        if budget <= 0:
            raise ValueError(f"{_ENV_OVERRIDE} must be positive, got {budget}")
        return budget
    try:
        if device is None:
            device = jax.devices()[0]
        kind = device.device_kind.lower()
    except Exception:
        return _VMEM_FALLBACK
    for marker, budget in _VMEM_BY_GENERATION:
        if marker in kind:
            return budget
    return _VMEM_FALLBACK


def _extra_planes(preconditioned: bool, warm_start: bool,
                  cg1: bool = False) -> int:
    """Plane-count surcharges over ``_PLANES_BOUND``: the Chebyshev
    recurrence's transients, and the cg1 recurrence's pinned
    ``s = A p`` plane plus its ``w`` transient.  A warm start costs NO
    extra plane - the x0 input aliases the x output buffer
    (``input_output_aliases`` in ``_cg_resident_call``; the kernel
    reads x0 once at init and immediately overwrites it with the seeded
    x).  Every gate and every kernel ``vmem_limit_bytes`` computes its
    budget through this one function so they cannot diverge.

    The Chebyshev surcharge is a MEASURED 6, not the modeled 2: at
    1024^2 f32 Mosaic's scoped allocation for the cheb kernel is
    52.92 MB = ~12.6 plane-equivalents (round 5, on-chip) - the
    z/d recurrence keeps more transients live across the in-loop
    stencils than the two the hand-count predicted.  7 + 6 = 13
    covers the measured footprint with margin.  Probe coverage of the
    resulting cheb gate is NOT the within-1% coverage of the
    unpreconditioned ladder: the probe's largest cheb grid (1600x1536
    = 2.46M cells, tools/capacity_probe_r05.json) sits ~5% below the
    13-plane ceiling (~2.58M cells), so the top few percent of
    admitted grids extrapolate from the measured footprint rather
    than an on-chip compile."""
    del warm_start  # plane-neutral via aliasing; kept for call clarity
    return (6 if preconditioned else 0) + (2 if cg1 else 0)


def supports_resident_2d(nx: int, ny: int, itemsize: int = 4,
                         device=None, preconditioned: bool = False,
                         warm_start: bool = False,
                         cg1: bool = False) -> bool:
    """True if an (nx, ny) grid's CG working set fits the resident kernel.

    Tiling needs ``nx % 8 == 0 and ny % 128 == 0`` (f32 (8,128) tiles);
    capacity needs ``_PLANES_BOUND`` planes within the VMEM budget -
    plus ``_extra_planes`` for Chebyshev/warm-start (the gate must match
    the kernel's own ``vmem_limit_bytes`` or it admits grids the
    compiler then rejects).
    """
    if nx % 8 != 0 or ny % 128 != 0:
        return False
    if itemsize != 4:
        return False  # f32 only: df64/other dtypes take the general path
    planes = _PLANES_BOUND + _extra_planes(preconditioned, warm_start,
                                           cg1=cg1)
    return planes * nx * ny * itemsize <= vmem_bytes(device)


def _axis_shifts(u, axis):
    """The two one-step shifts of ``u`` along ``axis`` with zero fill
    (Dirichlet boundary), as Mosaic-friendly concatenations."""
    lo = [slice(None)] * u.ndim
    hi = [slice(None)] * u.ndim
    one = [slice(None)] * u.ndim
    lo[axis] = slice(1, None)
    hi[axis] = slice(None, -1)
    one[axis] = slice(None, 1)
    zero = jnp.zeros_like(u[tuple(one)])
    fwd = jnp.concatenate([u[tuple(lo)], zero], axis)
    bwd = jnp.concatenate([zero, u[tuple(hi)]], axis)
    return fwd, bwd


def _shift_stencil(u, scale):
    """5-point Dirichlet Laplacian as in-register shifted adds.

    Same formulation as ``models.operators.Stencil2D.matvec`` (XLA
    backend), with the ``jnp.pad`` halo replaced by zero-filled
    concatenations that Mosaic lowers to lane/sublane shifts.
    """
    up, down = _axis_shifts(u, 0)
    left, right = _axis_shifts(u, 1)
    return scale * (4.0 * u - up - down - left - right)


def _shift_stencil_3d(u, scale):
    """7-point Dirichlet Laplacian (``Stencil3D.matvec`` semantics):
    shifts along the leading (plane) axis plus the 2D sublane/lane
    shifts, all in-register."""
    acc = 6.0 * u
    for axis in (0, 1, 2):
        fwd, bwd = _axis_shifts(u, axis)
        acc = acc - fwd - bwd
    return scale * acc


def _safe_div_f32(num, den):
    """``solver.cg._safe_div`` semantics in-kernel (not imported: solver
    depends on this module): freeze ONLY the exact 0/0 - iterations past
    an exact solve inside a check block have rho = p.Ap = 0, and alpha =
    0 then fixes every vector in place; a genuine breakdown (den = 0
    with num != 0) divides to inf ON PURPOSE so the health predicate
    stops the next block and reports BREAKDOWN, never a silent spin to
    MAXITER.  The df64 twin is :func:`_safe_div_df`."""
    zero = (num == 0.0) & (den == 0.0)
    return jnp.where(zero, 0.0,
                     num / jnp.where(zero, jnp.ones_like(den), den))


def _resident_kernel(nblocks, check_every, degree, stencil_fn, has_x0,
                     params_ref, cap_ref, *refs):
    if has_x0:
        (b_ref, x0_ref, x_ref, iters_ref, rr_ref, indef_ref, conv_ref,
         health_ref, hist_ref, r_ref, p_ref, state_f, state_i) = refs
    else:
        (b_ref, x_ref, iters_ref, rr_ref, indef_ref, conv_ref,
         health_ref, hist_ref, r_ref, p_ref, state_f, state_i) = refs
    scale = params_ref[0]
    tol = params_ref[1]
    rtol = params_ref[2]
    cap = cap_ref[0]

    def precond(r):
        """degree-term Chebyshev approximation of A^-1 applied to r -
        the in-kernel form of ``models.precond.ChebyshevPreconditioner
        .matvec`` (Saad Alg. 12.1 semi-iteration from z0 = 0): pure VPU
        work, ``degree - 1`` extra stencil applies, no reductions."""
        lmin = params_ref[3]
        lmax = params_ref[4]
        theta = (lmax + lmin) * 0.5
        delta = (lmax - lmin) * 0.5
        sigma = theta / delta
        rho_c = 1.0 / sigma
        d = r / theta
        z = d
        for _ in range(degree - 1):
            rho_n = 1.0 / (2.0 * sigma - rho_c)
            d = (rho_n * rho_c) * d + (2.0 * rho_n / delta) * (
                r - stencil_fn(z, scale))
            z = z + d
            rho_c = rho_n
        return z

    b = b_ref[:]
    if has_x0:
        # general init: r0 = b - A x0 (solver.cg's nonzero-x0 extension
        # of the reference's copy-only x0 = 0 fast path)
        x0 = x0_ref[:]
        x_ref[:] = x0
        r0 = b - stencil_fn(x0, scale)
    else:
        x_ref[:] = jnp.zeros_like(b)        # explicit x0 = 0 (quirk Q6)
        r0 = b                              # r0 = b  (CUDACG.cu:248)
    r_ref[:] = r0
    rr0 = jnp.sum(r0 * r0)                  # CUDACG.cu:261-266
    if degree > 0:
        z0 = precond(r0)
        p_ref[:] = z0                       # p0 = z0 (preconditioned init)
        rho0 = jnp.sum(r0 * z0)             # rho = r . z
    else:
        p_ref[:] = r0                       # p0 = r0 (CUDACG.cu:255)
        rho0 = rr0
    thresh = jnp.maximum(tol, rtol * jnp.sqrt(rr0))
    thresh2 = thresh * thresh

    state_f[0] = rr0       # ||r||^2 carried across blocks (convergence)
    state_f[1] = rho0      # r . z (== rr unpreconditioned)
    state_i[0] = jnp.int32(0)   # iterations completed
    state_i[1] = jnp.int32(0)   # indefiniteness observed (quirk Q1)

    # Block-granular residual trace (quirk Q7 on the flagship engine):
    # slot 0 = ||r0||^2, slot j+1 = ||r||^2 after check block j - the
    # value the kernel already holds in SMEM for the convergence
    # decision, so the trace costs nothing per iteration.  Blocks that
    # never run (converged / breakdown / cap) leave the -1.0 sentinel -
    # NOT NaN: the trace is always emitted, and a NaN fill would trip
    # jax_debug_nans on every default solve (the wrapper converts the
    # sentinel to NaN only when history is requested; ||r||^2 >= 0 makes
    # -1.0 unambiguous).
    hist_ref[0] = rr0

    def sentinel_fill(j, c):
        hist_ref[j] = jnp.float32(-1.0)
        return c

    lax.fori_loop(1, nblocks + 1, sentinel_fill, jnp.int32(0))

    def block(blk, carry):
        # Health mirrors the general solver's predicate (solver/cg.py):
        # non-finite scalars are a breakdown, and rho <= 0 with r != 0 is
        # a preconditioner breakdown (M not SPD) - stop, don't spin.
        healthy = (jnp.isfinite(state_f[0]) & jnp.isfinite(state_f[1])
                   & (state_f[1] > 0.0))

        # Continue-condition mirrors solver/cg.py's cond EXACTLY:
        # unconverged is rr >= thresh^2 (strict < converges, so an exact
        # rr == thresh^2 tie keeps iterating - same boundary as
        # _threshold_sq/_package), and rr > 0 stops an exactly-solved
        # system (iterating further would divide 0/0).
        @pl.when((state_f[0] >= thresh2) & (state_f[0] > 0.0)
                 & (state_i[0] < cap) & healthy)
        def _():
            # Final (partial) block: never run past the traced cap - the
            # general solver's _block_fits + remainder-pass semantics
            # (iterations <= maxiter/iter_cap always).
            nsteps = jnp.minimum(jnp.int32(check_every), cap - state_i[0])

            def one_iter(_, carry):
                rr, rho = carry
                p = p_ref[:]
                ap = stencil_fn(p, scale)
                pap = jnp.sum(p * ap)
                # pap == 0 means an exact solve (p == 0), not
                # indefiniteness - same guard as solver/cg.py's
                # (p_ap <= 0) & (rr > 0).
                state_i[1] = jnp.where((pap <= 0.0) & (rr > 0.0),
                                       jnp.int32(1), state_i[1])
                alpha = _safe_div_f32(rho, pap)
                x_ref[:] = x_ref[:] + alpha * p        # CUDACG.cu:314
                r_new = r_ref[:] - alpha * ap          # CUDACG.cu:320-321
                r_ref[:] = r_new
                rr_new = jnp.sum(r_new * r_new)        # CUDACG.cu:328
                if degree > 0:
                    z_new = precond(r_new)
                    rho_new = jnp.sum(r_new * z_new)
                else:
                    z_new, rho_new = r_new, rr_new
                beta = _safe_div_f32(rho_new, rho)     # CUDACG.cu:336-339
                p_ref[:] = z_new + beta * p
                return rr_new, rho_new

            rr_out, rho_out = lax.fori_loop(
                0, nsteps, one_iter, (state_f[0], state_f[1]))
            state_f[0] = rr_out
            state_f[1] = rho_out
            state_i[0] = state_i[0] + nsteps
            hist_ref[blk + 1] = rr_out
        return carry

    lax.fori_loop(0, nblocks, block, jnp.int32(0))

    iters_ref[0] = state_i[0]
    rr_ref[0] = state_f[0]
    indef_ref[0] = state_i[1]
    # converged, decided on the KERNEL's threshold: the wrapper cannot
    # recompute it bit-identically (different reduction order for ||b||
    # would let the flag contradict the actual stop decision).  Strict
    # rr < thresh^2, plus the exact-solve rr == 0 case - _package's
    # formula, so a rr == thresh^2 tie is NOT converged (and the
    # continue-condition above keeps iterating on it).
    conv_ref[0] = ((state_f[0] < thresh2)
                   | (state_f[0] == 0.0)).astype(jnp.int32)
    # final health, the general solver's exact formula (solver/cg.py):
    # a rho <= 0 stop with r != 0 is a preconditioner breakdown and must
    # surface as BREAKDOWN, not MAXITER.
    health_ref[0] = (jnp.isfinite(state_f[0]) & jnp.isfinite(state_f[1])
                     & ((state_f[1] > 0.0) | (state_f[0] == 0.0))
                     ).astype(jnp.int32)


def _coerce_x0(x0, b_grid):
    """Validate an optional warm-start x0 against the rhs grid: exactly
    the rhs's accepted shapes - flat ``(n,)`` or the exact grid shape -
    so a transposed/mis-shaped x0 is rejected, not silently
    reinterpreted."""
    if x0 is None:
        return None
    x0 = jnp.asarray(x0)
    if x0.ndim == 1 and x0.shape[0] == math.prod(b_grid.shape):
        x0 = x0.reshape(b_grid.shape)
    elif x0.shape != b_grid.shape:
        raise ValueError(
            f"x0 shape {x0.shape} matches neither the grid "
            f"{b_grid.shape} nor its flat length")
    if x0.dtype != jnp.float32:
        raise ValueError(f"x0 must be float32, got {x0.dtype}")
    return x0


def _check_grid_fits(shape, *, df64: bool, preconditioned: bool,
                     interpret: bool, warm_start: bool = False,
                     cg1: bool = False) -> None:
    """Shared entry gate of the four resident wrappers: raise unless the
    grid fits the kernel it is about to launch (tiling + the SAME plane
    budget the kernel's ``vmem_limit_bytes`` uses)."""
    if interpret:
        return
    if len(shape) == 2:
        ok = (supports_resident_df64_2d(*shape,
                                        preconditioned=preconditioned)
              if df64
              else supports_resident_2d(*shape,
                                        preconditioned=preconditioned,
                                        warm_start=warm_start, cg1=cg1))
        tiling = "nx % 8 == 0, ny % 128 == 0"
    else:
        ok = (supports_resident_df64_3d(*shape,
                                        preconditioned=preconditioned)
              if df64
              else supports_resident_3d(*shape,
                                        preconditioned=preconditioned,
                                        warm_start=warm_start, cg1=cg1))
        tiling = "ny % 8 == 0, nz % 128 == 0"
    if not ok:
        planes = (_PLANES_BOUND_DF64 + _extra_planes_df64(preconditioned)
                  if df64
                  else _PLANES_BOUND
                  + _extra_planes(preconditioned, warm_start, cg1=cg1))
        raise ValueError(
            f"{shape} {'df64' if df64 else 'f32'} grid does not fit the "
            f"resident kernel: needs {tiling} and {planes} * grid bytes "
            f"<= {vmem_bytes()} (set {_ENV_OVERRIDE} to override the "
            f"budget)")


def _check_method(method: str, precond_degree: int) -> None:
    if method not in ("cg", "cg1"):
        raise ValueError(
            f"resident method must be 'cg' or 'cg1', got {method!r}")
    if method == "cg1" and precond_degree > 0:
        raise ValueError(
            "the resident cg1 kernel is unpreconditioned (the "
            "preconditioned Chronopoulos-Gear form needs a third "
            "reduction); use method='cg' with precond_degree, or drop "
            "the preconditioner")


def _check_loop_args(check_every: int, maxiter: int,
                     precond_degree: int = 0) -> int:
    """Validate the loop arguments and return ``check_every`` clamped to
    ``[1, max(maxiter, 1)]``: a block never overshoots ``maxiter``, and
    ``maxiter == 0`` keeps ``check_every`` at 1 so ``nblocks`` computes
    to 0 (a zero-iteration solve) rather than dividing by zero - the
    general solver handles ``maxiter == 0`` gracefully and
    ``engine="auto"`` must not differ.  Shared by all four resident
    wrappers so the clamp cannot drift."""
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    if precond_degree < 0:
        raise ValueError(
            f"precond_degree must be >= 0, got {precond_degree}")
    if maxiter < 0:
        raise ValueError(f"maxiter must be >= 0, got {maxiter}")
    return max(1, min(check_every, maxiter))


def _resident_kernel_cg1(nblocks, check_every, stencil_fn, has_x0,
                         params_ref, cap_ref, *refs):
    """Chronopoulos-Gear single-reduction CG, VMEM-resident.

    Algebraically the textbook recurrence (``solver.cg._cg1`` - tests
    assert trajectory parity), rearranged so BOTH per-iteration inner
    products are evaluated at one point on the same pair of freshly
    computed vectors (r, w = A r): the two SMEM fold trees become
    INDEPENDENT and can overlap in the VPU's instruction stream, where
    the plain kernel's trees are serialized around the vector updates
    (the roofline's bottleneck #2, BASELINE.md).  Price: one extra
    pinned plane (s = A p) and one extra vector update per iteration.
    Unpreconditioned only (the preconditioned cg1 form needs a third
    dot).
    """
    if has_x0:
        (b_ref, x0_ref, x_ref, iters_ref, rr_ref, indef_ref, conv_ref,
         health_ref, hist_ref, r_ref, p_ref, s_ref, state_f,
         state_i) = refs
    else:
        (b_ref, x_ref, iters_ref, rr_ref, indef_ref, conv_ref,
         health_ref, hist_ref, r_ref, p_ref, s_ref, state_f,
         state_i) = refs
    scale = params_ref[0]
    tol = params_ref[1]
    rtol = params_ref[2]
    cap = cap_ref[0]

    b = b_ref[:]
    if has_x0:
        x0 = x0_ref[:]
        x_ref[:] = x0
        r0 = b - stencil_fn(x0, scale)
    else:
        x_ref[:] = jnp.zeros_like(b)        # explicit x0 = 0 (quirk Q6)
        r0 = b
    r_ref[:] = r0
    w0 = stencil_fn(r0, scale)
    rr0 = jnp.sum(r0 * r0)
    delta0 = jnp.sum(w0 * r0)
    p_ref[:] = r0
    s_ref[:] = w0
    thresh = jnp.maximum(tol, rtol * jnp.sqrt(rr0))
    thresh2 = thresh * thresh

    state_f[0] = rr0                        # ||r||^2 (== gamma, unprecond)
    state_f[1] = _safe_div_f32(rr0, delta0)  # alpha, one step ahead
    state_i[0] = jnp.int32(0)
    state_i[1] = ((delta0 <= 0.0) & (rr0 > 0.0)).astype(jnp.int32)

    hist_ref[0] = rr0

    def sentinel_fill(j, c):
        hist_ref[j] = jnp.float32(-1.0)
        return c

    lax.fori_loop(1, nblocks + 1, sentinel_fill, jnp.int32(0))

    def block(blk, carry):
        healthy = (jnp.isfinite(state_f[0]) & jnp.isfinite(state_f[1]))

        @pl.when((state_f[0] >= thresh2) & (state_f[0] > 0.0)
                 & (state_i[0] < cap) & healthy)
        def _():
            nsteps = jnp.minimum(jnp.int32(check_every), cap - state_i[0])

            def one_iter(_, carry):
                rr, alpha = carry
                x_ref[:] = x_ref[:] + alpha * p_ref[:]
                r_new = r_ref[:] - alpha * s_ref[:]
                r_ref[:] = r_new
                w = stencil_fn(r_new, scale)
                # the single evaluation point: both reductions on
                # (r_new, w) - independent fold trees
                rr_new = jnp.sum(r_new * r_new)
                delta = jnp.sum(w * r_new)
                beta = _safe_div_f32(rr_new, rr)
                denom = delta - beta * _safe_div_f32(rr_new, alpha)
                alpha_new = _safe_div_f32(rr_new, denom)
                state_i[1] = jnp.where((denom <= 0.0) & (rr_new > 0.0),
                                       jnp.int32(1), state_i[1])
                p_ref[:] = r_new + beta * p_ref[:]
                s_ref[:] = w + beta * s_ref[:]
                return rr_new, alpha_new

            rr_out, alpha_out = lax.fori_loop(
                0, nsteps, one_iter, (state_f[0], state_f[1]))
            state_f[0] = rr_out
            state_f[1] = alpha_out
            state_i[0] = state_i[0] + nsteps
            hist_ref[blk + 1] = rr_out
        return carry

    lax.fori_loop(0, nblocks, block, jnp.int32(0))

    iters_ref[0] = state_i[0]
    rr_ref[0] = state_f[0]
    indef_ref[0] = state_i[1]
    conv_ref[0] = ((state_f[0] < thresh2)
                   | (state_f[0] == 0.0)).astype(jnp.int32)
    # _cg1's health formula (gamma == rr unpreconditioned): non-finite
    # scalars are a breakdown; rr <= 0 cannot misreport because rr == 0
    # is the converged exact solve.
    health_ref[0] = (jnp.isfinite(state_f[0]) & jnp.isfinite(state_f[1])
                     ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "degree", "interpret", "method"))
def _cg_resident_call(scale, tol, rtol, lmin, lmax, cap, b_grid, x0_grid,
                      *, shape, maxiter, check_every, degree, interpret,
                      method="cg"):
    nblocks = -(-maxiter // check_every)
    params = jnp.stack([
        jnp.asarray(scale, jnp.float32),
        jnp.asarray(tol, jnp.float32),
        jnp.asarray(rtol, jnp.float32),
        jnp.asarray(lmin, jnp.float32),
        jnp.asarray(lmax, jnp.float32)])
    cap_arr = jnp.asarray(cap, jnp.int32).reshape(1)
    stencil_fn = _shift_stencil if len(shape) == 2 else _shift_stencil_3d
    has_x0 = x0_grid is not None
    if method == "cg1":
        kernel = functools.partial(_resident_kernel_cg1, nblocks,
                                   check_every, stencil_fn, has_x0)
    else:
        kernel = functools.partial(_resident_kernel, nblocks, check_every,
                                   degree, stencil_fn, has_x0)
    cells = math.prod(shape)
    grid_inputs = (b_grid,) if x0_grid is None else (b_grid, x0_grid)
    x, iters, rr, indef, conv, health, hist = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # params [scale,tol,rtol]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # iteration cap
        ] + [pl.BlockSpec(memory_space=pltpu.VMEM)] * len(grid_inputs),
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),   # x
            pl.BlockSpec(memory_space=pltpu.SMEM),   # iterations
            pl.BlockSpec(memory_space=pltpu.SMEM),   # final ||r||^2
            pl.BlockSpec(memory_space=pltpu.SMEM),   # indefinite flag
            pl.BlockSpec(memory_space=pltpu.SMEM),   # converged flag
            pl.BlockSpec(memory_space=pltpu.SMEM),   # healthy flag
            pl.BlockSpec(memory_space=pltpu.SMEM),   # per-block ||r||^2 trace
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((nblocks + 1,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM(shape, jnp.float32),          # r
            pltpu.VMEM(shape, jnp.float32),          # p
        ] + ([pltpu.VMEM(shape, jnp.float32)]        # s = A p (cg1 only)
             if method == "cg1" else []) + [
            pltpu.SMEM((2,), jnp.float32),           # rr, rho/alpha
            pltpu.SMEM((2,), jnp.int32),             # k, indefinite
        ],
        # The warm-start x0 input (input index 3) aliases the x output:
        # the kernel reads x0 exactly once at init and immediately seeds
        # x from it, so sharing the buffer is safe and keeps warm start
        # plane-neutral (XLA inserts a copy if the caller's x0 is still
        # live - correctness never depends on the donation landing).
        input_output_aliases=({3: 0} if has_x0 else {}),
        # The default scoped-vmem limit (16 MiB) is sized for streaming
        # kernels; residency is the point here, so lift it to the gated
        # footprint bound plus an 8 MiB fixed margin: Mosaic carries
        # SIZE-INDEPENDENT temporaries that a 1 MiB margin did not
        # cover once the plane bound dropped to the measured 7 (round
        # 5: 512^2 cheb allocated 11.81M against a 10M limit - ~2.8 MB
        # of overhead at a grid where planes are only 1 MB).  The
        # margin only loosens the compiler's self-check; the capacity
        # GATE stays planes * cells * 4 <= vmem_bytes(), and every
        # gate-admitted grid is probe-verified to actually fit
        # (tools/capacity_probe_r05.json).
        compiler_params=pltpu.CompilerParams(
            # clamped to the physical part: at gate-boundary grids the
            # planes-plus-margin figure can poke past the ceiling, and
            # the ceiling is the real cap anyway (graftlint GL102)
            vmem_limit_bytes=min(
                (_PLANES_BOUND
                 + _extra_planes(degree > 0, has_x0,
                                 cg1=method == "cg1"))
                * cells * 4 + (8 << 20),
                vmem_bytes())),
        interpret=interpret,
    )(params, cap_arr, *grid_inputs)
    return x, iters[0], rr[0], indef[0], conv[0], health[0], hist


def cg_resident_2d(scale, b2d, *, x0=None, tol=0.0, rtol=0.0,
                   maxiter=2000, check_every=32, iter_cap=None,
                   interpret=False, precond_degree=0, lmin=0.0, lmax=1.0,
                   method="cg"):
    """Run the whole CG solve for the 5-point stencil in one pallas kernel.

    Args:
      scale: stencil scale factor (traced scalar ok).
      b2d: right-hand side on the (nx, ny) grid, float32.
      x0: optional float32 warm-start guess (flat or grid shape);
        ``None`` = the reference's x0 = 0 fast path, otherwise the
        general ``r0 = b - A x0`` init (one extra stencil apply; the
        x0 buffer aliases the x output, so no extra VMEM plane).
      tol / rtol: absolute / relative tolerance on ``||r||_2`` (reference
        quirk Q3 semantics; threshold is ``max(tol, rtol * ||r0||)``
        with ``r0 = b`` for the default zero x0 - the general solver's
        exact formula, which for a near-exact warm start makes an
        ``rtol`` threshold much tighter than ``rtol * ||b||``).
      maxiter: static iteration bound (sizes the block loop).
      check_every: convergence-check block depth; iterations are reported
        at block granularity, matching ``solver.cg``'s ``check_every``
        (the final block truncates at ``maxiter``/``iter_cap``, so the
        count never exceeds the cap).
      iter_cap: optional *traced* cap <= maxiter (segmented solves vary
        this without recompiling).
      interpret: run in pallas interpret mode (CPU tests).
      precond_degree: 0 = unpreconditioned (the reference's
        configuration); k >= 1 applies the k-term Chebyshev polynomial
        preconditioner IN-KERNEL on the spectral interval
        ``[lmin, lmax]`` (``models.precond.ChebyshevPreconditioner``
        semantics) - ``k - 1`` extra stencil applies per iteration, all
        VPU work on the VMEM-resident planes.
      lmin / lmax: Chebyshev spectral interval (traced scalars; ignored
        when ``precond_degree == 0``).

    Returns:
      ``(x2d, iterations, rr, indefinite, converged, healthy, hist)`` -
      solution grid, block-aligned iteration count (int32), final
      ``||r||^2`` (f32), whether ``p.Ap <= 0`` was observed (int32 0/1;
      quirk Q1), the kernel's own convergence decision (int32 0/1), the
      general solver's health predicate at exit (int32 0/1; 0 means
      BREAKDOWN - non-finite scalars or ``rho <= 0`` with ``r != 0``),
      and the block-granular ``||r||^2`` trace (f32, ``nblocks + 1``
      slots: slot 0 is ``||r0||^2``, slot j+1 the value after check
      block j, -1.0 sentinel for blocks that never ran - the solver
      wrapper converts to NaN) - closing quirk Q7 on this engine at
      check-block granularity.
    """
    b2d = jnp.asarray(b2d)
    if b2d.ndim != 2:
        raise ValueError(f"b2d must be 2-D (the grid), got {b2d.shape}")
    if b2d.dtype != jnp.float32:
        raise ValueError(f"resident CG is float32-only, got {b2d.dtype}")
    check_every = _check_loop_args(check_every, maxiter, precond_degree)
    _check_method(method, precond_degree)
    x0 = _coerce_x0(x0, b2d)
    _check_grid_fits(b2d.shape, df64=False,
                     preconditioned=precond_degree > 0,
                     interpret=interpret, warm_start=x0 is not None,
                     cg1=method == "cg1")
    cap = maxiter if iter_cap is None else iter_cap
    return _cg_resident_call(
        scale, tol, rtol, lmin, lmax, cap, b2d, x0, shape=b2d.shape,
        maxiter=maxiter, check_every=check_every,
        degree=int(precond_degree), interpret=interpret, method=method)


def supports_resident_3d(nx: int, ny: int, nz: int, itemsize: int = 4,
                         device=None, preconditioned: bool = False,
                         warm_start: bool = False,
                         cg1: bool = False) -> bool:
    """True if an (nx, ny, nz) grid's CG working set fits the resident
    kernel: ``ny % 8 == 0 and nz % 128 == 0`` (the trailing two axes
    carry the (8, 128) f32 tiles; the leading plane axis is free) plus
    the same plane-count capacity bound as 2D."""
    if ny % 8 != 0 or nz % 128 != 0 or nx < 1:
        return False
    if itemsize != 4:
        return False
    planes = _PLANES_BOUND + _extra_planes(preconditioned, warm_start,
                                           cg1=cg1)
    return planes * nx * ny * nz * itemsize <= vmem_bytes(device)


def cg_resident_3d(scale, b3d, *, x0=None, tol=0.0, rtol=0.0,
                   maxiter=2000, check_every=32, iter_cap=None,
                   interpret=False, precond_degree=0, lmin=0.0, lmax=1.0,
                   method="cg"):
    """The 7-point-stencil (``Stencil3D``) form of :func:`cg_resident_2d`:
    same kernel, same semantics and return contract, with the 3D
    shifted-add Laplacian - for 3D grids small enough to pin in VMEM
    (up to ~128^3 f32 on a 128 MiB part; BASELINE's 256^3 north star
    runs on the fused-iteration streaming engine,
    ``solver.streaming.cg_streaming`` / ``solve(engine="streaming")``)."""
    b3d = jnp.asarray(b3d)
    if b3d.ndim != 3:
        raise ValueError(f"b3d must be 3-D (the grid), got {b3d.shape}")
    if b3d.dtype != jnp.float32:
        raise ValueError(f"resident CG is float32-only, got {b3d.dtype}")
    check_every = _check_loop_args(check_every, maxiter, precond_degree)
    _check_method(method, precond_degree)
    x0 = _coerce_x0(x0, b3d)
    _check_grid_fits(b3d.shape, df64=False,
                     preconditioned=precond_degree > 0,
                     interpret=interpret, warm_start=x0 is not None,
                     cg1=method == "cg1")
    cap = maxiter if iter_cap is None else iter_cap
    return _cg_resident_call(
        scale, tol, rtol, lmin, lmax, cap, b3d, x0, shape=b3d.shape,
        maxiter=maxiter, check_every=check_every,
        degree=int(precond_degree), interpret=interpret, method=method)


# -- df64 (double-float) resident CG ------------------------------------------
#
# The reference's defining precision is f64 (``CUDA_R_64F``,
# ``CUDACG.cu:216``); the framework's df64 layer (``ops/df64.py``) delivers
# f64-class values as (hi, lo) f32 pairs on hardware with no f64 units.
# Here the two combine: the ENTIRE df64 CG solve in one pallas kernel,
# eight planes (b/x/r/p, hi+lo each) pinned in VMEM, the stencil and both
# inner products evaluated in error-free-transform arithmetic on the VPU
# with zero per-iteration HBM traffic.  The df64 ops imported from
# ``ops.df64`` are branch-free elementwise jnp code, so they lower through
# Mosaic unchanged - including the add-only ``_two_prod`` error chain that
# no compiler contraction can break (see ``blas1._two_prod``).

# df64 working set: 8 pinned planes + ap (2) + the dot/stencil temporaries.
# Measured on v5e (round 5): Mosaic's actual scoped allocation at 1024^2 is
# 104.30M = 26.1 planes - a 24-plane limit made the compiler reject a grid
# the gate had admitted.  27 is the measured footprint plus headroom; the
# chip accepts the resulting 108 MiB scoped limit (128 MiB VMEM part).
_PLANES_BOUND_DF64 = 27


def _extra_planes_df64(preconditioned: bool) -> int:
    """df64 plane surcharge for the in-kernel Chebyshev recurrence.

    MEASURED 14, not the hand-modeled 4: at 512^2 Mosaic's scoped
    allocation for the df64 cheb kernel is 44.69 MB = ~41.7
    plane-equivalents (round 5, on-chip) - the EFT z/d hi/lo recurrence
    keeps far more transients live across the in-loop df64 stencils
    than the pair-count suggests.  27 + 14 = 41 covers it; the
    largest df64-cheb grid the probe compiled on-chip (768x1024 =
    786k cells, tools/capacity_probe_r05.json) sits ~4% below the
    ~818k-cell gate ceiling a 128 MiB part implies, so - unlike the
    within-1% f32 unpreconditioned ladder - the last few percent of
    admitted grids are extrapolated, not probe-verified.  Gates and
    the kernel's ``vmem_limit_bytes`` share this function (same
    invariant as ``_extra_planes``)."""
    return 14 if preconditioned else 0


def supports_resident_df64_2d(nx: int, ny: int, device=None,
                              preconditioned: bool = False) -> bool:
    """True if an (nx, ny) grid's df64 CG working set fits in VMEM."""
    if nx % 8 != 0 or ny % 128 != 0:
        return False
    planes = _PLANES_BOUND_DF64 + _extra_planes_df64(preconditioned)
    return planes * nx * ny * 4 <= vmem_bytes(device)


#: df64 fold-tree radix (env ``CMP_DF64_FOLD_RADIX``, default 2).  The
#: roofline's bottleneck-#2 experiment (a): a radix-r level combines r
#: contiguous chunks through a PAIRWISE tree (depth ceil(log2 r)), so
#: the dependent-add depth stays ~log2(m) at any radix - what radix 4
#: actually halves is the number of slice/pad/concatenate ROUNDS
#: (e.g. 13 -> 7 on an 8192-lane axis), isolating whether that
#: bookkeeping, not the adds, is what the trees pay for.  Read at
#: TRACE time: set the env var before the first kernel build to A/B on
#: hardware without code changes.  The replay-resumable df64 path
#: records the radix in its checkpoints (the summation order changes
#: bitwise results, so a cross-radix resume must fail loudly).
_FOLD_RADIX_ENV = "CMP_DF64_FOLD_RADIX"


def _fold_radix() -> int:
    radix = int(os.environ.get(_FOLD_RADIX_ENV, "2"))
    if radix < 2:
        raise ValueError(f"{_FOLD_RADIX_ENV} must be >= 2, got {radix}")
    return radix


def _fold_grid_df(hi, lo):
    """Reduce a df64 grid pair (any rank) to a scalar pair through
    radix-``_fold_radix()`` folding trees of full df64 adds - the
    in-kernel form of ``ops.df64._fold_df`` (contiguous chunk slices,
    never strided; axis by axis; ragged extents zero-pad, exact for
    adds)."""
    radix = _fold_radix()

    def fold_axis(h, l, axis):
        while h.shape[axis] > 1:
            m = h.shape[axis]
            r = min(radix, m)
            chunk = -(-m // r)
            pad = chunk * r - m
            if pad:
                padding = [slice(None)] * h.ndim
                padding[axis] = slice(None, pad)
                zh = jnp.zeros_like(h[tuple(padding)])
                h = jnp.concatenate([h, zh], axis)
                l = jnp.concatenate([l, jnp.zeros_like(zh)], axis)
            parts = []
            for j in range(r):
                sl = [slice(None)] * h.ndim
                sl[axis] = slice(j * chunk, (j + 1) * chunk)
                parts.append((h[tuple(sl)], l[tuple(sl)]))
            # pairwise within the level: a linear accumulator chain
            # would lengthen the dependent-add critical path (r-1 per
            # level) and invert the latency experiment this lever runs
            while len(parts) > 1:
                nxt = [df.add(parts[j], parts[j + 1])
                       for j in range(0, len(parts) - 1, 2)]
                if len(parts) % 2:
                    nxt.append(parts[-1])
                parts = nxt
            h, l = parts[0]
        return h, l

    for axis in range(hi.ndim):
        hi, lo = fold_axis(hi, lo, axis)
    at0 = (0,) * hi.ndim
    return hi[at0], lo[at0]


def _dot_df(xh, xl, yh, yl):
    """In-kernel df64 inner product of two plane pairs (scalar pair out):
    two-prod leaves with the cross terms (``ops.df64._dot_local``
    semantics), renormalized, then the half-folding add tree."""
    p, e = _two_prod(xh, yh)
    e = e + (xh * yl + xl * yh)
    hi, lo = _two_sum(p, e)
    return _fold_grid_df(hi, lo)


def _shift_stencil_df(uh, ul, scale_h, scale_l):
    """5-point Dirichlet Laplacian on a df64 plane pair: ``4*u`` is exact
    in f32, the four neighbor subtractions are full df64 adds, the scale
    is one df64 mul (``ops.df64.stencil2d_matvec`` semantics with the
    pad replaced by zero-filled shifts)."""
    acc = (4.0 * uh, 4.0 * ul)
    for axis in (0, 1):
        for s in _axis_shifts_pair(uh, ul, axis):
            acc = df.sub(acc, s)
    return df.mul((scale_h, scale_l), acc)


def _axis_shifts_pair(uh, ul, axis):
    """``_axis_shifts`` applied to an (hi, lo) pair: the shift moves both
    words identically (exact), so the df64 value shifts exactly."""
    fh, bh_ = _axis_shifts(uh, axis)
    fl, bl_ = _axis_shifts(ul, axis)
    return (fh, fl), (bh_, bl_)


def _shift_stencil_df_3d(uh, ul, scale_h, scale_l):
    """7-point df64 Laplacian (``ops.df64.stencil3d_matvec`` semantics):
    ``6*u`` is NOT exact in f32 (6 = 2*3), so it is built as the exact
    ``4*u + 2*u`` through a full df64 add; the six neighbor
    subtractions and the scale follow the 2D form."""
    acc = df.add((4.0 * uh, 4.0 * ul), (2.0 * uh, 2.0 * ul))
    for axis in (0, 1, 2):
        for s in _axis_shifts_pair(uh, ul, axis):
            acc = df.sub(acc, s)
    return df.mul((scale_h, scale_l), acc)


def _safe_div_df(num, den):
    """df64 num/den with the exact-solve freeze of ``solver.df64._safe_div``:
    0/0 (both hi words exactly zero) yields 0, a genuine breakdown
    (den = 0, num != 0) still produces inf/NaN for the health check."""
    zero = jnp.logical_and(num[0] == 0.0, den[0] == 0.0)
    den_safe = (jnp.where(zero, jnp.ones_like(den[0]), den[0]),
                jnp.where(zero, jnp.zeros_like(den[1]), den[1]))
    q = df.div(num, den_safe)
    return (jnp.where(zero, jnp.zeros_like(q[0]), q[0]),
            jnp.where(zero, jnp.zeros_like(q[1]), q[1]))


def _resident_kernel_df64(nblocks, check_every, degree, stencil_df_fn,
                          has_x0, params_ref, cap_ref, *refs):
    if has_x0:
        (bh_ref, bl_ref, x0h_ref, x0l_ref,
         xh_ref, xl_ref, iters_ref, rr_ref, indef_ref,
         conv_ref, health_ref, hist_ref, rh_ref, rl_ref,
         ph_ref, pl_ref, state_f, state_i) = refs
    else:
        (bh_ref, bl_ref,
         xh_ref, xl_ref, iters_ref, rr_ref, indef_ref,
         conv_ref, health_ref, hist_ref, rh_ref, rl_ref,
         ph_ref, pl_ref, state_f, state_i) = refs
    scale = (params_ref[0], params_ref[1])
    tol = params_ref[2]
    rtol = params_ref[3]
    cap = cap_ref[0]

    def precond_df(r):
        """degree-term Chebyshev approximation of A^-1 in df64 - the
        in-kernel form of ``solver.df64._chebyshev_apply`` (same
        semi-iteration, every scalar and plane op in double-float)."""
        theta = (params_ref[4], params_ref[5])
        delta = (params_ref[6], params_ref[7])
        one = (jnp.float32(1.0), jnp.float32(0.0))
        two = (jnp.float32(2.0), jnp.float32(0.0))
        sigma = df.div(theta, delta)
        rho_c = df.div(one, sigma)
        d = df.div(r, theta)
        z = d
        for _ in range(degree - 1):
            rho_n = df.div(one, df.sub(df.mul(two, sigma), rho_c))
            ax = stencil_df_fn(z[0], z[1], scale[0], scale[1])
            resid = df.sub(r, ax)
            d = df.add(df.mul(df.mul(rho_n, rho_c), d),
                       df.mul(df.div(df.mul(two, rho_n), delta), resid))
            z = df.add(z, d)
            rho_c = rho_n
        return z

    bh, bl = bh_ref[:], bl_ref[:]
    if has_x0:
        # general init r0 = b - A x0 in full df64 (solver.df64's
        # nonzero-x0 extension of the reference's copy-only fast path)
        x0 = (x0h_ref[:], x0l_ref[:])
        xh_ref[:], xl_ref[:] = x0
        r0 = df.sub((bh, bl), stencil_df_fn(x0[0], x0[1],
                                            scale[0], scale[1]))
    else:
        xh_ref[:] = jnp.zeros_like(bh)      # explicit x0 = 0 (quirk Q6)
        xl_ref[:] = jnp.zeros_like(bh)
        r0 = (bh, bl)                       # r0 = b  (CUDACG.cu:248)
    rh_ref[:], rl_ref[:] = r0
    rr0 = _dot_df(r0[0], r0[1], r0[0], r0[1])
    if degree > 0:
        z0 = precond_df(r0)
        ph_ref[:], pl_ref[:] = z0           # p0 = z0 (preconditioned)
        rho0 = _dot_df(r0[0], r0[1], z0[0], z0[1])
    else:
        ph_ref[:], pl_ref[:] = r0           # p0 = r0 (CUDACG.cu:255)
        rho0 = rr0

    # threshold^2 = max(tol^2, rtol^2 * ||r0||^2), df64
    # (solver.df64._threshold semantics; tol/rtol squares via two-prod)
    tol2 = _two_prod(tol, tol)
    rtol2 = _two_prod(rtol, rtol)
    rt = df.mul(rtol2, rr0)
    thr = (jnp.maximum(tol2[0], rt[0]),
           jnp.where(tol2[0] >= rt[0], tol2[1], rt[1]))

    state_f[0], state_f[1] = rr0            # ||r||^2 df64 across blocks
    state_f[2], state_f[3] = rho0           # r . z df64 (== rr plain)
    state_i[0] = jnp.int32(0)               # iterations completed
    state_i[1] = jnp.int32(0)               # indefiniteness observed

    # Block-granular ||r||^2 trace, hi word only (DF64CGResult.
    # residual_history's documented diagnostic semantics) - same layout
    # and -1.0 never-ran sentinel as the f32 kernel (NaN would trip
    # jax_debug_nans on every default solve).
    hist_ref[0] = rr0[0]

    def sentinel_fill(j, c):
        hist_ref[j] = jnp.float32(-1.0)
        return c

    lax.fori_loop(1, nblocks + 1, sentinel_fill, jnp.int32(0))

    def block(blk, carry):
        rr_blk = (state_f[0], state_f[1])
        unconverged = jnp.logical_not(df.less(rr_blk, thr))
        nontrivial = rr_blk[0] > 0.0
        # rho <= 0 with r != 0 is a preconditioner breakdown (M not
        # SPD): stop, don't spin (solver.df64's cond semantics).
        healthy = (jnp.isfinite(rr_blk[0]) & jnp.isfinite(state_f[2])
                   & (state_f[2] > 0.0))

        @pl.when(unconverged & nontrivial & healthy & (state_i[0] < cap))
        def _():
            nsteps = jnp.minimum(jnp.int32(check_every), cap - state_i[0])

            def one_iter(_, carry):
                rr, rho = carry
                p = (ph_ref[:], pl_ref[:])
                ap = stencil_df_fn(p[0], p[1], scale[0], scale[1])
                pap = _dot_df(p[0], p[1], ap[0], ap[1])
                state_i[1] = jnp.where(
                    (pap[0] <= 0.0) & (rr[0] > 0.0),
                    jnp.int32(1), state_i[1])
                alpha = _safe_div_df(rho, pap)
                x_new = df.axpy(alpha, p, (xh_ref[:], xl_ref[:]))
                xh_ref[:], xl_ref[:] = x_new
                r_new = df.axpy(df.neg(alpha), ap, (rh_ref[:], rl_ref[:]))
                rh_ref[:], rl_ref[:] = r_new
                rr_new = _dot_df(r_new[0], r_new[1], r_new[0], r_new[1])
                if degree > 0:
                    z_new = precond_df(r_new)
                    rho_new = _dot_df(r_new[0], r_new[1],
                                      z_new[0], z_new[1])
                else:
                    z_new, rho_new = r_new, rr_new
                beta = _safe_div_df(rho_new, rho)
                p_new = df.axpy(beta, p, z_new)
                ph_ref[:], pl_ref[:] = p_new
                # No keep-mask: _safe_div_df already freezes the exact
                # 0/0 (alpha = 0 fixes every vector in place, so rr_new
                # recomputes bitwise-identically), and a genuine
                # breakdown (pap = 0, rho != 0) must flow inf/nan into
                # the CARRIED scalars so the next block's health
                # predicate stops the solve - a pap-only mask kept them
                # finite and delayed BREAKDOWN by a full extra block
                # (the f32 kernel and solver.df64 stop one block after
                # the breakdown iteration).
                return rr_new, rho_new

            rr_out, rho_out = lax.fori_loop(
                0, nsteps, one_iter,
                ((state_f[0], state_f[1]), (state_f[2], state_f[3])))
            state_f[0], state_f[1] = rr_out
            state_f[2], state_f[3] = rho_out
            state_i[0] = state_i[0] + nsteps
            hist_ref[blk + 1] = rr_out[0]
        return carry

    lax.fori_loop(0, nblocks, block, jnp.int32(0))

    iters_ref[0] = state_i[0]
    rr_ref[0] = state_f[0]
    rr_ref[1] = state_f[1]
    indef_ref[0] = state_i[1]
    # converged, decided on the kernel's own df64 threshold (the wrapper
    # cannot recompute thr without a second full dot for rr0)
    conv = jnp.logical_or(df.less((state_f[0], state_f[1]), thr),
                          state_f[0] == 0.0)
    conv_ref[0] = conv.astype(jnp.int32)
    # final health (solver.df64 semantics): non-finite scalars or a
    # rho <= 0 preconditioner breakdown with r != 0 -> BREAKDOWN.
    health_ref[0] = (jnp.isfinite(state_f[0]) & jnp.isfinite(state_f[2])
                     & ((state_f[2] > 0.0) | (state_f[0] == 0.0))
                     ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "degree", "interpret"))
def _cg_resident_df64_call(scale_h, scale_l, tol, rtol, theta, delta, cap,
                           bh, bl, x0h, x0l, *, shape, maxiter,
                           check_every, degree, interpret):
    nblocks = -(-maxiter // check_every)
    params = jnp.stack([
        jnp.asarray(scale_h, jnp.float32),
        jnp.asarray(scale_l, jnp.float32),
        jnp.asarray(tol, jnp.float32),
        jnp.asarray(rtol, jnp.float32),
        jnp.asarray(theta[0], jnp.float32),
        jnp.asarray(theta[1], jnp.float32),
        jnp.asarray(delta[0], jnp.float32),
        jnp.asarray(delta[1], jnp.float32)])
    cap_arr = jnp.asarray(cap, jnp.int32).reshape(1)
    stencil_df_fn = (_shift_stencil_df if len(shape) == 2
                     else _shift_stencil_df_3d)
    has_x0 = x0h is not None
    kernel = functools.partial(_resident_kernel_df64, nblocks, check_every,
                               degree, stencil_df_fn, has_x0)
    cells = math.prod(shape)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    grid_inputs = (bh, bl) if not has_x0 else (bh, bl, x0h, x0l)
    xh, xl, iters, rr, indef, conv, health, hist = pl.pallas_call(
        kernel,
        in_specs=[smem, smem] + [vmem] * len(grid_inputs),
        out_specs=[vmem, vmem, smem, smem, smem, smem, smem, smem],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),      # x hi
            jax.ShapeDtypeStruct(shape, jnp.float32),      # x lo
            jax.ShapeDtypeStruct((1,), jnp.int32),         # iterations
            jax.ShapeDtypeStruct((2,), jnp.float32),       # ||r||^2 df64
            jax.ShapeDtypeStruct((1,), jnp.int32),         # indefinite
            jax.ShapeDtypeStruct((1,), jnp.int32),         # converged
            jax.ShapeDtypeStruct((1,), jnp.int32),         # healthy
            jax.ShapeDtypeStruct((nblocks + 1,), jnp.float32),  # rr trace
        ],
        scratch_shapes=[
            pltpu.VMEM(shape, jnp.float32),                # r hi
            pltpu.VMEM(shape, jnp.float32),                # r lo
            pltpu.VMEM(shape, jnp.float32),                # p hi
            pltpu.VMEM(shape, jnp.float32),                # p lo
            pltpu.SMEM((4,), jnp.float32),                 # rr, rho (df64)
            pltpu.SMEM((2,), jnp.int32),                   # k, indefinite
        ],
        # The warm-start pair (input indices 4/5) aliases the x output
        # pair, mirroring the f32 kernel's trick: the kernel reads x0
        # exactly once at init and immediately seeds x from it, so a
        # df64 warm start stays plane-neutral in the VMEM budget.
        input_output_aliases=({4: 0, 5: 1} if has_x0 else {}),
        compiler_params=pltpu.CompilerParams(
            # same fixed margin as the f32 kernel, same physical clamp
            vmem_limit_bytes=min(
                (_PLANES_BOUND_DF64
                 + _extra_planes_df64(degree > 0))
                * cells * 4 + (8 << 20),
                vmem_bytes())),
        interpret=interpret,
    )(params, cap_arr, *grid_inputs)
    return (xh, xl, iters[0], (rr[0], rr[1]), indef[0], conv[0],
            health[0], hist)


def _coerce_x0_pair(x0, b_grid):
    """Validate an optional df64 warm-start ``(hi, lo)`` pair against the
    rhs grid (the df64 form of :func:`_coerce_x0`): flat or exact grid
    shape, f32 words, both words the same shape."""
    if x0 is None:
        return None, None
    if not (isinstance(x0, tuple) and len(x0) == 2):
        raise ValueError(
            "df64 x0 must be an (hi, lo) pair of f32 arrays "
            "(ops.df64.split_f64 produces one from host float64)")
    x0h = jnp.asarray(x0[0], jnp.float32)
    x0l = jnp.asarray(x0[1], jnp.float32)
    if x0h.shape != x0l.shape:
        raise ValueError(
            f"x0 words must share a shape, got {x0h.shape} / {x0l.shape}")
    n = math.prod(b_grid.shape)
    if x0h.ndim == 1 and x0h.shape[0] == n:
        x0h, x0l = x0h.reshape(b_grid.shape), x0l.reshape(b_grid.shape)
    elif x0h.shape != b_grid.shape:
        raise ValueError(
            f"x0 shape {x0h.shape} matches neither the grid "
            f"{b_grid.shape} nor its flat length")
    return x0h, x0l


def cg_resident_df64_2d(scale, b_pair, *, x0=None, tol=0.0, rtol=0.0,
                        maxiter=2000, check_every=32, iter_cap=None,
                        interpret=False, precond_degree=0,
                        theta=(1.0, 0.0), delta=(1.0, 0.0)):
    """df64 CG for the 5-point stencil, entirely inside one pallas kernel.

    Args:
      scale: df64 stencil scale - an ``(hi, lo)`` pair of f32 scalars.
      b_pair: right-hand side as an ``(hi, lo)`` pair of (nx, ny) f32
        grids (``ops.df64.split_f64`` produces one from host float64).
      x0: optional df64 warm-start guess as an ``(hi, lo)`` pair (flat
        or grid shape); ``None`` = the reference's x0 = 0 fast path,
        otherwise the general ``r0 = b - A x0`` init in full df64 (one
        extra in-kernel stencil apply; the pair aliases the x output
        pair, so a warm start costs no extra VMEM planes).
      tol / rtol / maxiter / check_every / iter_cap / interpret: as
        :func:`cg_resident_2d`; the convergence threshold is evaluated
        in df64 (``solver.df64`` semantics).

    ``precond_degree`` >= 1 applies the k-term Chebyshev polynomial
    IN-KERNEL in df64 arithmetic on the spectral interval described by
    the df64 ``theta``/``delta`` pairs (``solver.df64._chebyshev_apply``
    semantics; get them from ``solver.df64.chebyshev_interval``).

    Returns:
      ``(x_hi, x_lo, iterations, (rr_hi, rr_lo), indefinite, converged,
      healthy, hist)`` - ``converged`` is decided inside the kernel on
      its df64 threshold (``max(tol^2, rtol^2 ||r0||^2)``,
      ``solver.df64._threshold``); ``healthy`` 0 means BREAKDOWN
      (non-finite scalars or ``rho <= 0`` with ``r != 0``); ``hist`` is
      the block-granular ``||r||^2`` trace, hi word only (slot 0 =
      ``||r0||^2``, slot j+1 after check block j, -1.0 sentinel for
      never-run blocks - the f32 kernel's layout).
    """
    bh = jnp.asarray(b_pair[0], jnp.float32)
    bl = jnp.asarray(b_pair[1], jnp.float32)
    if bh.ndim != 2 or bh.shape != bl.shape:
        raise ValueError(
            f"b_pair must be two equal (nx, ny) grids, got "
            f"{bh.shape} / {bl.shape}")
    check_every = _check_loop_args(check_every, maxiter, precond_degree)
    x0h, x0l = _coerce_x0_pair(x0, bh)
    _check_grid_fits(bh.shape, df64=True,
                     preconditioned=precond_degree > 0,
                     interpret=interpret)
    cap = maxiter if iter_cap is None else iter_cap
    return _cg_resident_df64_call(
        scale[0], scale[1], tol, rtol, theta, delta, cap, bh, bl,
        x0h, x0l, shape=bh.shape, maxiter=maxiter,
        check_every=check_every, degree=int(precond_degree),
        interpret=interpret)


def supports_resident_df64_3d(nx: int, ny: int, nz: int, device=None,
                              preconditioned: bool = False) -> bool:
    """3D form of :func:`supports_resident_df64_2d`: trailing-axes
    tiling plus the df64 plane-count bound."""
    if ny % 8 != 0 or nz % 128 != 0 or nx < 1:
        return False
    planes = _PLANES_BOUND_DF64 + _extra_planes_df64(preconditioned)
    return planes * nx * ny * nz * 4 <= vmem_bytes(device)


def cg_resident_df64_3d(scale, b_pair, *, x0=None, tol=0.0, rtol=0.0,
                        maxiter=2000, check_every=32, iter_cap=None,
                        interpret=False, precond_degree=0,
                        theta=(1.0, 0.0), delta=(1.0, 0.0)):
    """The 7-point-stencil form of :func:`cg_resident_df64_2d`: same
    kernel and return contract with the df64 3D Laplacian
    (``ops.df64.stencil3d_matvec`` semantics - ``6*u`` built as the
    exact ``4*u + 2*u``)."""
    bh = jnp.asarray(b_pair[0], jnp.float32)
    bl = jnp.asarray(b_pair[1], jnp.float32)
    if bh.ndim != 3 or bh.shape != bl.shape:
        raise ValueError(
            f"b_pair must be two equal (nx, ny, nz) grids, got "
            f"{bh.shape} / {bl.shape}")
    check_every = _check_loop_args(check_every, maxiter, precond_degree)
    x0h, x0l = _coerce_x0_pair(x0, bh)
    _check_grid_fits(bh.shape, df64=True,
                     preconditioned=precond_degree > 0,
                     interpret=interpret)
    cap = maxiter if iter_cap is None else iter_cap
    return _cg_resident_df64_call(
        scale[0], scale[1], tol, rtol, theta, delta, cap, bh, bl,
        x0h, x0l, shape=bh.shape, maxiter=maxiter,
        check_every=check_every, degree=int(precond_degree),
        interpret=interpret)
