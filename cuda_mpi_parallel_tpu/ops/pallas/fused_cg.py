"""Fused-iteration HBM-streaming CG kernels (the 256^3 north-star path).

The VMEM-resident engine (``resident.py``) ends at the VMEM boundary
(~128^3 f32).  Beyond it - BASELINE config #4's 256^3 grid, 67 MB per
vector - the general ``lax.while_loop`` solver runs each CG iteration as
several XLA fusions whose intermediates cross HBM at every fusion
boundary: measured 1.344 ms/iter at 256^3 on v5e, consistent with ~16
full plane-passes of HBM traffic per iteration against the reference's
hot loop (``CUDACG.cu:269-352``).

These kernels carry the resident engine's idea - fuse the whole
iteration, keep intermediates on-chip - past the VMEM boundary by
streaming double-buffered slabs (``stencil.py``'s DMA pattern) through
TWO pallas launches per iteration, the minimum the CG data flow allows
(each of the two inner products is a global barrier: alpha needs ALL of
p.Ap before any x/r update, beta needs ALL of ||r||^2 before any p
update):

* **pass A** (``p`` update + matvec + first dot): reads r and p with
  halo slabs, forms ``p_new = r + beta * p`` in VMEM (the p-update of
  the PREVIOUS iteration, deferred so it fuses with this iteration's
  matvec), writes ``p_new``, applies the stencil in-register, and
  accumulates ``p_new . A p_new`` into SMEM across the sequential grid.
  ``Ap`` is NOT written to HBM - pass B recomputes it, trading ~1 slab
  of VPU stencil work for a full plane-pass of traffic each way.
* **pass B** (vector updates + second dot): reads ``p_new`` with halo,
  recomputes ``Ap``, updates ``x += alpha p_new`` and
  ``r -= alpha Ap`` in place (blocked, pipelined, input/output
  aliased), accumulating ``||r_new||^2``.

Per-iteration HBM traffic: pass A reads r, p and writes p_new (3
plane-passes + halo), pass B reads p_new, x, r and writes x, r (5) -
**8 plane-passes** vs the general solver's ~16, i.e. ~0.55 GB/iter at
256^3 against v5e's 819 GB/s => ~0.67 ms/iter floor.  The scalar
recurrence (alpha, beta, convergence) stays in the surrounding jitted
``lax.while_loop`` (``solver/streaming.py``) - scalars never leave the
device, launches stay at 2/iter inside one executable.

Trajectory: mathematically identical to ``solver.cg`` (same recurrence,
x0 = 0 fast path, ``_safe_div`` semantics); inner products accumulate
slab-by-slab in grid order, so values agree with the general solver's
full-array dots to f32 reduction-order rounding.

Interpret mode runs the same kernels on CPU for tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import (
    _HALO,
    _shift_left,
    _shift_right,
    _slab_copy,
    _slab_copy3d,
    _slab_wait,
    _slab_wait3d,
)

# VMEM budget for one fused-CG launch: pass B's pipelined blocked
# arrays (x, r in + out, double-buffered = 8 slab-heights) dominate;
# pass A holds 4 halo slabs.  Sized against the 128 MiB parts with
# room for Mosaic temporaries.
_VMEM_BUDGET = 64 * 1024 * 1024


def _stencil_slab_2d(u, scale, bm):
    """5-point Laplacian on a (bm + 2*_HALO, ny) halo slab -> (bm, ny)
    interior (the compute body of ``stencil._stencil2d_kernel``)."""
    w = u[_HALO - 1:_HALO + bm + 1]
    mid = w[1:-1]
    up = w[:-2]
    down = w[2:]
    left = _shift_right(mid)
    right = _shift_left(mid)
    return scale * (4.0 * mid - up - down - left - right)


def _stencil_slab_3d(u, scale):
    """7-point Laplacian on a (bm+2, ny, nz) halo slab -> (bm, ny, nz)
    interior (the compute body of ``stencil._stencil3d_kernel``)."""
    mid = u[1:-1]
    xm = u[:-2]
    xp = u[2:]
    ym = jnp.concatenate(
        [jnp.zeros_like(mid[:, :1]), mid[:, :-1]], axis=1)
    yp = jnp.concatenate(
        [mid[:, 1:], jnp.zeros_like(mid[:, :1])], axis=1)
    zm = _shift_right(mid)
    zp = _shift_left(mid)
    return scale * (6.0 * mid - xm - xp - ym - yp - zm - zp)


def _interior(slab, bm, ndim):
    """The bm-row/plane interior of a halo slab (2D slabs carry _HALO
    rows each side, 3D slabs one plane each side)."""
    if ndim == 2:
        return slab[_HALO:_HALO + bm]
    return slab[1:-1]


def _halo_pm1(slab, bm, ndim):
    """Interior plus exactly one halo row/plane each side: the region a
    one-step stencil of the interior needs."""
    if ndim == 2:
        return slab[_HALO - 1:_HALO + bm + 1]
    return slab


def _fill_edge_halo(slab, lo_ref, hi_ref, block, bm, nx, ndim):
    """Overwrite the one consumed boundary row/plane of an edge block's
    slab with neighbor halo data (distributed row-partition: the global
    Dirichlet zero-fill becomes the neighbor's boundary).  ``_slab_copy*``
    zero-filled the edge region; only the +-1 row/plane the stencil
    actually reads is replaced."""
    nblocks = nx // bm
    lo_at = _HALO - 1 if ndim == 2 else 0
    hi_at = _HALO + bm if ndim == 2 else bm + 1

    def fill_lo():
        slab[lo_at:lo_at + 1] = lo_ref[:]

    def fill_hi():
        slab[hi_at:hi_at + 1] = hi_ref[:]

    if nblocks == 1:
        fill_lo()
        fill_hi()
        return
    pl.when(block == 0)(fill_lo)
    pl.when(block == nblocks - 1)(fill_hi)


# -- pass A: p_new = r + beta * p; pap = p_new . A p_new ----------------------


def _pass_a_kernel(params_ref, *refs, bm, nx, ndim, has_halo):
    if has_halo:
        (r_lo, r_hi, p_lo, p_hi, r_hbm, p_hbm, pnew_ref, pap_ref,
         rslabs, pslabs, sems, acc) = refs
    else:
        (r_hbm, p_hbm, pnew_ref, pap_ref,
         rslabs, pslabs, sems, acc) = refs
    i = pl.program_id(0)
    n = pl.num_programs(0)
    copy, wait = (_slab_copy, _slab_wait) if ndim == 2 else (
        _slab_copy3d, _slab_wait3d)

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.float32(0.0)
        copy(r_hbm, rslabs.at[0], sems.at[0], 0, bm, nx)
        copy(p_hbm, pslabs.at[0], sems.at[2], 0, bm, nx)

    @pl.when(i + 1 < n)
    def _():
        copy(r_hbm, rslabs.at[(i + 1) % 2], sems.at[(i + 1) % 2],
             i + 1, bm, nx)
        copy(p_hbm, pslabs.at[(i + 1) % 2], sems.at[2 + (i + 1) % 2],
             i + 1, bm, nx)

    wait(r_hbm, rslabs.at[i % 2], sems.at[i % 2], i, bm, nx)
    wait(p_hbm, pslabs.at[i % 2], sems.at[2 + i % 2], i, bm, nx)
    if has_halo:
        _fill_edge_halo(rslabs.at[i % 2], r_lo, r_hi, i, bm, nx, ndim)
        _fill_edge_halo(pslabs.at[i % 2], p_lo, p_hi, i, bm, nx, ndim)

    scale = params_ref[0]
    beta = params_ref[1]
    theta = params_ref[2]
    # The deferred p-update: p_new on the FULL halo slab (elementwise, so
    # the halo rows come straight from r/p's halos - no cross-slab
    # dependency on p_new values this pass writes).  The v-input is
    # divided by theta IN-SLAB: 1.0 for the unpreconditioned path (x/1.0
    # is exact, so the trajectory is untouched) or the Chebyshev interval
    # center for the degree-1 polynomial (z = r/theta fused into the
    # p-update - the whole degree-1 preconditioner costs zero passes).
    pnew_slab = rslabs[i % 2] / theta + beta * pslabs[i % 2]
    if ndim == 2:
        ap = _stencil_slab_2d(pnew_slab, scale, bm)
    else:
        ap = _stencil_slab_3d(pnew_slab, scale)
    pnew_int = _interior(pnew_slab, bm, ndim)
    pnew_ref[:] = pnew_int
    acc[0] += jnp.sum(pnew_int * ap)

    @pl.when(i == n - 1)
    def _():
        pap_ref[0] = acc[0]


# -- pass B: x += alpha p; r -= alpha Ap; rr = r.r ----------------------------


def _pass_b_kernel(alpha_ref, *refs, bm, nx, ndim, has_halo, with_rz):
    if has_halo:
        (pn_lo, pn_hi, pnew_hbm, x_ref, r_ref,
         xout_ref, rout_ref, rr_ref, *rest) = refs
    else:
        (pnew_hbm, x_ref, r_ref,
         xout_ref, rout_ref, rr_ref, *rest) = refs
    if with_rz:
        rz_ref, pslabs, sems, acc = rest
    else:
        pslabs, sems, acc = rest
    i = pl.program_id(0)
    n = pl.num_programs(0)
    copy, wait = (_slab_copy, _slab_wait) if ndim == 2 else (
        _slab_copy3d, _slab_wait3d)

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.float32(0.0)
        if with_rz:
            acc[1] = jnp.float32(0.0)
        copy(pnew_hbm, pslabs.at[0], sems.at[0], 0, bm, nx)

    @pl.when(i + 1 < n)
    def _():
        copy(pnew_hbm, pslabs.at[(i + 1) % 2], sems.at[(i + 1) % 2],
             i + 1, bm, nx)

    wait(pnew_hbm, pslabs.at[i % 2], sems.at[i % 2], i, bm, nx)
    if has_halo:
        _fill_edge_halo(pslabs.at[i % 2], pn_lo, pn_hi, i, bm, nx, ndim)

    scale = alpha_ref[0]
    alpha = alpha_ref[1]
    slab = pslabs[i % 2]
    if ndim == 2:
        ap = _stencil_slab_2d(slab, scale, bm)
    else:
        ap = _stencil_slab_3d(slab, scale)
    pnew_int = _interior(slab, bm, ndim)
    xout_ref[:] = x_ref[:] + alpha * pnew_int       # CUDACG.cu:314
    r_new = r_ref[:] - alpha * ap                   # CUDACG.cu:320-321
    rout_ref[:] = r_new
    acc[0] += jnp.sum(r_new * r_new)                # CUDACG.cu:328
    if with_rz:
        # degree-1 Chebyshev rho = r . (r/theta), elementwise like the
        # general solver's dot(r, m @ r) - NOT rr/theta, whose single
        # scalar division rounds differently
        theta = alpha_ref[2]
        acc[1] += jnp.sum(r_new * (r_new / theta))

    @pl.when(i == n - 1)
    def _():
        rr_ref[0] = acc[0]
        if with_rz:
            rz_ref[0] = acc[1]


def _slab_shape(bm, grid_shape):
    if len(grid_shape) == 2:
        return (bm + 2 * _HALO, grid_shape[1])
    return (bm + 2, grid_shape[1], grid_shape[2])


@functools.partial(jax.jit, static_argnames=("bm", "interpret"))
def fused_cg_pass_a(scale, beta, r, p, halos=None, *, bm: int,
                    interpret: bool = False, theta=None):
    """One streamed pass: ``p_new = r/theta + beta * p``;
    ``pap = p_new . A p_new``.

    ``r``/``p``: full grids ((nx, ny) or (nx, ny, nz)) in HBM; returns
    ``(p_new, pap)``.  ``beta``/``scale``/``theta`` ride in SMEM so
    sweeps reuse the executable.

    ``theta``: optional traced divisor for the r-term (default 1.0 -
    exact, leaves the unpreconditioned trajectory bit-identical).  The
    degree-1 Chebyshev preconditioner is ``z = r/theta``; folding the
    division here makes that polynomial cost zero extra passes.  For
    degree >= 2 the caller passes the cheb output ``z`` as ``r`` and
    leaves ``theta`` at 1.

    ``halos``: optional ``(r_lo, r_hi, p_lo, p_hi)`` neighbor boundary
    rows/planes (each ``(1,) + shape[1:]``) for the distributed
    row-partition - they replace the global Dirichlet zero edge, and the
    returned ``pap`` is then the LOCAL partial sum the caller psums.
    """
    shape = r.shape
    ndim = r.ndim
    nx = shape[0]
    has_halo = halos is not None
    params = jnp.stack([jnp.asarray(scale, jnp.float32),
                        jnp.asarray(beta, jnp.float32),
                        jnp.asarray(1.0 if theta is None else theta,
                                    jnp.float32)])
    kernel = functools.partial(_pass_a_kernel, bm=bm, nx=nx, ndim=ndim,
                               has_halo=has_halo)
    block = (bm,) + shape[1:]
    index_map = (lambda i: (i, 0)) if ndim == 2 else (lambda i: (i, 0, 0))
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    halo_inputs = tuple(halos) if has_halo else ()
    pnew, pap = pl.pallas_call(
        kernel,
        grid=(nx // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [vmem] * len(halo_inputs)                 # halo rows (tiny)
        + [
            pl.BlockSpec(memory_space=pl.ANY),      # r (manual halo DMA)
            pl.BlockSpec(memory_space=pl.ANY),      # p (manual halo DMA)
        ],
        out_specs=[
            pl.BlockSpec(block, index_map),         # p_new (pipelined)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # pap
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2,) + _slab_shape(bm, shape), jnp.float32),  # r
            pltpu.VMEM((2,) + _slab_shape(bm, shape), jnp.float32),  # p
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SMEM((1,), jnp.float32),          # pap accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
        interpret=interpret,
    )(params, *halo_inputs, r, p)
    return pnew, pap[0]


@functools.partial(jax.jit, static_argnames=("bm", "interpret", "with_rz"))
def fused_cg_pass_b(scale, alpha, pnew, x, r, halos=None, *, bm: int,
                    interpret: bool = False, theta=None,
                    with_rz: bool = False):
    """One streamed pass: ``x += alpha p``, ``r -= alpha A p``,
    ``rr = r . r`` - with ``A p`` recomputed from ``p_new``'s halo slabs
    rather than read back from HBM.  Returns ``(x_new, r_new, rr)``;
    the x/r inputs are donated to their outputs (in-place update).

    ``with_rz=True`` additionally accumulates
    ``rz = r_new . (r_new / theta)`` - the degree-1 Chebyshev
    ``rho = r . M^-1 r`` fused into the pass for free (the r_new values
    are already in registers) - and returns ``(x_new, r_new, rr, rz)``.

    ``halos``: optional ``(pn_lo, pn_hi)`` neighbor boundary rows/planes
    of ``p_new`` for the distributed row-partition; ``rr`` (and ``rz``)
    are then the local partials the caller psums.
    """
    shape = x.shape
    ndim = x.ndim
    nx = shape[0]
    has_halo = halos is not None
    params = jnp.stack([jnp.asarray(scale, jnp.float32),
                        jnp.asarray(alpha, jnp.float32),
                        jnp.asarray(1.0 if theta is None else theta,
                                    jnp.float32)])
    kernel = functools.partial(_pass_b_kernel, bm=bm, nx=nx, ndim=ndim,
                               has_halo=has_halo, with_rz=with_rz)
    block = (bm,) + shape[1:]
    index_map = (lambda i: (i, 0)) if ndim == 2 else (lambda i: (i, 0, 0))
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    halo_inputs = tuple(halos) if has_halo else ()
    nh = len(halo_inputs)
    rz_outs = ([pl.BlockSpec(memory_space=pltpu.SMEM)] if with_rz else [])
    rz_shapes = ([jax.ShapeDtypeStruct((1,), jnp.float32)] if with_rz
                 else [])
    out = pl.pallas_call(
        kernel,
        grid=(nx // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [vmem] * nh                               # p_new halo rows
        + [
            pl.BlockSpec(memory_space=pl.ANY),      # p_new (manual halo DMA)
            pl.BlockSpec(block, index_map),         # x (pipelined)
            pl.BlockSpec(block, index_map),         # r (pipelined)
        ],
        out_specs=[
            pl.BlockSpec(block, index_map),         # x out
            pl.BlockSpec(block, index_map),         # r out
            pl.BlockSpec(memory_space=pltpu.SMEM),  # rr
        ] + rz_outs,
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.float32),
        ] + rz_shapes,
        scratch_shapes=[
            pltpu.VMEM((2,) + _slab_shape(bm, shape), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SMEM((2,), jnp.float32),
        ],
        # x and r update in place: same-index blocked specs, elementwise
        # math - the pipelined fetch of block i+1 never overlaps the
        # writeback of block i's rows.
        input_output_aliases={2 + nh: 0, 3 + nh: 1},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
        interpret=interpret,
    )(params, *halo_inputs, pnew, x, r)
    if with_rz:
        x_new, r_new, rr, rz = out
        return x_new, r_new, rr[0], rz[0]
    x_new, r_new, rr = out
    return x_new, r_new, rr[0]


# -- fused Chebyshev step (streamed polynomial preconditioning) ---------------
#
# One step of the three-term Chebyshev semi-iteration
# (models.precond.ChebyshevPreconditioner.matvec, Saad Alg. 12.1) as a
# single slab-streamed launch:
#
#     d_new = c1 * d + c2 * (r - A z)        (c1 = rho_new*rho,
#     z_new = z + d_new                       c2 = 2*rho_new/delta)
#
# The matvec's operand z streams through manual halo-slab DMA (the
# pass-A pattern); r and d are elementwise and ride the pipelined
# blocked specs.  ``first=True`` fuses the polynomial's init
# (d0 = z0 = r/theta) into the step: the ONLY halo-DMA'd input is then
# r itself, and z0 is formed in-slab - 3 plane-passes instead of 5.
# ``last=True`` accumulates ``rho = r . z_new`` into SMEM across the
# grid, so the PCG reduction costs no extra pass.  A degree-k
# application is (k-1) launches: first -> middle* -> last (a degree-2
# application is one first+last launch); degree 1 never reaches these
# kernels (z = r/theta folds into pass A/B via their theta params).


def _cheb_step_kernel(params_ref, *refs, bm, nx, ndim, first, last):
    if first:
        (v_hbm, zout_ref, dout_ref, *rest) = refs
    else:
        (v_hbm, r_ref, d_ref, zout_ref, dout_ref, *rest) = refs
    if last:
        rz_ref, slabs, sems, acc = rest
    else:
        slabs, sems, acc = rest
    i = pl.program_id(0)
    n = pl.num_programs(0)
    copy, wait = (_slab_copy, _slab_wait) if ndim == 2 else (
        _slab_copy3d, _slab_wait3d)

    @pl.when(i == 0)
    def _():
        if last:
            acc[0] = jnp.float32(0.0)
        copy(v_hbm, slabs.at[0], sems.at[0], 0, bm, nx)

    @pl.when(i + 1 < n)
    def _():
        copy(v_hbm, slabs.at[(i + 1) % 2], sems.at[(i + 1) % 2],
             i + 1, bm, nx)

    wait(v_hbm, slabs.at[i % 2], sems.at[i % 2], i, bm, nx)

    scale = params_ref[0]
    theta = params_ref[1]
    c1 = params_ref[2]
    c2 = params_ref[3]
    if first:
        # v is r: z0 = r/theta formed on the FULL halo slab (elementwise,
        # so z0's halo rows are exactly the neighboring z0 values) and
        # d0 = z0 - the polynomial's init fused into its first step.
        r_slab = slabs[i % 2]
        z_slab = r_slab / theta
        r_int = _interior(r_slab, bm, ndim)
        d_int = _interior(z_slab, bm, ndim)
    else:
        z_slab = slabs[i % 2]
        r_int = r_ref[:]
        d_int = d_ref[:]
    if ndim == 2:
        az = _stencil_slab_2d(z_slab, scale, bm)
    else:
        az = _stencil_slab_3d(z_slab, scale)
    z_int = _interior(z_slab, bm, ndim)
    d_new = c1 * d_int + c2 * (r_int - az)
    z_new = z_int + d_new
    zout_ref[:] = z_new
    dout_ref[:] = d_new
    if last:
        acc[0] += jnp.sum(r_int * z_new)

        @pl.when(i == n - 1)
        def _():
            rz_ref[0] = acc[0]


@functools.partial(jax.jit, static_argnames=("bm", "first", "last",
                                             "interpret"))
def fused_cheb_step(scale, theta, c1, c2, v, r=None, d=None, *, bm: int,
                    first: bool, last: bool, interpret: bool = False):
    """One streamed Chebyshev semi-iteration step.

    ``v`` is the halo-DMA'd operand: the residual ``r`` itself when
    ``first`` (z0 = v/theta is formed in-slab and d0 = z0), else the
    current polynomial iterate ``z`` (with ``r``/``d`` as pipelined
    elementwise inputs).  Returns ``(z_new, d_new)``, plus
    ``rho = r . z_new`` when ``last`` (the PCG reduction fused into the
    final step).  All scalars are traced SMEM params - a degree-k sweep
    reuses (k-1) executables across iterations.
    """
    shape = v.shape
    ndim = v.ndim
    nx = shape[0]
    params = jnp.stack([jnp.asarray(scale, jnp.float32),
                        jnp.asarray(theta, jnp.float32),
                        jnp.asarray(c1, jnp.float32),
                        jnp.asarray(c2, jnp.float32)])
    kernel = functools.partial(_cheb_step_kernel, bm=bm, nx=nx, ndim=ndim,
                               first=first, last=last)
    block = (bm,) + shape[1:]
    index_map = (lambda i: (i, 0)) if ndim == 2 else (lambda i: (i, 0, 0))
    elt_inputs = () if first else (r, d)
    rz_outs = ([pl.BlockSpec(memory_space=pltpu.SMEM)] if last else [])
    rz_shapes = ([jax.ShapeDtypeStruct((1,), jnp.float32)] if last else [])
    out = pl.pallas_call(
        kernel,
        grid=(nx // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pl.ANY)]       # v (manual halo DMA)
        + [pl.BlockSpec(block, index_map)] * len(elt_inputs),
        out_specs=[
            pl.BlockSpec(block, index_map),         # z out
            pl.BlockSpec(block, index_map),         # d out
        ] + rz_outs,
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
        ] + rz_shapes,
        scratch_shapes=[
            pltpu.VMEM((2,) + _slab_shape(bm, shape), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SMEM((1,), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
        interpret=interpret,
    )(params, v, *elt_inputs)
    if last:
        z_new, d_new, rz = out
        return z_new, d_new, rz[0]
    z_new, d_new = out
    return z_new, d_new


def pick_block_streaming(shape, itemsize: int = 4,
                         budget_bytes: int | None = None) -> int:
    """Slab height for the fused-CG passes.

    The binding constraint is pass B: two manual p_new halo slabs plus
    four pipelined blocked buffers (x, r in + out, double-buffered = 8
    block-heights) plus stencil temporaries.  2D keeps the original
    conservative model (~14 block-heights within 24 MB - bench-validated
    at 1M and 16M rows).  3D uses the round-5 MEASURED model: Mosaic's
    actual scoped allocation at 256^3 is ~9.5 block-heights (bm=32
    needed 81 MB, bm=16 fits the 64 MiB kernel limit and RUNS), so ~10
    heights within ``_VMEM_BUDGET`` - the old 14-in-24MB model picked
    bm=4 at 256^3 where bm=16 is 9% faster (788 -> 716 us/iter, the
    difference between 1.59x and 1.79x over the general engine).  The
    largest power-of-two divisor wins (bigger slabs = fewer grid steps
    = less DMA bookkeeping), capped at 128 rows / 16 planes.
    """
    nx = shape[0]
    row_bytes = itemsize
    for d in shape[1:]:
        row_bytes *= d
    if len(shape) == 2:
        halo, heights, cap = 2 * _HALO, 14, 128
        budget = 24 * 1024 * 1024 if budget_bytes is None else budget_bytes
    else:
        halo, heights, cap = 2, 10, 16
        budget = _VMEM_BUDGET if budget_bytes is None else budget_bytes
    best = 0
    bm = 8 if len(shape) == 2 else 1
    while bm <= nx:
        if nx % bm == 0 and heights * (bm + halo) * row_bytes <= budget:
            best = bm
        bm *= 2
    if not best:
        raise ValueError(
            f"no feasible fused-CG block for grid {shape}: one "
            f"row/plane is {row_bytes} bytes")
    return min(best, cap) if nx % cap == 0 and best >= cap else best


def supports_streaming(shape, itemsize: int = 4) -> bool:
    """Shape gate of the fused-CG kernels: the plain stencil kernels'
    DMA tiling constraints, plus a feasible slab height.

    ``itemsize`` must match what the solve path passes to
    ``pick_block_streaming`` (8 for the df64 paths - hi/lo pairs double
    the slabs), or the gate can approve a shape the picker then rejects.
    """
    if len(shape) == 2:
        nx, ny = shape
        ok = nx % 8 == 0 and ny % 128 == 0
    elif len(shape) == 3:
        nx, ny, nz = shape
        ok = nx % 2 == 0 and ny % 8 == 0 and nz % 128 == 0
    else:
        return False
    if not ok:
        return False
    try:
        pick_block_streaming(shape, itemsize=itemsize)
    except ValueError:
        return False
    return True


# -- df64 (double-float) fused streaming passes --------------------------------
#
# The reference's defining precision (CUDA_R_64F, CUDACG.cu:216) at the
# north-star scale: the same two-pass fused iteration with every plane an
# (hi, lo) f32 pair and every product/accumulation in error-free-transform
# arithmetic (ops.df64 - branch-free elementwise jnp code that lowers
# through Mosaic unchanged, proven by the resident df64 kernel).  HBM
# traffic doubles (two words per value): 16 plane-passes per iteration
# vs the general df64 solver's ~32 at the same fusion boundaries.

from .. import df64 as _df  # noqa: E402  (section-local import, see above)
from .resident import _dot_df as _dot_df_grid  # noqa: E402


def _stencil_slab_df(u, scale, bm, ndim):
    """df64 Laplacian on an (hi, lo) halo-slab pair -> interior pair.

    2D: ``4*u`` is exact in f32; 3D: ``6*u`` built as the exact
    ``4*u + 2*u`` (``ops.df64.stencil*_matvec`` semantics).  Vertical
    neighbors come from the slab's halo rows/planes; lane/sublane
    shifts move both words identically (exact).
    """
    uh, ul = u
    if ndim == 2:
        wh = uh[_HALO - 1:_HALO + bm + 1]
        wl = ul[_HALO - 1:_HALO + bm + 1]
        acc = (4.0 * wh[1:-1], 4.0 * wl[1:-1])
        for nb in ((wh[:-2], wl[:-2]), (wh[2:], wl[2:]),
                   (_shift_right(wh[1:-1]), _shift_right(wl[1:-1])),
                   (_shift_left(wh[1:-1]), _shift_left(wl[1:-1]))):
            acc = _df.sub(acc, nb)
    else:
        mid_h, mid_l = uh[1:-1], ul[1:-1]
        acc = _df.add((4.0 * mid_h, 4.0 * mid_l),
                      (2.0 * mid_h, 2.0 * mid_l))
        ylo = (jnp.concatenate([jnp.zeros_like(mid_h[:, :1]),
                                mid_h[:, :-1]], axis=1),
               jnp.concatenate([jnp.zeros_like(mid_l[:, :1]),
                                mid_l[:, :-1]], axis=1))
        yhi = (jnp.concatenate([mid_h[:, 1:],
                                jnp.zeros_like(mid_h[:, :1])], axis=1),
               jnp.concatenate([mid_l[:, 1:],
                                jnp.zeros_like(mid_l[:, :1])], axis=1))
        for nb in ((uh[:-2], ul[:-2]), (uh[2:], ul[2:]), ylo, yhi,
                   (_shift_right(mid_h), _shift_right(mid_l)),
                   (_shift_left(mid_h), _shift_left(mid_l))):
            acc = _df.sub(acc, nb)
    return _df.mul(scale, acc)


def _interior_pair(slab, bm, ndim):
    return (_interior(slab[0], bm, ndim), _interior(slab[1], bm, ndim))


def _pass_a_kernel_df64(params_ref, *refs, bm, nx, ndim, has_halo):
    if has_halo:
        (rh_lo, rh_hi, rl_lo, rl_hi, ph_lo, ph_hi, pl_lo, pl_hi,
         rh_hbm, rl_hbm, ph_hbm, pl_hbm,
         pnh_ref, pnl_ref, pap_ref,
         rh_slabs, rl_slabs, ph_slabs, pl_slabs, sems, acc) = refs
    else:
        (rh_hbm, rl_hbm, ph_hbm, pl_hbm,
         pnh_ref, pnl_ref, pap_ref,
         rh_slabs, rl_slabs, ph_slabs, pl_slabs, sems, acc) = refs
    i = pl.program_id(0)
    n = pl.num_programs(0)
    copy, wait = (_slab_copy, _slab_wait) if ndim == 2 else (
        _slab_copy3d, _slab_wait3d)
    arrays = ((rh_hbm, rh_slabs, 0), (rl_hbm, rl_slabs, 1),
              (ph_hbm, ph_slabs, 2), (pl_hbm, pl_slabs, 3))

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.float32(0.0)
        acc[1] = jnp.float32(0.0)
        for hbm, slabs, si in arrays:
            copy(hbm, slabs.at[0], sems.at[2 * si], 0, bm, nx)

    @pl.when(i + 1 < n)
    def _():
        for hbm, slabs, si in arrays:
            copy(hbm, slabs.at[(i + 1) % 2], sems.at[2 * si + (i + 1) % 2],
                 i + 1, bm, nx)

    for hbm, slabs, si in arrays:
        wait(hbm, slabs.at[i % 2], sems.at[2 * si + i % 2], i, bm, nx)
    if has_halo:
        halos = ((rh_slabs, rh_lo, rh_hi), (rl_slabs, rl_lo, rl_hi),
                 (ph_slabs, ph_lo, ph_hi), (pl_slabs, pl_lo, pl_hi))
        for slabs, lo_ref, hi_ref in halos:
            _fill_edge_halo(slabs.at[i % 2], lo_ref, hi_ref, i, bm, nx,
                            ndim)

    scale = (params_ref[0], params_ref[1])
    beta = (params_ref[2], params_ref[3])
    r_slab = (rh_slabs[i % 2], rl_slabs[i % 2])
    p_slab = (ph_slabs[i % 2], pl_slabs[i % 2])
    # deferred p-update on the FULL halo slab (elementwise in df64)
    bh = jnp.broadcast_to(beta[0], r_slab[0].shape)
    bl = jnp.broadcast_to(beta[1], r_slab[0].shape)
    pnew_slab = _df.add(r_slab, _df.mul((bh, bl), p_slab))
    ap = _stencil_slab_df(pnew_slab, scale, bm, ndim)
    pnew_int = _interior_pair(pnew_slab, bm, ndim)
    pnh_ref[:], pnl_ref[:] = pnew_int
    part = _dot_df_grid(pnew_int[0], pnew_int[1], ap[0], ap[1])
    s = _df.add((acc[0], acc[1]), part)
    acc[0], acc[1] = s

    @pl.when(i == n - 1)
    def _():
        pap_ref[0] = acc[0]
        pap_ref[1] = acc[1]


def _pass_b_kernel_df64(params_ref, *refs, bm, nx, ndim, has_halo):
    if has_halo:
        (pnh_lo, pnh_hi, pnl_lo, pnl_hi,
         pnh_hbm, pnl_hbm, xh_ref, xl_ref, rh_ref, rl_ref,
         xho_ref, xlo_ref, rho_ref, rlo_ref, rr_ref,
         ph_slabs, pl_slabs, sems, acc) = refs
    else:
        (pnh_hbm, pnl_hbm, xh_ref, xl_ref, rh_ref, rl_ref,
         xho_ref, xlo_ref, rho_ref, rlo_ref, rr_ref,
         ph_slabs, pl_slabs, sems, acc) = refs
    i = pl.program_id(0)
    n = pl.num_programs(0)
    copy, wait = (_slab_copy, _slab_wait) if ndim == 2 else (
        _slab_copy3d, _slab_wait3d)
    arrays = ((pnh_hbm, ph_slabs, 0), (pnl_hbm, pl_slabs, 1))

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.float32(0.0)
        acc[1] = jnp.float32(0.0)
        for hbm, slabs, si in arrays:
            copy(hbm, slabs.at[0], sems.at[2 * si], 0, bm, nx)

    @pl.when(i + 1 < n)
    def _():
        for hbm, slabs, si in arrays:
            copy(hbm, slabs.at[(i + 1) % 2], sems.at[2 * si + (i + 1) % 2],
                 i + 1, bm, nx)

    for hbm, slabs, si in arrays:
        wait(hbm, slabs.at[i % 2], sems.at[2 * si + i % 2], i, bm, nx)
    if has_halo:
        for slabs, lo_ref, hi_ref in ((ph_slabs, pnh_lo, pnh_hi),
                                      (pl_slabs, pnl_lo, pnl_hi)):
            _fill_edge_halo(slabs.at[i % 2], lo_ref, hi_ref, i, bm, nx,
                            ndim)

    scale = (params_ref[0], params_ref[1])
    alpha = (params_ref[2], params_ref[3])
    slab = (ph_slabs[i % 2], pl_slabs[i % 2])
    ap = _stencil_slab_df(slab, scale, bm, ndim)
    pnew_int = _interior_pair(slab, bm, ndim)
    ah = jnp.broadcast_to(alpha[0], pnew_int[0].shape)
    al = jnp.broadcast_to(alpha[1], pnew_int[0].shape)
    x_new = _df.add((xh_ref[:], xl_ref[:]),
                    _df.mul((ah, al), pnew_int))
    xho_ref[:], xlo_ref[:] = x_new
    r_new = _df.sub((rh_ref[:], rl_ref[:]), _df.mul((ah, al), ap))
    rho_ref[:], rlo_ref[:] = r_new
    part = _dot_df_grid(r_new[0], r_new[1], r_new[0], r_new[1])
    s = _df.add((acc[0], acc[1]), part)
    acc[0], acc[1] = s

    @pl.when(i == n - 1)
    def _():
        rr_ref[0] = acc[0]
        rr_ref[1] = acc[1]


@functools.partial(jax.jit, static_argnames=("bm", "interpret"))
def fused_cg_pass_a_df64(scale, beta, r, p, halos=None, *, bm: int,
                         interpret: bool = False):
    """df64 pass A: ``p_new = r + beta p``; ``pap = p_new . A p_new``.

    ``scale``/``beta``: df64 scalar pairs; ``r``/``p``: (hi, lo) grid
    pairs; ``halos``: optional (r_lo, r_hi, p_lo, p_hi) each as an
    (hi, lo) pair of boundary rows.  Returns ``(p_new_pair, pap_pair)``.
    """
    shape = r[0].shape
    ndim = r[0].ndim
    nx = shape[0]
    has_halo = halos is not None
    params = jnp.stack([jnp.asarray(scale[0], jnp.float32),
                        jnp.asarray(scale[1], jnp.float32),
                        jnp.asarray(beta[0], jnp.float32),
                        jnp.asarray(beta[1], jnp.float32)])
    kernel = functools.partial(_pass_a_kernel_df64, bm=bm, nx=nx,
                               ndim=ndim, has_halo=has_halo)
    block = (bm,) + shape[1:]
    index_map = (lambda i: (i, 0)) if ndim == 2 else (lambda i: (i, 0, 0))
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    halo_inputs = ()
    if has_halo:
        (r_lo, r_hi, p_lo, p_hi) = halos
        halo_inputs = (r_lo[0], r_hi[0], r_lo[1], r_hi[1],
                       p_lo[0], p_hi[0], p_lo[1], p_hi[1])
    slab = _slab_shape(bm, shape)
    pnh, pnl, pap = pl.pallas_call(
        kernel,
        grid=(nx // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [vmem] * len(halo_inputs)
        + [pl.BlockSpec(memory_space=pl.ANY)] * 4,   # r/p hi+lo
        out_specs=[
            pl.BlockSpec(block, index_map),          # p_new hi
            pl.BlockSpec(block, index_map),          # p_new lo
            pl.BlockSpec(memory_space=pltpu.SMEM),   # pap (df64 pair)
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2,) + slab, jnp.float32),    # r hi
            pltpu.VMEM((2,) + slab, jnp.float32),    # r lo
            pltpu.VMEM((2,) + slab, jnp.float32),    # p hi
            pltpu.VMEM((2,) + slab, jnp.float32),    # p lo
            pltpu.SemaphoreType.DMA((8,)),
            pltpu.SMEM((2,), jnp.float32),           # pap df64 accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
        interpret=interpret,
    )(params, *halo_inputs, r[0], r[1], p[0], p[1])
    return (pnh, pnl), (pap[0], pap[1])


@functools.partial(jax.jit, static_argnames=("bm", "interpret"))
def fused_cg_pass_b_df64(scale, alpha, pnew, x, r, halos=None, *, bm: int,
                         interpret: bool = False):
    """df64 pass B: ``x += alpha p``, ``r -= alpha A p``, ``rr = r.r``;
    Ap recomputed from ``p_new``'s halo slabs; x/r pairs donated
    in place.  Returns ``(x_pair, r_pair, rr_pair)``."""
    shape = x[0].shape
    ndim = x[0].ndim
    nx = shape[0]
    has_halo = halos is not None
    params = jnp.stack([jnp.asarray(scale[0], jnp.float32),
                        jnp.asarray(scale[1], jnp.float32),
                        jnp.asarray(alpha[0], jnp.float32),
                        jnp.asarray(alpha[1], jnp.float32)])
    kernel = functools.partial(_pass_b_kernel_df64, bm=bm, nx=nx,
                               ndim=ndim, has_halo=has_halo)
    block = (bm,) + shape[1:]
    index_map = (lambda i: (i, 0)) if ndim == 2 else (lambda i: (i, 0, 0))
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    halo_inputs = ()
    if has_halo:
        (pn_lo, pn_hi) = halos
        halo_inputs = (pn_lo[0], pn_hi[0], pn_lo[1], pn_hi[1])
    nh = len(halo_inputs)
    slab = _slab_shape(bm, shape)
    xh, xl, rh, rl, rr = pl.pallas_call(
        kernel,
        grid=(nx // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [vmem] * nh
        + [pl.BlockSpec(memory_space=pl.ANY)] * 2    # p_new hi+lo
        + [pl.BlockSpec(block, index_map)] * 4,      # x/r hi+lo
        out_specs=[
            pl.BlockSpec(block, index_map),          # x hi out
            pl.BlockSpec(block, index_map),          # x lo out
            pl.BlockSpec(block, index_map),          # r hi out
            pl.BlockSpec(block, index_map),          # r lo out
            pl.BlockSpec(memory_space=pltpu.SMEM),   # rr (df64 pair)
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct(shape, jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2,) + slab, jnp.float32),    # p_new hi
            pltpu.VMEM((2,) + slab, jnp.float32),    # p_new lo
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SMEM((2,), jnp.float32),
        ],
        input_output_aliases={3 + nh: 0, 4 + nh: 1, 5 + nh: 2,
                              6 + nh: 3},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
        interpret=interpret,
    )(params, *halo_inputs, pnew[0], pnew[1], x[0], x[1], r[0], r[1])
    return (xh, xl), (rh, rl), (rr[0], rr[1])
