"""Pallas TPU kernels: the framework's hand-written native-kernel layer
(the role ``cusparseSpMV``/cuBLAS kernels play in the reference,
``CUDACG.cu:288,248-347`` - here written in Pallas/Mosaic, not called from a
vendor library)."""

from .resident import (
    cg_resident_2d,
    cg_resident_3d,
    cg_resident_df64_2d,
    cg_resident_df64_3d,
    supports_resident_2d,
    supports_resident_3d,
    supports_resident_df64_2d,
    supports_resident_df64_3d,
    vmem_bytes,
)
from .stencil import (
    pick_block_planes_3d,
    pick_block_rows_2d,
    stencil2d_apply,
    stencil3d_apply,
    supports_2d,
    supports_3d,
)

__all__ = [
    "cg_resident_2d",
    "cg_resident_3d",
    "cg_resident_df64_2d",
    "cg_resident_df64_3d",
    "supports_resident_2d",
    "supports_resident_3d",
    "supports_resident_df64_2d",
    "supports_resident_df64_3d",
    "vmem_bytes",
    "pick_block_planes_3d",
    "pick_block_rows_2d",
    "stencil2d_apply",
    "stencil3d_apply",
    "supports_2d",
    "supports_3d",
]
