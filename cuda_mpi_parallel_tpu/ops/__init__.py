"""Compute primitives: SpMV variants and fused BLAS-1 (TPU replacements for
the reference's cuSPARSE/cuBLAS calls, ``CUDACG.cu:248-347``)."""

from . import blas1, spmv

__all__ = ["blas1", "spmv"]
