"""Sparse matrix-vector product (SpMV) implementations, TPU-first.

This module is the framework's equivalent of the reference's single native
dependency, ``cusparseSpMV`` (reference ``CUDACG.cu:272-301``: the
``cusparseSpMV_bufferSize`` / ``cudaMalloc dBuffer`` / ``cusparseSpMV`` /
``cusparseDnVecGetValues`` sub-stack).  Where the reference delegates the
O(nnz) work to an opaque vendor kernel over CSR, we provide:

* ``csr_matvec``  - pure-JAX CSR SpMV via gather + segment-sum.  XLA compiles
  this to a fused gather/scatter; it is the correctness reference and the
  general-sparsity fallback.
* ``ell_matvec``  - SpMV over a padded ELL layout ``(n_rows, k)``.  TPU vector
  units want dense (8, 128) tiles; ELL turns the ragged CSR gather into a
  rectangular gather + row-sum that XLA can tile onto the VPU.  This is the
  preferred device layout for irregular sparsity.

All functions are shape-polymorphic in the Python sense but trace to static
shapes under ``jit`` (no data-dependent shapes - an XLA requirement the
reference never faced because cuSPARSE kernels are launched eagerly).

No workspace management is needed on TPU: the reference re-queries and
re-allocates its SpMV workspace every iteration (``CUDACG.cu:273,281`` - a
per-iteration leak, SURVEY quirk Q2); under XLA, buffers are planned once at
compile time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def csr_row_indices(indptr: jax.Array, nnz: int) -> jax.Array:
    """Expand a CSR ``indptr`` into per-entry row ids (COO row array).

    Computed once at operator-construction time, not per matvec (unlike the
    reference, which re-derives its SpMV workspace every iteration,
    ``CUDACG.cu:273-285``).
    """
    return jnp.searchsorted(
        indptr, jnp.arange(nnz, dtype=indptr.dtype), side="right"
    ).astype(jnp.int32) - 1


def csr_matvec(
    data: jax.Array,
    indices: jax.Array,
    rows: jax.Array,
    x: jax.Array,
    n_rows: int,
) -> jax.Array:
    """y = A @ x for A in CSR form (with precomputed COO row ids).

    Semantics of ``cusparseSpMV(..., alpha=1, beta=0)`` at ``CUDACG.cu:288``.
    """
    return jax.ops.segment_sum(
        data * jnp.take(x, indices, axis=0), rows, num_segments=n_rows
    )


def csr_matmat(
    data: jax.Array,
    indices: jax.Array,
    rows: jax.Array,
    x: jax.Array,
    n_rows: int,
) -> jax.Array:
    """Y = A @ X for CSR A and a column stack X of shape ``(n, k)``.

    The many-RHS SpMM: ONE sweep of the matrix entries (the memory-
    bound cost - arXiv 2204.00900: SpMV throughput IS sustained stream
    bandwidth) serves all ``k`` columns, so each extra column costs
    only the extra vector traffic.  Column ``j`` of the result is
    bit-identical to ``csr_matvec(..., x[:, j], ...)`` - the gathered
    rows and the segment sums are columnwise independent.
    """
    return jax.ops.segment_sum(
        data[:, None] * jnp.take(x, indices, axis=0), rows,
        num_segments=n_rows)


def ell_matvec(vals: jax.Array, cols: jax.Array, x: jax.Array) -> jax.Array:
    """y = A @ x for A in padded ELL form.

    ``vals``/``cols`` have shape ``(n_rows, k)``; padding entries carry
    ``val == 0`` (their column index is arbitrary but in-range), so the
    row-sum is exact without masking.
    """
    return jnp.sum(vals * jnp.take(x, cols, axis=0), axis=1)


def ell_matmat(vals: jax.Array, cols: jax.Array, x: jax.Array) -> jax.Array:
    """Y = A @ X for padded-ELL A and ``(n, k)`` X: one rectangular
    gather serves all columns (``jnp.take`` with the ``(n_rows, w)``
    index array yields ``(n_rows, w, k)``), row-summed per column."""
    return jnp.sum(vals[..., None] * jnp.take(x, cols, axis=0), axis=1)


def dense_matvec(a: jax.Array, x: jax.Array) -> jax.Array:
    """y = A @ x for dense A - rides the MXU directly."""
    return a @ x


def dia_matvec(bands: jax.Array, offsets, x: jax.Array) -> jax.Array:
    """y = A @ x for A in DIA (diagonal) form: ``y[i] += bands[d, i] *
    x[i + offsets[d]]``.

    The gather-free sparse format: each diagonal contributes one
    statically-shifted elementwise multiply-add, so the whole matvec is
    shifts + FMAs that XLA fuses into a single VPU pass - no index
    arrays in HBM at all.  On TPU this is ~2000x faster than the
    gather-based CSR path for banded matrices (measured 43 ms -> ~20 us
    per CG iteration on 1M-row 2D Poisson) because TPU vector memory has
    no efficient random access.  ``offsets`` must be a static tuple (it
    shapes the trace); the padded out-of-range band entries must be zero.
    """
    zero = jnp.zeros((), x.dtype)
    y = jnp.zeros_like(x)
    for d, k in enumerate(offsets):
        if k == 0:
            xs = x
        elif k > 0:
            xs = jnp.concatenate([x[k:], jnp.full((k,), zero)])
        else:
            xs = jnp.concatenate([jnp.full((-k,), zero), x[:k]])
        y = y + bands[d] * xs
    return y


def dia_matmat(bands: jax.Array, offsets, x: jax.Array) -> jax.Array:
    """Y = A @ X for DIA A and ``(n, k)`` X: the same statically-shifted
    FMAs as :func:`dia_matvec`, with each band broadcast across the
    RHS columns - one pass over the bands serves all ``k``."""
    zero_row = jnp.zeros((1,) + x.shape[1:], x.dtype)

    def shifted(k):
        if k == 0:
            return x
        if k > 0:
            return jnp.concatenate(
                [x[k:], jnp.broadcast_to(zero_row, (k,) + x.shape[1:])])
        return jnp.concatenate(
            [jnp.broadcast_to(zero_row, (-k,) + x.shape[1:]), x[:k]])

    y = jnp.zeros_like(x)
    for d, k in enumerate(offsets):
        y = y + bands[d][:, None] * shifted(k)
    return y


def csr_diagonal(
    data: jax.Array, indices: jax.Array, rows: jax.Array, n_rows: int
) -> jax.Array:
    """Extract diag(A) from CSR (for the Jacobi preconditioner).

    The reference has no preconditioning at all; BASELINE config #3 requires
    Jacobi-PCG.
    """
    on_diag = indices == rows
    return jax.ops.segment_sum(
        jnp.where(on_diag, data, jnp.zeros_like(data)), rows, num_segments=n_rows
    )
