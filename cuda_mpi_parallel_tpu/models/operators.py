"""Linear operators: the framework's data-structure layer.

This replaces the reference's descriptor machinery - the legacy
``cusparseMatDescr_t`` (dead code at ``CUDACG.cu:203-209``), the generic-API
``cusparseCreateCsr`` (``:213-216``) and ``cusparseCreateDnVec`` (``:223,229``)
- with registered JAX pytrees.  Because operators are pytrees, they pass
straight through ``jit`` / ``shard_map`` / ``lax.while_loop`` carriers: there
are no handles to create or destroy, and the reference's 24-line ``CLEANUP``
teardown macro (``CUDACG.cu:10-33``) has no equivalent here - XLA owns all
buffers.

Operator taxonomy (all expose ``matvec``/``__matmul__``/``diagonal``):

* ``DenseOperator``    - dense A, rides the MXU (BASELINE config #1).
* ``CSRMatrix``        - general sparsity, gather + segment-sum (the layout
  of the reference's hardcoded system, ``CUDACG.cu:94-117``).
* ``ELLMatrix``        - padded rectangular layout, the TPU-preferred device
  format.
* ``Stencil2D/3D``     - matrix-free 5-point / 7-point Poisson application:
  on TPU the idiomatic way to apply a stencil is shifted adds on the grid,
  not a sparse gather (BASELINE configs #2 and #4).
* ``JacobiPreconditioner`` - diag(A)^-1 (BASELINE config #3).

Host-side constructors (``from_scipy`` etc.) use numpy; everything reachable
from ``matvec`` is pure traced JAX.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import spmv


class LinearOperator:
    """Abstract symmetric-positive-(semi)definite operator interface."""

    shape: Tuple[int, int]

    @property
    def n(self) -> int:
        return self.shape[0]

    @property
    def dtype(self):
        raise NotImplementedError

    def matvec(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def matmat(self, x: jax.Array) -> jax.Array:
        """Apply to a column stack ``(n, k)`` -> ``(n, k)`` (the
        many-RHS path, ``solver.many``).  The default vmaps
        :meth:`matvec` over columns - correct for any pure operator;
        formats where one batched sweep beats ``k`` gathers (CSR/ELL/
        DIA/dense, the distributed CSR operators) override it with a
        true SpMM so the matrix is read ONCE for all columns."""
        return jax.vmap(self.matvec, in_axes=1, out_axes=1)(x)

    def __matmul__(self, x: jax.Array) -> jax.Array:
        return self.matvec(x)

    def diagonal(self) -> jax.Array:
        raise NotImplementedError

    def to_dense(self) -> jax.Array:
        """Materialize (small problems / tests only)."""
        eye = jnp.eye(self.shape[1], dtype=self.dtype)
        return jax.vmap(self.matvec, in_axes=1, out_axes=1)(eye)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("a",),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class DenseOperator(LinearOperator):
    """Dense matrix operator - SpMV is a plain MXU matmul."""

    a: jax.Array

    @property
    def shape(self):
        return self.a.shape

    @property
    def dtype(self):
        return self.a.dtype

    def matvec(self, x):
        return spmv.dense_matvec(self.a, x)

    def matmat(self, x):
        return self.a @ x  # one MXU matmul serves every column

    def diagonal(self):
        return jnp.diagonal(self.a)

    def to_dense(self):
        return self.a


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("data", "indices", "indptr", "rows"),
    meta_fields=("shape",),
)
@dataclasses.dataclass(frozen=True)
class CSRMatrix(LinearOperator):
    """CSR sparse matrix as a JAX pytree.

    Same logical layout as the reference's host arrays ``h_valA`` /
    ``h_csrRowPtrA`` / ``h_csrColIndA`` (``CUDACG.cu:94-117``): 0-based,
    int32 indices.  Additionally carries ``rows`` - per-entry COO row ids,
    precomputed once at construction so the hot matvec is a single
    gather + segment-sum (the reference instead re-derives SpMV workspace
    every iteration, ``CUDACG.cu:273-285``, quirk Q2).
    """

    data: jax.Array     # (nnz,)
    indices: jax.Array  # (nnz,) int32 column indices
    indptr: jax.Array   # (n_rows+1,) int32
    rows: jax.Array     # (nnz,) int32 row ids (derived)
    shape: Tuple[int, int]

    @classmethod
    def from_arrays(cls, data, indices, indptr, shape=None) -> "CSRMatrix":
        data = jnp.asarray(data)
        indices = jnp.asarray(indices, dtype=jnp.int32)
        indptr = jnp.asarray(indptr, dtype=jnp.int32)
        n_rows = indptr.shape[0] - 1
        if shape is None:
            shape = (n_rows, n_rows)
        rows = spmv.csr_row_indices(indptr, data.shape[0])
        return cls(data=data, indices=indices, indptr=indptr, rows=rows,
                   shape=tuple(shape))

    @classmethod
    def from_scipy(cls, mat, dtype=None) -> "CSRMatrix":
        csr = mat.tocsr()
        data = csr.data if dtype is None else csr.data.astype(dtype)
        return cls.from_arrays(data, csr.indices, csr.indptr, csr.shape)

    @classmethod
    def from_coo(cls, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 n: int, dtype=None) -> "CSRMatrix":
        """Sort COO triplets into canonical CSR (row-major, ascending
        columns).  Duplicates are kept (CSR semantics sum them in matvec);
        the shared assembly used by the stencil generators and
        ``permuted``'s fallback."""
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals)
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        if dtype is not None:
            vals = vals.astype(np.dtype(dtype))
        return cls.from_arrays(vals, cols.astype(np.int32), indptr, (n, n))

    @classmethod
    def from_dense(cls, a, tol: float = 0.0) -> "CSRMatrix":
        a = np.asarray(a)
        mask = np.abs(a) > tol
        indptr = np.concatenate([[0], np.cumsum(mask.sum(axis=1))]).astype(np.int32)
        rows_np, cols_np = np.nonzero(mask)
        return cls.from_arrays(a[rows_np, cols_np], cols_np.astype(np.int32),
                               indptr, a.shape)

    @property
    def nnz(self) -> int:
        return self.data.shape[0]

    @property
    def dtype(self):
        return self.data.dtype

    def matvec(self, x):
        return spmv.csr_matvec(self.data, self.indices, self.rows, x,
                               self.shape[0])

    def matmat(self, x):
        return spmv.csr_matmat(self.data, self.indices, self.rows, x,
                               self.shape[0])

    def diagonal(self):
        return spmv.csr_diagonal(self.data, self.indices, self.rows,
                                 self.shape[0])

    def to_dense(self):
        out = jnp.zeros(self.shape, dtype=self.dtype)
        return out.at[self.rows, self.indices].add(self.data)

    def bandwidth(self) -> int:
        """max |i - j| over stored entries (host-side; C++ fast path)."""
        from ..native import bindings

        if bindings.available():
            return bindings.csr_bandwidth(np.asarray(self.indptr),
                                          np.asarray(self.indices))
        rows = np.asarray(self.rows, dtype=np.int64)
        cols = np.asarray(self.indices, dtype=np.int64)
        return int(np.abs(rows - cols).max()) if rows.size else 0

    def rcm_permutation(self) -> np.ndarray:
        """Reverse Cuthill-McKee ordering (perm[new] = old) minimizing the
        bandwidth of ``P A P^T`` - the locality lever for the gather-based
        SpMV formats (the x-gather becomes near-sequential).  Assumes a
        symmetric sparsity pattern (SPD matrices always have one).  Native
        C++ path when built; scipy.sparse.csgraph fallback.
        """
        from ..native import bindings

        if bindings.available():
            return bindings.rcm_order(np.asarray(self.indptr),
                                      np.asarray(self.indices))
        import scipy.sparse as sp
        from scipy.sparse.csgraph import reverse_cuthill_mckee

        m = sp.csr_matrix(
            (np.asarray(self.data), np.asarray(self.indices),
             np.asarray(self.indptr)), shape=self.shape)
        return np.asarray(reverse_cuthill_mckee(m, symmetric_mode=True),
                          dtype=np.int32)

    def permuted(self, perm: np.ndarray) -> "CSRMatrix":
        """Symmetric permutation ``P A P^T`` (row/column reorder).

        Solving the permuted system: ``A' x' = b'`` with ``b' = b[perm]``
        gives ``x = scatter(x', perm)`` i.e. ``x[perm] = x'``.
        """
        perm = np.asarray(perm)
        n = self.shape[0]
        if perm.shape != (n,):
            raise ValueError(f"permutation shape {perm.shape} != ({n},)")
        if not np.array_equal(np.sort(perm), np.arange(n)):
            raise ValueError("perm is not a permutation of range(n)")
        from ..native import bindings

        if bindings.available():
            vals, indices, indptr = bindings.csr_permute_sym(
                np.asarray(self.indptr), np.asarray(self.indices),
                np.asarray(self.data), perm)
            return CSRMatrix.from_arrays(vals, indices, indptr, self.shape)
        inv = np.empty(n, dtype=np.int64)
        inv[perm] = np.arange(n)
        return CSRMatrix.from_coo(inv[np.asarray(self.rows)],
                                  inv[np.asarray(self.indices)],
                                  np.asarray(self.data), n)

    def to_dia(self, max_diags: int = 512) -> "DIAMatrix":
        """Convert to the gather-free DIA format (see ``DIAMatrix``)."""
        return DIAMatrix.from_csr(self, max_diags=max_diags)

    def to_shiftell(self, h: int | None = None,
                    kc: int = 8) -> "ShiftELLMatrix":
        """Convert to the pallas shift-ELL format (see ``ShiftELLMatrix``).
        ``h=None`` picks the block height by the packing cost model
        (``ops.pallas.spmv.choose_h``).  Combine with
        ``rcm_permutation``/``permuted`` first for unstructured matrices -
        sheet count tracks chunk-distance diversity, which RCM
        concentrates."""
        return ShiftELLMatrix.from_csr(self, h=h, kc=kc)

    def to_shiftell_df64(self, h: int | None = None,
                         kc: int = 8) -> "ShiftELLDF64Matrix":
        """Convert to the double-float pallas shift-ELL format - f64-class
        SpMV on assembled matrices (``solver.df64.cg_df64``; the
        reference's ``CUDA_R_64F`` CSR configuration,
        ``CUDACG.cu:216,288``).  Values split from this matrix's stored
        data; pass f64 data at construction (e.g. ``mmio`` loads) for
        full df64 matrix precision - f32-stored data is exact but carries
        no low word."""
        return ShiftELLDF64Matrix.from_csr(self, h=h, kc=kc)

    def to_ell(self, width: int | None = None) -> "ELLMatrix":
        """Convert to padded ELL (host-side; C++ fast path when built)."""
        indptr = np.asarray(self.indptr)
        data = np.asarray(self.data)
        indices = np.asarray(self.indices)

        from ..native import bindings

        if bindings.available():
            vals, cols = bindings.csr_to_ell(indptr, indices, data,
                                             width=width)
            return ELLMatrix(vals=jnp.asarray(vals), cols=jnp.asarray(cols),
                             shape=self.shape)

        counts = np.diff(indptr)
        k = int(counts.max()) if width is None else int(width)
        if width is not None and counts.max() > width:
            raise ValueError(
                f"ELL width {width} < max row nnz {int(counts.max())}")
        n = self.shape[0]
        vals = np.zeros((n, k), dtype=data.dtype)
        cols = np.zeros((n, k), dtype=np.int32)
        for i in range(n):
            lo, hi = indptr[i], indptr[i + 1]
            vals[i, : hi - lo] = data[lo:hi]
            cols[i, : hi - lo] = indices[lo:hi]
        return ELLMatrix(vals=jnp.asarray(vals), cols=jnp.asarray(cols),
                         shape=self.shape)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("vals", "cols"),
    meta_fields=("shape",),
)
@dataclasses.dataclass(frozen=True)
class ELLMatrix(LinearOperator):
    """Padded ELL layout ``(n_rows, k)`` - the TPU-preferred sparse format.

    TPU vector units operate on dense (8, 128) tiles; the ragged CSR gather
    is hostile to that, so rows are padded to a common width ``k`` with
    zero-valued entries (in-range column index 0).  For stencil-structured
    matrices k is tiny (5 or 7) and padding waste is negligible.
    """

    vals: jax.Array  # (n_rows, k)
    cols: jax.Array  # (n_rows, k) int32
    shape: Tuple[int, int]

    @property
    def width(self) -> int:
        return self.vals.shape[1]

    @property
    def dtype(self):
        return self.vals.dtype

    def matvec(self, x):
        return spmv.ell_matvec(self.vals, self.cols, x)

    def matmat(self, x):
        return spmv.ell_matmat(self.vals, self.cols, x)

    def diagonal(self):
        row_ids = jnp.arange(self.shape[0], dtype=self.cols.dtype)[:, None]
        return jnp.sum(jnp.where(self.cols == row_ids, self.vals, 0), axis=1)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("bands",),
    meta_fields=("offsets", "shape"),
)
@dataclasses.dataclass(frozen=True)
class DIAMatrix(LinearOperator):
    """DIA (diagonal) sparse format: the gather-free TPU layout for banded
    matrices.

    ``bands[d, i] = A[i, i + offsets[d]]`` (row-indexed storage,
    zero-padded where ``i + offset`` is out of range).  The matvec is one
    statically-shifted fused multiply-add per diagonal - no index arrays,
    no gather - which on TPU beats the CSR/ELL gather paths by ~3 orders
    of magnitude for structured matrices (see ``ops.spmv.dia_matvec``).
    Combine with ``CSRMatrix.rcm_permutation`` to first concentrate a
    general matrix's population near the diagonal, then convert the
    banded result here when its diagonal count is small enough.
    """

    bands: jax.Array          # (n_diags, n)
    offsets: Tuple[int, ...]  # static: shapes the trace
    shape: Tuple[int, int]

    @classmethod
    def from_csr(cls, a: "CSRMatrix", max_diags: int = 512) -> "DIAMatrix":
        """Convert a CSR matrix (host-side).  Fails when the matrix
        populates more than ``max_diags`` distinct diagonals - DIA's
        storage and compute are O(n_diags * n), so scattered sparsity
        should stay in CSR/ELL."""
        rows = np.asarray(a.rows, dtype=np.int64)
        cols = np.asarray(a.indices, dtype=np.int64)
        data = np.asarray(a.data)
        offs = np.unique(cols - rows)
        if offs.size > max_diags:
            raise ValueError(
                f"matrix populates {offs.size} diagonals > max_diags="
                f"{max_diags}; DIA would be denser than ELL - keep CSR/ELL "
                f"(or RCM-reorder first)")
        n = a.shape[0]
        bands = np.zeros((offs.size, n), dtype=data.dtype)
        didx = np.searchsorted(offs, cols - rows)  # offs is sorted-unique
        np.add.at(bands, (didx, rows), data)
        return cls(bands=jnp.asarray(bands),
                   offsets=tuple(int(k) for k in offs), shape=a.shape)

    @property
    def n_diags(self) -> int:
        return len(self.offsets)

    @property
    def dtype(self):
        return self.bands.dtype

    def matvec(self, x):
        return spmv.dia_matvec(self.bands, self.offsets, x)

    def matmat(self, x):
        return spmv.dia_matmat(self.bands, self.offsets, x)

    def diagonal(self):
        if 0 in self.offsets:
            return self.bands[self.offsets.index(0)]
        return jnp.zeros(self.shape[0], self.dtype)


def _pallas_interpret() -> bool:
    """Pallas kernels run compiled on TPU, interpreted elsewhere (tests)."""
    return jax.default_backend() != "tpu"


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("vals", "lane_idx", "chunk_blocks", "diag"),
    meta_fields=("shape", "h", "kc", "n_sheets", "nch", "nch_pad",
                 "pad"),
)
@dataclasses.dataclass(frozen=True)
class ShiftELLMatrix(LinearOperator):
    """Shift-ELL: the pallas-kernel sparse format for assembled matrices.

    The TPU equivalent of the reference's ``cusparseSpMV`` over CSR
    (``CUDACG.cu:288``): nonzeros are packed host-side into "sheets" whose
    matvec needs only a VMEM sublane shift plus one hardware lane gather
    per sheet (``ops.pallas.spmv``) - measured ~20-40x faster than the
    XLA gather paths (csr/ell) on 1M-row matrices.  Cost scales with the
    sheet count: == max nnz/row for banded matrices (any structured
    problem, or unstructured ones after RCM), growing with chunk-distance
    diversity for scattered sparsity.  ``x`` must stay VMEM-resident
    (n <= ~2.5M f32 rows per device; shard larger systems).
    """

    vals: jax.Array          # (n_chunks, kc, h+1, 128); row h = ws meta
    lane_idx: jax.Array      # (n_chunks, kc, h, 128) i16 (h%16==0) or i32
    chunk_blocks: jax.Array  # (n_chunks,) int32, non-decreasing
    diag: jax.Array      # (n,) - stored; the sheet layout loses O(1) access
    shape: Tuple[int, int]
    h: int
    kc: int
    n_sheets: int         # real sheets (cost model; arrays are padded)
    nch: int
    nch_pad: int
    pad: int

    @classmethod
    def from_csr(cls, a: "CSRMatrix", h: int | None = None,
                 kc: int = 8) -> "ShiftELLMatrix":
        from ..ops.pallas import spmv as pk

        n = a.shape[0]
        if h is None:
            h = pk.choose_h(np.asarray(a.indptr), np.asarray(a.indices),
                            n, kc=kc, itemsize=np.dtype(a.dtype).itemsize)
        packed = pk.pack_shift_ell(
            np.asarray(a.indptr), np.asarray(a.indices),
            np.asarray(a.data), n, h=h, kc=kc)
        return cls(
            vals=jnp.asarray(packed.vals),
            lane_idx=jnp.asarray(packed.lane_idx),
            chunk_blocks=jnp.asarray(packed.chunk_blocks),
            diag=a.diagonal(),
            shape=a.shape, h=packed.h, kc=packed.kc,
            n_sheets=packed.n_sheets, nch=packed.nch,
            nch_pad=packed.nch_pad, pad=packed.pad)

    @property
    def dtype(self):
        return self.vals.dtype

    def matvec(self, x):
        from ..ops.pallas import spmv as pk

        return pk.shift_ell_matvec(
            x, self.vals, self.lane_idx, self.chunk_blocks,
            h=self.h, kc=self.kc, n=self.shape[0],
            nch=self.nch, nch_pad=self.nch_pad, pad=self.pad,
            interpret=_pallas_interpret())

    def diagonal(self):
        return self.diag


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("vals_hi", "vals_lo", "lane_idx", "chunk_blocks",
                 "diag_hi", "diag_lo"),
    meta_fields=("shape", "h", "kc", "n_sheets", "nch", "nch_pad", "pad"),
)
@dataclasses.dataclass(frozen=True)
class ShiftELLDF64Matrix:
    """Double-float shift-ELL: f64-class assembled SpMV at pallas speed.

    The TPU equivalent of the reference's defining configuration - f64
    ``cusparseSpMV`` over assembled CSR (``CUDA_R_64F`` descriptor,
    ``CUDACG.cu:216,288``) - on hardware with no f64 units.  Values and
    vectors are unevaluated (hi, lo) f32 pairs (``ops.df64``); the
    kernel gathers both x planes with shared lane indices and
    accumulates through error-free transforms (``ops.pallas.spmv``
    df64 section).  Use with ``solver.df64.cg_df64``; NOT a
    ``LinearOperator`` - the f32 solver cannot consume the pair
    representation (``matvec_df`` replaces ``matvec``).

    Both x planes must stay VMEM-resident: half the f32 capacity,
    n <= ~1.3M rows per device at the 10 MB v5e budget; shard larger
    systems over a mesh.
    """

    vals_hi: jax.Array        # (n_chunks, kc, h+1, 128) f32; row h = meta
    vals_lo: jax.Array        # (n_chunks, kc, h+1, 128) f32; row h = 0
    lane_idx: jax.Array       # (n_chunks, kc, h, 128) i16 or i32
    chunk_blocks: jax.Array   # (n_chunks,) int32, non-decreasing
    diag_hi: jax.Array        # (n,) diag(A) hi (Jacobi preconditioning)
    diag_lo: jax.Array        # (n,) diag(A) lo
    shape: Tuple[int, int]
    h: int
    kc: int
    n_sheets: int
    nch: int
    nch_pad: int
    pad: int

    @classmethod
    def from_csr(cls, a: "CSRMatrix", h: int | None = None,
                 kc: int = 8) -> "ShiftELLDF64Matrix":
        from ..ops.pallas import spmv as pk

        n = a.shape[0]
        indptr = np.asarray(a.indptr)
        indices = np.asarray(a.indices)
        data64 = np.asarray(a.data, dtype=np.float64)
        if h is None:
            # both x planes resident: budget as one f64 plane (itemsize 8)
            h = pk.choose_h(indptr, indices, n, kc=kc, itemsize=8)
        packed = pk.pack_shift_ell_df64(indptr, indices, data64, n,
                                        h=h, kc=kc)
        # diagonal in df64: hi/lo split of the f64 diagonal
        diag64 = np.zeros(n, dtype=np.float64)
        rows = np.repeat(np.arange(n), np.diff(indptr))
        on_diag = rows == indices
        np.add.at(diag64, rows[on_diag], data64[on_diag])
        diag_hi = diag64.astype(np.float32)
        diag_lo = (diag64 - diag_hi.astype(np.float64)).astype(np.float32)
        return cls(
            vals_hi=jnp.asarray(packed.vals_hi),
            vals_lo=jnp.asarray(packed.vals_lo),
            lane_idx=jnp.asarray(packed.lane_idx),
            chunk_blocks=jnp.asarray(packed.chunk_blocks),
            diag_hi=jnp.asarray(diag_hi), diag_lo=jnp.asarray(diag_lo),
            shape=a.shape, h=packed.h, kc=packed.kc,
            n_sheets=packed.n_sheets, nch=packed.nch,
            nch_pad=packed.nch_pad, pad=packed.pad)

    @classmethod
    def from_shiftell(cls, a: "ShiftELLMatrix") -> "ShiftELLDF64Matrix":
        """Lift an f32 shift-ELL matrix to df64 (lo planes = 0): the
        matrix values stay exactly what they were in f32, but matvec
        products and sums accumulate in df64."""
        return cls(
            vals_hi=a.vals, vals_lo=jnp.zeros_like(a.vals),
            lane_idx=a.lane_idx, chunk_blocks=a.chunk_blocks,
            diag_hi=a.diag, diag_lo=jnp.zeros_like(a.diag),
            shape=a.shape, h=a.h, kc=a.kc, n_sheets=a.n_sheets,
            nch=a.nch, nch_pad=a.nch_pad, pad=a.pad)

    @property
    def nnz_dtype(self):
        return self.vals_hi.dtype

    def matvec_df(self, x):
        """(y_hi, y_lo) = A @ (x_hi, x_lo); x is a df64 pair."""
        from ..ops.pallas import spmv as pk

        return pk.shift_ell_matvec_df64(
            x[0], x[1], self.vals_hi, self.vals_lo, self.lane_idx,
            self.chunk_blocks, h=self.h, kc=self.kc, n=self.shape[0],
            nch=self.nch, nch_pad=self.nch_pad, pad=self.pad,
            interpret=_pallas_interpret())

    def diagonal_df(self):
        return self.diag_hi, self.diag_lo

    def matvec(self, x):
        raise TypeError(
            "ShiftELLDF64Matrix is a double-float operator: use "
            "solver.df64.cg_df64 (matvec_df), not the f32 solve path")

    def __matmul__(self, x):
        return self.matvec(x)


# Above ~3 VMEM's worth of grid the CG state cannot stay resident on-chip
# and the slab-DMA pallas kernels win (measured: 1210 vs 1612 us/CG-iter at
# 4096^2 f32 on v5e); below it XLA's fused while_loop is optimal.
_PALLAS_BYTES_THRESHOLD = 48 * 2 ** 20


def _resolve_backend(backend: str, grid, itemsize: int, supported: bool) -> str:
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown backend: {backend!r}")
    if backend != "auto":
        return backend
    n_bytes = itemsize
    for g in grid:
        n_bytes *= g
    return "pallas" if (supported and n_bytes >= _PALLAS_BYTES_THRESHOLD) \
        else "xla"


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("scale",),
    meta_fields=("grid", "backend", "_dtype_name"),
)
@dataclasses.dataclass(frozen=True)
class Stencil2D(LinearOperator):
    """Matrix-free 2D 5-point Poisson (Dirichlet) operator.

    ``A x`` where A is the standard finite-difference Laplacian
    ``(4u[i,j] - u[i-1,j] - u[i+1,j] - u[i,j-1] - u[i,j+1]) * scale`` - the
    matrix of BASELINE config #2, applied as shifted adds on the grid rather
    than a sparse gather (the TPU-idiomatic formulation: pure VPU work,
    no indices in HBM at all).

    ``backend``: "xla" (default - fused shifted adds; optimal when the CG
    state fits in VMEM) or "pallas" (double-buffered slab-DMA kernel,
    ``ops/pallas/stencil.py``; wins in the HBM-bound regime - measured
    757 vs 702 GB/s at 4096^2 f32 on v5e).
    """

    scale: jax.Array  # traced scalar (scale sweeps reuse one executable)
    grid: Tuple[int, int]
    backend: str = "xla"
    _dtype_name: str = "float32"

    @classmethod
    def create(cls, nx: int, ny: int, scale: float = 1.0, dtype=jnp.float32,
               backend: str = "xla"):
        dtype = jnp.dtype(dtype)
        from ..ops.pallas import stencil as pk

        backend = _resolve_backend(backend, (nx, ny), dtype.itemsize,
                                   pk.supports_2d(nx, ny))
        if backend == "pallas" and not pk.supports_2d(nx, ny):
            raise ValueError(
                f"pallas 2D stencil needs nx % 8 == 0 and ny % 128 == 0,"
                f" got ({nx}, {ny})")
        return cls(scale=jnp.asarray(scale, dtype), grid=(nx, ny),
                   backend=backend, _dtype_name=dtype.name)

    @property
    def shape(self):
        n = self.grid[0] * self.grid[1]
        return (n, n)

    @property
    def dtype(self):
        return jnp.dtype(self._dtype_name)

    def matvec(self, x):
        nx, ny = self.grid
        u = x.reshape(nx, ny)
        if self.backend == "pallas":
            from ..ops.pallas import stencil as pk

            bm = pk.pick_block_rows_2d(nx, ny, self.dtype.itemsize)
            y = pk.stencil2d_apply(u, self.scale, bm=bm,
                                   interpret=_pallas_interpret())
            return y.reshape(-1)
        up = jnp.pad(u, 1)
        y = (4.0 * u
             - up[:-2, 1:-1] - up[2:, 1:-1]
             - up[1:-1, :-2] - up[1:-1, 2:])
        return (self.scale * y).reshape(-1)

    def diagonal(self):
        return jnp.full(self.shape[0], 4.0, dtype=self.dtype) * self.scale


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("scale",),
    meta_fields=("grid", "backend", "_dtype_name"),
)
@dataclasses.dataclass(frozen=True)
class Stencil3D(LinearOperator):
    """Matrix-free 3D 7-point Poisson (Dirichlet) operator.

    The north-star problem (BASELINE config #4: N=256^3).  Same shifted-add
    formulation as ``Stencil2D``; the distributed version partitions the
    leading grid axis across the mesh and exchanges boundary planes with
    ``lax.ppermute`` (see the ``parallel`` package).

    ``backend``: "xla" or "pallas" (+-1-plane slab-DMA kernel; 683 vs
    664 GB/s at 256^3 f32 on v5e).
    """

    scale: jax.Array
    grid: Tuple[int, int, int]
    backend: str = "xla"
    _dtype_name: str = "float32"

    @classmethod
    def create(cls, nx: int, ny: int, nz: int, scale: float = 1.0,
               dtype=jnp.float32, backend: str = "xla"):
        dtype = jnp.dtype(dtype)
        from ..ops.pallas import stencil as pk

        backend = _resolve_backend(backend, (nx, ny, nz), dtype.itemsize,
                                   pk.supports_3d(nx, ny, nz))
        if backend == "pallas" and not pk.supports_3d(nx, ny, nz):
            raise ValueError(
                f"pallas 3D stencil needs nx % 2 == 0, ny % 8 == 0 and "
                f"nz % 128 == 0, got ({nx}, {ny}, {nz})")
        return cls(scale=jnp.asarray(scale, dtype), grid=(nx, ny, nz),
                   backend=backend, _dtype_name=dtype.name)

    @property
    def shape(self):
        n = self.grid[0] * self.grid[1] * self.grid[2]
        return (n, n)

    @property
    def dtype(self):
        return jnp.dtype(self._dtype_name)

    def matvec(self, x):
        nx, ny, nz = self.grid
        u = x.reshape(nx, ny, nz)
        if self.backend == "pallas":
            from ..ops.pallas import stencil as pk

            bm = pk.pick_block_planes_3d(nx, ny, nz, self.dtype.itemsize)
            y = pk.stencil3d_apply(u, self.scale, bm=bm,
                                   interpret=_pallas_interpret())
            return y.reshape(-1)
        up = jnp.pad(u, 1)
        y = (6.0 * u
             - up[:-2, 1:-1, 1:-1] - up[2:, 1:-1, 1:-1]
             - up[1:-1, :-2, 1:-1] - up[1:-1, 2:, 1:-1]
             - up[1:-1, 1:-1, :-2] - up[1:-1, 1:-1, 2:])
        return (self.scale * y).reshape(-1)

    def diagonal(self):
        return jnp.full(self.shape[0], 6.0, dtype=self.dtype) * self.scale


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("inv_diag",),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class JacobiPreconditioner(LinearOperator):
    """M^-1 = diag(A)^-1 (BASELINE config #3).

    The reference has no preconditioning; this is the first rung the new
    framework adds above it.
    """

    inv_diag: jax.Array

    @classmethod
    def from_operator(cls, a: LinearOperator) -> "JacobiPreconditioner":
        return cls(inv_diag=1.0 / a.diagonal())

    @property
    def shape(self):
        n = self.inv_diag.shape[0]
        return (n, n)

    @property
    def dtype(self):
        return self.inv_diag.dtype

    def matvec(self, x):
        return self.inv_diag * x

    def matmat(self, x):
        return self.inv_diag[:, None] * x

    def diagonal(self):
        return self.inv_diag


@partial(
    jax.tree_util.register_dataclass,
    data_fields=(),
    meta_fields=("dim", "_dtype_name"),
)
@dataclasses.dataclass(frozen=True)
class IdentityOperator(LinearOperator):
    """M = I - the 'no preconditioner' object (keeps the PCG body uniform)."""

    dim: int
    _dtype_name: str = "float32"

    @property
    def shape(self):
        return (self.dim, self.dim)

    @property
    def dtype(self):
        return jnp.dtype(self._dtype_name)

    def matvec(self, x):
        return x

    def matmat(self, x):
        return x

    def diagonal(self):
        return jnp.ones(self.n, dtype=self.dtype)
