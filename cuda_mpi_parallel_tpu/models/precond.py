"""Preconditioners beyond Jacobi: Chebyshev polynomial and block-Jacobi.

The reference has **no preconditioning at all** - its CG is the plain
textbook recurrence (``CUDACG.cu:269-352``) and its only robustness device
is a hard exit (SURVEY SS5).  ``JacobiPreconditioner`` (models/operators.py)
is the first rung above it; this module adds the two next rungs that are
actually TPU-idiomatic:

* ``ChebyshevPreconditioner`` - a fixed-degree Chebyshev polynomial in A
  applied to the residual.  Matrix-polynomial preconditioning is the
  TPU-native choice: its only ingredient is the operator's own matvec
  (stencil shifted-adds / ELL rows - all VPU work, zero data-dependent
  control flow), it inherits the distributed operator's communication
  untouched, and for the halo-exchange stencil operators it adds no
  collectives beyond those ppermutes (contrast ILU/SSOR triangular
  solves, which serialize along the sparsity structure and are hostile
  to both the VPU and ``jit``).
* ``BlockJacobiPreconditioner`` - M^-1 = blockdiag(A)^-1 with dense blocks:
  the application is one batched (n_blocks, bs, bs) x (n_blocks, bs)
  matmul, which XLA maps straight onto the MXU.

Both are symmetric positive definite by construction (tests check this),
so CG's theory applies to the preconditioned system.

Spectral bounds for Chebyshev come from ``estimate_lmax`` - on-device
power iteration, jittable, psum-reducing under ``axis_name`` so the same
code serves the ``shard_map`` path.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import blas1
from .operators import CSRMatrix, LinearOperator


def estimate_lmax(
    a: LinearOperator,
    *,
    iters: int = 30,
    axis_name: Optional[str] = None,
    safety: float = 1.05,
) -> jax.Array:
    """Largest-eigenvalue estimate of SPD ``a`` by on-device power iteration.

    Returns ``safety *`` (final Rayleigh quotient) as a 0-d device scalar -
    jittable, no host sync.  Under ``axis_name`` the operator is the *local*
    block of a row-partitioned global operator and the reductions psum over
    the mesh, so the estimate is of the GLOBAL spectrum.

    The deterministic start vector has nonzero overlap with the dominant
    eigenvector for any symmetric A that is not specially aligned with it;
    ``iters=30`` gives ~1% accuracy on the Poisson operators (tests check
    against the analytic 2D/3D Laplacian spectrum).  ``safety`` inflates
    the estimate so Chebyshev's interval truly covers the spectrum - an
    eigenvalue outside [lmin, lmax] could flip the polynomial's sign and
    destroy positive definiteness.
    """
    n_local = a.shape[0]
    dtype = a.dtype
    # Deterministic pseudo-random start: device-unique via axis_index so
    # shards do not mirror each other (a mirrored start can be orthogonal
    # to non-symmetric eigenvectors of the global operator).
    idx = jnp.arange(n_local, dtype=dtype)
    if axis_name is not None:
        names = (axis_name if isinstance(axis_name, (tuple, list))
                 else (axis_name,))
        shard = jnp.zeros((), jnp.int32)
        for nm in names:  # linearized multi-axis shard index
            shard = shard * lax.psum(jnp.int32(1), nm) + lax.axis_index(nm)
        idx = idx + shard.astype(dtype) * n_local
    v0 = jnp.sin(idx * 12.9898 + 78.233) + 1.5

    def body(_, v):
        w = a @ v
        nrm = jnp.sqrt(blas1.dot(w, w, axis_name=axis_name))
        return w / jnp.maximum(nrm, jnp.asarray(1e-30, dtype))

    v = lax.fori_loop(0, iters, body, v0 / jnp.sqrt(
        blas1.dot(v0, v0, axis_name=axis_name)))
    rayleigh = blas1.dot(v, a @ v, axis_name=axis_name)
    return rayleigh * jnp.asarray(safety, dtype)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("a", "lmin", "lmax"),
    meta_fields=("degree",),
)
@dataclasses.dataclass(frozen=True)
class ChebyshevPreconditioner(LinearOperator):
    """M^-1 r = p(A) r with p the ``degree``-term Chebyshev approximation
    of A^-1 on [lmin, lmax] (p has polynomial degree ``degree - 1``).

    Classic three-term Chebyshev semi-iteration for ``A z = r`` from z0 = 0
    (Saad, *Iterative Methods for Sparse Linear Systems*, Alg. 12.1), run
    for ``degree`` steps, with the iterate z a fixed polynomial in A times
    r - hence symmetric, and positive definite when [lmin, lmax] covers
    the spectrum.  ``degree=1`` is the single-term p(A) = I/theta
    (Richardson scaling); each application costs ``degree - 1`` matvecs
    and no reductions.  On a mesh the application inherits whatever
    communication the operator's matvec does: for the halo-exchange
    stencil operators that is ppermutes only - NO extra psums per CG
    iteration - but for ``DistCSR`` each matvec all-gathers x, so the
    polynomial repeats that O(n)-volume collective degree - 1 times;
    prefer low degrees (or jacobi) for distributed general CSR.

    Use ``from_operator`` for automatic bounds: lmax by power iteration,
    ``lmin = lmax / ratio``.  The smaller the ratio, the stronger (and
    costlier per application) the preconditioner; 30 is the common
    smoother convention and a good CG default.
    """

    a: LinearOperator      # the operator being preconditioned (pytree)
    lmin: jax.Array        # 0-d device scalars: traced, sweeps don't
    lmax: jax.Array        # recompile
    degree: int = 4

    @classmethod
    def from_operator(
        cls,
        a: LinearOperator,
        *,
        degree: int = 4,
        ratio: float = 30.0,
        lmax: Optional[float] = None,
        lmin: Optional[float] = None,
        axis_name: Optional[str] = None,
        power_iters: int = 30,
    ) -> "ChebyshevPreconditioner":
        if degree < 1:
            raise ValueError(f"degree must be >= 1, got {degree}")
        dtype = a.dtype
        lmax_v = (estimate_lmax(a, iters=power_iters, axis_name=axis_name)
                  if lmax is None else jnp.asarray(lmax, dtype))
        lmin_v = (lmax_v / ratio if lmin is None
                  else jnp.asarray(lmin, dtype))
        return cls(a=a, lmin=lmin_v, lmax=lmax_v, degree=degree)

    @property
    def shape(self) -> Tuple[int, int]:
        return self.a.shape

    @property
    def dtype(self):
        return self.a.dtype

    def matvec(self, r):
        theta = (self.lmax + self.lmin) / 2    # interval center
        delta = (self.lmax - self.lmin) / 2    # interval half-width
        sigma = theta / delta
        rho = 1.0 / sigma
        d = r / theta
        z = d
        # degree is static and small: a Python loop unrolls into the jitted
        # body and XLA fuses each step's vector work around its matvec.
        for _ in range(self.degree - 1):
            rho_new = 1.0 / (2.0 * sigma - rho)
            d = rho_new * rho * d + (2.0 * rho_new / delta) * (r - self.a @ z)
            z = z + d
            rho = rho_new
        return z

    def diagonal(self):
        raise NotImplementedError(
            "polynomial preconditioner has no cheap explicit diagonal")


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("inv_blocks",),
    meta_fields=("dim",),
)
@dataclasses.dataclass(frozen=True)
class BlockJacobiPreconditioner(LinearOperator):
    """M^-1 = blockdiag(A)^-1 with dense ``(bs, bs)`` blocks.

    Application is a single batched matmul ``(n_blocks, bs, bs) @
    (n_blocks, bs)`` - MXU work, no gather, no control flow.  Block size 1
    degenerates to ``JacobiPreconditioner`` exactly (tested).

    Construction happens on host (numpy): the block diagonal of a CSR /
    dense matrix is extracted, symmetrized within each block, and each
    block is inverted by dense LU.  Trailing rows when ``bs`` does not
    divide n are handled by padding with identity.
    """

    inv_blocks: jax.Array  # (n_blocks, bs, bs)
    dim: int               # unpadded dimension

    @classmethod
    def from_operator(cls, a, block_size: int = 8) -> "BlockJacobiPreconditioner":
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        n = a.shape[0]
        blocks = _extract_diag_blocks(a, block_size)
        inv = np.linalg.inv(blocks)
        # Inverting each symmetrized block keeps M^-1 symmetric; SPD of the
        # global matrix implies SPD of its principal submatrices, so the
        # inverses are SPD too.
        return cls(inv_blocks=jnp.asarray(inv), dim=n)

    @property
    def block_size(self) -> int:
        return self.inv_blocks.shape[1]

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.dim, self.dim)

    @property
    def dtype(self):
        return self.inv_blocks.dtype

    def matvec(self, x):
        bs = self.block_size
        n_blocks = self.inv_blocks.shape[0]
        pad = n_blocks * bs - self.dim
        xb = jnp.pad(x, (0, pad)).reshape(n_blocks, bs)
        yb = jnp.einsum("bij,bj->bi", self.inv_blocks, xb)
        return yb.reshape(-1)[: self.dim]

    def diagonal(self):
        d = jnp.diagonal(self.inv_blocks, axis1=1, axis2=2).reshape(-1)
        return d[: self.dim]


def _extract_diag_blocks(a, bs: int) -> np.ndarray:
    """Host-side (n_blocks, bs, bs) block diagonal of ``a``, symmetrized,
    identity-padded past row n."""
    n = a.shape[0]
    n_blocks = -(-n // bs)
    blocks = np.tile(np.eye(bs), (n_blocks, 1, 1))

    if isinstance(a, CSRMatrix):
        data = np.asarray(a.data, dtype=np.float64)
        indices = np.asarray(a.indices)
        rows = np.asarray(a.rows)
        cols = indices
        in_block = rows // bs == cols // bs
        br = rows[in_block]
        blocks[br // bs, br % bs, cols[in_block] % bs] = 0.0
        np.add.at(blocks, (br // bs, br % bs, cols[in_block] % bs),
                  data[in_block])
        # restore identity on padded tail rows (cleared only if touched -
        # they never are, since rows < n <= n_blocks*bs)
    elif hasattr(a, "to_dense") or isinstance(a, np.ndarray):
        if n > 8192 and not isinstance(a, np.ndarray):
            raise ValueError(
                f"block-Jacobi extraction from a non-CSR operator "
                f"materializes the dense matrix; n={n} is too large - "
                f"assemble a CSRMatrix instead")
        dense = np.asarray(a if isinstance(a, np.ndarray) else a.to_dense(),
                           dtype=np.float64)
        for k in range(n_blocks):
            lo, hi = k * bs, min((k + 1) * bs, n)
            blocks[k, : hi - lo, : hi - lo] = dense[lo:hi, lo:hi]
    else:
        raise TypeError(
            f"block-Jacobi extraction supports CSRMatrix or dense, got "
            f"{type(a).__name__}")

    blocks = 0.5 * (blocks + np.transpose(blocks, (0, 2, 1)))
    return blocks.astype(np.dtype(a.dtype) if hasattr(a, "dtype")
                         else np.float64)
