"""Random SPD test/benchmark matrices (BASELINE config #1).

The reference cannot generate problems at all - its only system is hardcoded
(``CUDACG.cu:94-117``).  These generators produce well-conditioned SPD
matrices with a controllable spectrum so CG iteration counts are predictable
in tests.
"""
from __future__ import annotations

import numpy as np

from .operators import CSRMatrix, DenseOperator


def random_spd_dense(n: int, *, cond: float = 100.0, seed: int = 0,
                     dtype=np.float64) -> DenseOperator:
    """Dense SPD matrix with condition number ~``cond``: A = Q diag(s) Q^T."""
    rng = np.random.default_rng(seed)
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.geomspace(1.0, cond, n)
    a = (q * s) @ q.T
    a = 0.5 * (a + a.T)  # exact symmetry
    return DenseOperator(a=_to_jax(a, dtype))


def random_spd_sparse(n: int, *, density: float = 0.01, seed: int = 0,
                      dtype=np.float64) -> CSRMatrix:
    """Sparse SPD via B + B^T + diagonal dominance shift."""
    rng = np.random.default_rng(seed)
    nnz_target = max(n, int(density * n * n))
    rows = rng.integers(0, n, nnz_target)
    cols = rng.integers(0, n, nnz_target)
    vals = rng.standard_normal(nnz_target)
    import scipy.sparse as sp

    b = sp.coo_matrix((vals, (rows, cols)), shape=(n, n)).tocsr()
    a = b + b.T
    # Diagonal dominance => SPD (Gershgorin).
    row_abs = np.asarray(np.abs(a).sum(axis=1)).ravel()
    a = a + sp.diags(row_abs + 1.0)
    a.sort_indices()
    return CSRMatrix.from_arrays(a.data.astype(np.dtype(dtype)),
                                 a.indices.astype(np.int32),
                                 a.indptr.astype(np.int32), a.shape)


def _to_jax(a: np.ndarray, dtype):
    import jax.numpy as jnp

    return jnp.asarray(a.astype(np.dtype(dtype)))
