"""Problem/operator families: the data layer (reference: hardcoded system at
``CUDACG.cu:74-117``; here: operator types + generators + loaders)."""

from . import poisson, random_spd
from .operators import (
    CSRMatrix,
    DenseOperator,
    DIAMatrix,
    ELLMatrix,
    IdentityOperator,
    JacobiPreconditioner,
    LinearOperator,
    ShiftELLMatrix,
    Stencil2D,
    Stencil3D,
)
from .multigrid import MultigridPreconditioner
from .precond import (
    BlockJacobiPreconditioner,
    ChebyshevPreconditioner,
    estimate_lmax,
)

__all__ = [
    "BlockJacobiPreconditioner",
    "CSRMatrix",
    "ChebyshevPreconditioner",
    "DIAMatrix",
    "DenseOperator",
    "ELLMatrix",
    "IdentityOperator",
    "JacobiPreconditioner",
    "LinearOperator",
    "MultigridPreconditioner",
    "ShiftELLMatrix",
    "Stencil2D",
    "Stencil3D",
    "estimate_lmax",
    "poisson",
    "random_spd",
]
