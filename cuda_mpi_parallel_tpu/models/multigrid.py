"""Geometric multigrid V-cycle preconditioner for the stencil operators.

The reference solves its system with bare CG (``CUDACG.cu:269-352``); for
the Poisson-family problems that dominate the BASELINE configs, multigrid
preconditioning changes the *algorithmic* complexity: CG alone needs
O(sqrt(cond)) ~ O(n_grid) iterations on the Laplacian, MG-preconditioned
CG needs O(1) - measured 12 -> 16 iterations from 64^2 to 512^2 at rtol
1e-8 (versus 199 -> 1500+ unpreconditioned); tests assert the
grid-independence.

TPU-first construction - every ingredient maps onto the VPU with static
shapes and no gather:

* **Hierarchy**: cell-centered 2x-per-axis coarsening.  Every level is the
  SAME matrix-free unit stencil at scale/4 per level - no assembled coarse
  matrices, no setup beyond a tuple of scales.  (Consistency: the
  transfers below have unit row sums, so on smooth fields
  ``R A_h P ~ s h^2 (-Lap) = (s/4) h_c^2 (-Lap)`` - the unit stencil at a
  quarter the scale.  Piecewise-constant transfers with the exact-Galerkin
  s/2 scaling were measured NOT grid-independent - 28/40/55 iterations at
  64/128/256 - and are not used.)
* **Transfers**: separable cell-centered bilinear interpolation
  (per-axis weights 3/4, 1/4) and its adjoint-over-2 full-weighting
  restriction (per-axis weights 1/8, 3/8, 3/8, 1/8).  Both are
  pad + reshape + fused multiply-adds: no gathers, no strided slices
  (interleaving is a stack+reshape, which XLA lowers to a relayout).
* **Smoother**: weighted Jacobi - the stencil diagonal is constant, so a
  sweep is ``z += omega/diag * (r - A z)``, one fused elementwise pass
  around the stencil matvec.  Pre- and post-sweep counts are equal, making
  the V-cycle a symmetric operator; it is positive definite because
  ``omega * lmax(D^-1 A) < 2`` (the Laplacian has lmax(D^-1 A) < 2, so
  the default omega=0.8 is safe).  Symmetry needs only R = c P^T with a
  symmetric coarse solve - it does NOT need exact Galerkin coarse
  operators - so the rediscretized hierarchy above is legitimate inside
  plain (non-flexible) CG.  Tests check SPD-ness explicitly.
* **Distributed**: the same V-cycle runs on ``DistStencil2D/3D`` local
  blocks - coarsening halves the *local* leading extent (2-cell aggregates
  never straddle a shard boundary when the local extent is even), each
  level's smoother matvec does its own ppermute halo exchange, and the
  transfers exchange one boundary plane along the partitioned axis (their
  3/4 + 1/4 stencils reach one cell across the shard edge).  When the
  local extent can no longer halve, the (tiny) residual is ``all_gather``-
  ed once and the remaining levels continue on the replicated global
  coarse grid, identically on every shard - so the distributed hierarchy
  is EXACTLY the single-device hierarchy (tests assert iteration parity),
  at the cost of one small collective per cycle at the gather level.
  ``DistStencil3DPencil`` blocks work the same way with TWO partitioned
  grid axes: transfers halo-exchange over both mesh axes and the gather
  level all_gathers over both.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .operators import LinearOperator, Stencil2D, Stencil3D

#: per-level scale factor for the rediscretized coarse operator (see
#: module docstring: unit-row-sum transfers make RAP ~ scale/4)
_COARSE_SCALE = 0.25


def _can_halve(grid, min_extent: int) -> bool:
    return not any(g % 2 or g // 2 < min_extent for g in grid)


def _level_ops(a, min_extent: int, max_levels: int):
    """Operator hierarchies by halving grid extents, finest first.

    Returns ``(ops, global_ops)``.  For single-device Stencil2D/3D,
    ``global_ops`` is empty and ``ops`` halves until an extent goes odd or
    would drop below ``min_extent``.  For DistStencil2D/3D, ``ops`` halves
    the LOCAL leading extent as far as it can; if the *global* grid can
    still coarsen past that point, ``global_ops`` continues the hierarchy
    with replicated single-device stencils (applied identically on every
    shard after one ``all_gather`` - see ``_vcycle``), so the combined
    hierarchy has exactly the single-device depth.

    Coarse levels always use ``backend="xla"``: they are far below the
    pallas HBM threshold, and the pallas kernels' tile-divisibility
    constraints do not generally survive halving.
    """
    from ..parallel.operators import (
        DistStencil2D,
        DistStencil3D,
        DistStencil3DPencil,
    )

    def _replicated(scale, ggrid, dtype_name, budget):
        """Replicated single-device continuation of a distributed
        hierarchy, starting one level BELOW the global grid ``ggrid``."""
        if budget <= 0 or not _can_halve(ggrid, min_extent):
            return ()
        cls2 = Stencil2D if len(ggrid) == 2 else Stencil3D
        out = [cls2(scale=scale * _COARSE_SCALE,
                    grid=tuple(g // 2 for g in ggrid),
                    backend="xla", _dtype_name=dtype_name)]
        while len(out) < budget and _can_halve(out[-1].grid, min_extent):
            prev = out[-1]
            out.append(dataclasses.replace(
                prev, scale=prev.scale * _COARSE_SCALE,
                grid=tuple(g // 2 for g in prev.grid)))
        return tuple(out)

    ops = [a]
    global_ops = ()
    while len(ops) + len(global_ops) < max_levels:
        op = ops[-1]
        if isinstance(op, (Stencil2D, Stencil3D)):
            if not _can_halve(op.grid, min_extent):
                break
            coarse = dataclasses.replace(
                op, scale=op.scale * _COARSE_SCALE,
                grid=tuple(g // 2 for g in op.grid), backend="xla")
        elif isinstance(op, (DistStencil2D, DistStencil3D)):
            lg = op.local_grid
            if _can_halve(lg, min_extent):
                coarse = dataclasses.replace(
                    op, scale=op.scale * _COARSE_SCALE,
                    local_grid=tuple(g // 2 for g in lg), backend="xla")
            else:
                # local extent exhausted: continue on the replicated
                # global grid if it can still coarsen
                ggrid = (lg[0] * op.n_shards,) + tuple(lg[1:])
                global_ops = _replicated(op.scale, ggrid, op._dtype_name,
                                         max_levels - len(ops))
                break
        elif isinstance(op, DistStencil3DPencil):
            lg = op.local_grid
            if _can_halve(lg, min_extent):
                coarse = dataclasses.replace(
                    op, scale=op.scale * _COARSE_SCALE,
                    local_grid=tuple(g // 2 for g in lg))
            else:
                ggrid = (lg[0] * op.shards[0], lg[1] * op.shards[1], lg[2])
                global_ops = _replicated(op.scale, ggrid, op._dtype_name,
                                         max_levels - len(ops))
                break
        else:
            raise TypeError(
                f"multigrid supports Stencil2D/3D, DistStencil2D/3D and "
                f"DistStencil3DPencil, got {type(op).__name__}")
        ops.append(coarse)
    return tuple(ops), tuple(global_ops)


def _op_grid(op) -> Tuple[int, ...]:
    return op.grid if hasattr(op, "grid") else op.local_grid


def _op_dist(op):
    """(axis_name, n_shards) for distributed stencil blocks, else None."""
    if hasattr(op, "axis_name") and getattr(op, "n_shards", 1) > 1:
        return op.axis_name, op.n_shards
    return None


def _axis_dists(op) -> Tuple:
    """Per-grid-axis ``(mesh_axis_name, n_shards) | None``: which local
    grid axes are partitioned, and over what.  Slabs partition axis 0
    only; pencils partition axes 0 and 1, each over its own mesh axis."""
    ndim = len(_op_grid(op))
    if hasattr(op, "axis_names"):  # DistStencil3DPencil
        return ((op.axis_names[0], op.shards[0]),
                (op.axis_names[1], op.shards[1])) + (None,) * (ndim - 2)
    return (_op_dist(op),) + (None,) * (ndim - 1)


def _pad_axis0(u: jax.Array, dist) -> jax.Array:
    """Pad axis 0 with one plane per side: neighbor halos when partitioned
    (``lax.ppermute``), zeros (Dirichlet) at global domain edges."""
    if dist is None:
        return jnp.pad(u, [(1, 1)] + [(0, 0)] * (u.ndim - 1))
    from ..parallel.halo import exchange_halo

    axis_name, n_shards = dist
    lo, hi = exchange_halo(u, axis_name, n_shards)
    return jnp.concatenate([lo, u, hi], axis=0)


def _p1d(c: jax.Array, axis: int, dist=None) -> jax.Array:
    """Cell-centered bilinear prolongation along ``axis``: nc -> 2nc.

    Fine cell 2I gets 3/4 c(I) + 1/4 c(I-1); fine cell 2I+1 gets
    3/4 c(I) + 1/4 c(I+1); out-of-range neighbors are zero (Dirichlet)
    or the neighbor shard's plane (when ``dist`` names the mesh axis
    this grid axis is partitioned over).
    """
    cm = jnp.moveaxis(c, axis, 0)
    pad = _pad_axis0(cm, dist)
    even = 0.75 * cm + 0.25 * pad[:-2]
    odd = 0.75 * cm + 0.25 * pad[2:]
    out = jnp.stack([even, odd], axis=1).reshape((-1,) + cm.shape[1:])
    return jnp.moveaxis(out, 0, axis)


def _r1d(f: jax.Array, axis: int, dist=None) -> jax.Array:
    """Full-weighting restriction along ``axis`` (adjoint of ``_p1d``
    over 2): coarse I gets 3/8 (f(2I) + f(2I+1)) + 1/8 (f(2I-1) + f(2I+2)).
    """
    fm = jnp.moveaxis(f, axis, 0)
    n2 = fm.shape[0]
    pad = _pad_axis0(fm, dist)
    pairs = fm.reshape((n2 // 2, 2) + fm.shape[1:])
    left = pad[:-2].reshape((n2 // 2, 2) + fm.shape[1:])[:, 0]   # f(2I-1)
    right = pad[2:].reshape((n2 // 2, 2) + fm.shape[1:])[:, 1]   # f(2I+2)
    out = 0.375 * (pairs[:, 0] + pairs[:, 1]) + 0.125 * (left + right)
    return jnp.moveaxis(out, 0, axis)


def _restrict(r: jax.Array, fine_grid, dists=None) -> jax.Array:
    f = r.reshape(fine_grid)
    for ax in range(len(fine_grid)):
        f = _r1d(f, ax, dists[ax] if dists else None)
    return f.reshape(-1)


def _prolong(e: jax.Array, fine_grid, dists=None) -> jax.Array:
    c = e.reshape(tuple(g // 2 for g in fine_grid))
    for ax in range(len(fine_grid)):
        c = _p1d(c, ax, dists[ax] if dists else None)
    return c.reshape(-1)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("ops", "global_ops"),
    meta_fields=("omega", "pre_sweeps", "post_sweeps", "coarse_sweeps"),
)
@dataclasses.dataclass(frozen=True)
class MultigridPreconditioner(LinearOperator):
    """One symmetric V(nu, nu) cycle of geometric multigrid as M^-1."""

    ops: Tuple  # level operators, finest first (pytree of stencils)
    global_ops: Tuple = ()  # replicated coarse continuation (distributed)
    omega: float = 0.8
    pre_sweeps: int = 1
    post_sweeps: int = 1
    coarse_sweeps: int = 16

    @classmethod
    def from_operator(
        cls,
        a,
        *,
        omega: float = 0.8,
        sweeps: int = 1,
        coarse_sweeps: int = 16,
        min_extent: int = 2,
        max_levels: int = 16,
    ) -> "MultigridPreconditioner":
        """Build the hierarchy from a (Dist)Stencil2D/3D operator.

        ``sweeps`` sets BOTH pre- and post-smoothing counts (they must be
        equal for symmetry, so only one knob is exposed).
        """
        ops, global_ops = _level_ops(a, min_extent, max_levels)
        return cls(ops=ops, global_ops=global_ops, omega=omega,
                   pre_sweeps=sweeps, post_sweeps=sweeps,
                   coarse_sweeps=coarse_sweeps)

    @property
    def n_levels(self) -> int:
        return len(self.ops) + len(self.global_ops)

    @property
    def shape(self):
        return self.ops[0].shape

    @property
    def dtype(self):
        return self.ops[0].dtype

    def matvec(self, r):
        return self._vcycle(0, r)

    def _smooth(self, op, z, r, sweeps: int):
        inv_diag = 1.0 / op.diagonal()[0]  # constant-diagonal stencils
        w = jnp.asarray(self.omega, r.dtype) * inv_diag
        for _ in range(sweeps):
            z = z + w * (r - op @ z)
        return z

    def _vcycle(self, level: int, r, ops=None):
        ops = self.ops if ops is None else ops
        op = ops[level]
        last = level == len(ops) - 1
        if last and ops is self.ops and self.global_ops:
            # Distributed gather level: the local extent cannot halve
            # further, but the global grid can.  Smooth locally, then
            # all_gather the residual (the grid here is tiny - this is
            # O(coarse n) over ICI once per cycle) and continue the exact
            # single-device hierarchy, replicated on every shard.
            return self._gather_level(op, r)
        if last:
            # Coarsest level: omega-Jacobi iterations from z0 = 0 - a fixed
            # symmetric polynomial in A (keeps the whole cycle symmetric,
            # unlike an inner CG solve which would vary with r).
            return self._smooth(op, jnp.zeros_like(r), r,
                                self.coarse_sweeps)
        grid = _op_grid(op)
        dists = _axis_dists(op)
        # pre-smooth from zero initial guess
        z = self._smooth(op, jnp.zeros_like(r), r, self.pre_sweeps)
        # coarse-grid correction on the residual
        rc = _restrict(r - op @ z, grid, dists)
        ec = self._vcycle(level + 1, rc, ops)
        z = z + _prolong(ec, grid, dists)
        # post-smooth
        return self._smooth(op, z, r, self.post_sweeps)

    def _gather_level(self, op, r):
        """Gather level, generic over the partitioned grid axes: one
        tiled ``all_gather`` per partitioned axis reassembles the (tiny)
        global residual, the replicated hierarchy continues identically
        on every shard, and each shard slices its own block back out of
        the prolonged correction.  Covers slabs (one axis) and pencils
        (two) with the same code path."""
        from jax import lax

        lg = _op_grid(op)
        dists = _axis_dists(op)
        ggrid = tuple(g * (d[1] if d else 1) for g, d in zip(lg, dists))
        z = self._smooth(op, jnp.zeros_like(r), r, self.pre_sweeps)
        resid = (r - op @ z).reshape(lg)
        for ax, d in enumerate(dists):
            if d:
                resid = lax.all_gather(resid, d[0], axis=ax, tiled=True)
        rc_g = _restrict(resid.reshape(-1), ggrid)
        ec_g = self._vcycle(0, rc_g, self.global_ops)
        e_fine = _prolong(ec_g, ggrid).reshape(ggrid)
        itype = lax.axis_index(
            next(d[0] for d in dists if d)).dtype
        starts = tuple(
            lax.axis_index(d[0]) * g if d else jnp.zeros((), itype)
            for g, d in zip(lg, dists))
        e_local = lax.dynamic_slice(e_fine, starts, lg)
        z = z + e_local.reshape(-1)
        return self._smooth(op, z, r, self.post_sweeps)

    def diagonal(self):
        raise NotImplementedError(
            "multigrid preconditioner has no cheap explicit diagonal")
