"""Matrix Market I/O (the SuiteSparse path, BASELINE config #5).

The reference's only 'data loader' is 50 lines of hardcoded array literals
(``CUDACG.cu:94-117``).  Real workloads come as Matrix Market files
(thermal2, G3_circuit, parabolic_fem...); this module loads them into
``CSRMatrix`` via scipy's parser, with an optional native C++ fast path for
multi-GB files (``native/``), and validates SPD-relevant structure.
"""
from __future__ import annotations

import numpy as np

from .operators import CSRMatrix


def load_matrix_market(path: str, dtype=np.float64,
                       check_symmetric: bool = True,
                       native: bool = True) -> CSRMatrix:
    """Load a Matrix Market file as CSR.

    Symmetric-stored files are expanded to full storage (CG's SpMV wants
    the whole row).  ``check_symmetric`` verifies structural symmetry on
    general-stored files and raises on asymmetric input, because CG
    silently diverges on nonsymmetric systems (the reference would too -
    it never checks, quirk Q4).

    ``native=True`` uses the C++ parser (``native/csrtools.cpp``) when the
    library is available and the file is coordinate-format; scipy handles
    everything else.
    """
    import scipy.io
    import scipy.sparse as sp

    if native:
        from ..native import bindings

        if bindings.available():
            try:
                vals, indices, indptr, shape = bindings.mm_read(path)
            except bindings.NativeUnsupported:
                vals = None  # unsupported variant/size -> scipy fallback
            if vals is not None:
                if shape[0] != shape[1]:
                    raise ValueError(f"matrix is not square: {shape}")
                if check_symmetric:
                    _check_symmetric(
                        sp.csr_matrix((vals, indices, indptr), shape=shape))
                return CSRMatrix.from_arrays(
                    vals.astype(np.dtype(dtype)), indices, indptr, shape)

    m = scipy.io.mmread(path)
    if not sp.issparse(m):
        m = sp.csr_matrix(m)
    m = m.tocsr()
    if m.shape[0] != m.shape[1]:
        raise ValueError(f"matrix is not square: {m.shape}")
    if check_symmetric:
        _check_symmetric(m)
    m.sort_indices()
    return CSRMatrix.from_arrays(m.data.astype(np.dtype(dtype)),
                                 m.indices.astype(np.int32),
                                 m.indptr.astype(np.int32), m.shape)


def _check_symmetric(m) -> None:
    diff = abs(m - m.T)
    if diff.nnz and diff.max() > 1e-10 * max(abs(m).max(), 1.0):
        raise ValueError(
            "matrix is not symmetric; CG requires a symmetric operator")


def save_matrix_market(path: str, a: CSRMatrix) -> None:
    import scipy.io
    import scipy.sparse as sp

    m = sp.csr_matrix(
        (np.asarray(a.data), np.asarray(a.indices), np.asarray(a.indptr)),
        shape=a.shape)
    scipy.io.mmwrite(path, m)
