"""Unstructured-FEM-like SPD generators: SuiteSparse stand-ins.

BASELINE config #5 names SuiteSparse matrices (thermal2 / G3_circuit /
parabolic_fem) that a zero-egress image cannot download.  This module
generates matrices with the same *character* - unstructured finite-element
Laplacians over random planar triangulations: symmetric positive definite,
irregular sparsity (5-9 nnz/row, no bandable structure until RCM), the
workload class where TPU SpMV is gather-bound and the RCM pipeline
matters.  Real .mtx files dropped into ``matrices/`` still take precedence
in ``bench.py --all``.
"""
from __future__ import annotations

import numpy as np

from .operators import CSRMatrix


def random_fem_2d(n_points: int, *, seed: int = 0,
                  dtype=np.float64) -> CSRMatrix:
    """SPD stiffness-like matrix over a random Delaunay triangulation.

    Builds the graph Laplacian of the triangulation's edge graph with
    random positive edge weights (conductances), plus a small positive
    diagonal shift - the same structure as a P1 FEM stiffness matrix for
    a heterogeneous diffusion problem with a mass/reaction term, and the
    same irregular sparsity (average degree ~6 in 2D).
    """
    from scipy.spatial import Delaunay

    rng = np.random.default_rng(seed)
    pts = rng.random((n_points, 2))
    tri = Delaunay(pts)

    # unique undirected edges of the triangulation
    s = tri.simplices
    edges = np.concatenate([s[:, [0, 1]], s[:, [1, 2]], s[:, [0, 2]]])
    edges = np.sort(edges, axis=1)
    edges = np.unique(edges, axis=0)
    i, j = edges[:, 0], edges[:, 1]

    # conductances ~ lognormal (heterogeneous medium)
    w = np.exp(rng.standard_normal(edges.shape[0]) * 0.5).astype(np.float64)

    # Laplacian: A[i,j] = -w_ij, A[i,i] = sum_j w_ij + shift
    rows = np.concatenate([i, j, i, j])
    cols = np.concatenate([j, i, i, j])
    vals = np.concatenate([-w, -w, w, w])
    shift = 1e-3
    rows = np.concatenate([rows, np.arange(n_points)])
    cols = np.concatenate([cols, np.arange(n_points)])
    vals = np.concatenate([vals, np.full(n_points, shift)])

    import scipy.sparse as sp

    m = sp.coo_matrix((vals, (rows, cols)),
                      shape=(n_points, n_points)).tocsr()
    m.sum_duplicates()
    m.sort_indices()
    m = m.astype(np.dtype(dtype))
    return CSRMatrix.from_scipy(m)
