"""Poisson problem generators (the framework's benchmark model family).

The reference ships exactly one hardcoded 3x3 system (``CUDACG.cu:74-117``);
``oracle_system()`` reproduces it bit-for-bit as the regression oracle.  The
BASELINE configs add 2D 5-point (config #2/#3) and 3D 7-point (config #4,
N=256^3) Poisson Laplacians; those are generated here both as assembled CSR
(for the generic-sparse code path) and consumed matrix-free via
``Stencil2D/3D`` (the TPU-preferred path).

All generation is host-side numpy (vectorized - no Python per-row loops, so
building the N=1M 2D system takes milliseconds).
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from .operators import CSRMatrix, Stencil2D, Stencil3D


def oracle_system(dtype=jnp.float64) -> Tuple[CSRMatrix, jnp.ndarray, np.ndarray]:
    """The reference's hardcoded system and its documented solution.

    A (symmetric, *indefinite* - eigenvalues {-0.236, 2, 4.236}, SURVEY
    quirk Q1), b, and the expected x = [0.50, 0.75, 1.00] from the comment
    at ``CUDACG.cu:74-82``.  CSR arrays match ``h_valA`` / ``h_csrRowPtrA`` /
    ``h_csrColIndA`` / ``h_b`` (``CUDACG.cu:94-117,136-140``): n=3, nnz=5,
    0-based int32 indices.
    """
    val = np.array([3.0, 2.0, 2.0, 2.0, 1.0], dtype=np.float64)
    indptr = np.array([0, 2, 3, 5], dtype=np.int32)
    indices = np.array([0, 2, 1, 0, 2], dtype=np.int32)
    b = np.array([3.5, 1.5, 2.0], dtype=np.float64)
    x_expected = np.array([0.5, 0.75, 1.0], dtype=np.float64)
    a = CSRMatrix.from_arrays(val.astype(np.dtype(dtype)), indices, indptr,
                              (3, 3))
    return a, jnp.asarray(b, dtype=dtype), x_expected


def poisson_1d_csr(n: int, scale: float = 1.0, dtype=np.float64) -> CSRMatrix:
    """Tridiagonal [-1, 2, -1] * scale (Dirichlet)."""
    rows, cols, vals = [], [], []
    idx = np.arange(n)
    rows.append(idx); cols.append(idx); vals.append(np.full(n, 2.0))
    rows.append(idx[:-1]); cols.append(idx[1:]); vals.append(np.full(n - 1, -1.0))
    rows.append(idx[1:]); cols.append(idx[:-1]); vals.append(np.full(n - 1, -1.0))
    return _coo_to_csr(np.concatenate(rows), np.concatenate(cols),
                       np.concatenate(vals) * scale, n, dtype)


def poisson_2d_csr(nx: int, ny: int, scale: float = 1.0,
                   dtype=np.float64) -> CSRMatrix:
    """Assembled 2D 5-point Laplacian (Dirichlet), row-major grid order.

    Identical matrix to ``Stencil2D(nx, ny, scale)`` - asserted by tests.
    """
    n = nx * ny
    i, j = np.meshgrid(np.arange(nx), np.arange(ny), indexing="ij")
    lin = (i * ny + j).ravel()
    i, j = i.ravel(), j.ravel()
    rows = [lin]
    cols = [lin]
    vals = [np.full(n, 4.0)]
    for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        ni, nj = i + di, j + dj
        ok = (ni >= 0) & (ni < nx) & (nj >= 0) & (nj < ny)
        rows.append(lin[ok])
        cols.append((ni * ny + nj)[ok])
        vals.append(np.full(int(ok.sum()), -1.0))
    return _coo_to_csr(np.concatenate(rows), np.concatenate(cols),
                       np.concatenate(vals) * scale, n, dtype)


def poisson_3d_csr(nx: int, ny: int, nz: int, scale: float = 1.0,
                   dtype=np.float64) -> CSRMatrix:
    """Assembled 3D 7-point Laplacian (Dirichlet), row-major grid order."""
    n = nx * ny * nz
    i, j, k = np.meshgrid(np.arange(nx), np.arange(ny), np.arange(nz),
                          indexing="ij")
    lin = ((i * ny + j) * nz + k).ravel()
    i, j, k = i.ravel(), j.ravel(), k.ravel()
    rows = [lin]
    cols = [lin]
    vals = [np.full(n, 6.0)]
    for di, dj, dk in ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0),
                       (0, 0, -1), (0, 0, 1)):
        ni, nj, nk = i + di, j + dj, k + dk
        ok = ((ni >= 0) & (ni < nx) & (nj >= 0) & (nj < ny)
              & (nk >= 0) & (nk < nz))
        rows.append(lin[ok])
        cols.append(((ni * ny + nj) * nz + nk)[ok])
        vals.append(np.full(int(ok.sum()), -1.0))
    return _coo_to_csr(np.concatenate(rows), np.concatenate(cols),
                       np.concatenate(vals) * scale, n, dtype)


def poisson_2d_operator(nx: int, ny: int, scale: float = 1.0,
                        dtype=jnp.float32, backend: str = "xla") -> Stencil2D:
    return Stencil2D.create(nx, ny, scale=scale, dtype=dtype,
                            backend=backend)


def poisson_3d_operator(nx: int, ny: int, nz: int, scale: float = 1.0,
                        dtype=jnp.float32, backend: str = "xla") -> Stencil3D:
    return Stencil3D.create(nx, ny, nz, scale=scale, dtype=dtype,
                            backend=backend)


def _coo_to_csr(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                n: int, dtype) -> CSRMatrix:
    """Canonical-CSR assembly (delegates to the shared CSRMatrix.from_coo)."""
    return CSRMatrix.from_coo(rows, cols, vals, n, dtype=dtype)
