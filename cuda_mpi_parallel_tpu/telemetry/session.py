"""``observe_solve``: one context manager that wires a solve into the
whole telemetry stack.

Composes, in one ``with`` block:

* a solve id + ``solve_start``/``solve_end`` events (:mod:`.events`);
* a ``utils.timing.Timer`` for named phase sections (build / solve /
  verify - the working version of the reference's dead ``cpuSecond``,
  ``CUDACG.cu:35-39``);
* an optional ``jax.profiler`` trace (``utils.timing.profile_trace``);
* registry metrics: solve count/outcome, iteration totals, wall-time
  histogram (:mod:`.registry`).

The context NEVER reads device values on its own - the caller decides
when the solve's results are synced by calling ``obs.finish(result)``
(typically after ``time_fn``/``block_until_ready``, which synced
already).  An unfinished scope still emits ``solve_end`` with
``status="unobserved"`` so traces have no dangling starts.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, Optional

import numpy as np

from ..utils import timing
from . import events
from .registry import REGISTRY

__all__ = ["SolveObservation", "observe_solve", "solve_metrics"]

#: cap on per-boundary check_block events for one solve: a 2000-
#: iteration history at check_every=1 must not turn the trace file
#: into a 2000-line wall; boundaries are strided to stay under this.
MAX_CHECK_BLOCK_EVENTS = 32


#: per-solve iteration histogram buckets (iteration-flavored, spanning
#: the 3-iteration oracle to capped 256^3 marathons)
ITERATION_BUCKETS = (1, 3, 10, 30, 100, 300, 1000, 3000, 10000, 30000)


def solve_metrics():
    """The registry metrics every observed solve feeds (get-or-create,
    so import order never matters)."""
    return {
        "solves": REGISTRY.counter(
            "solves_total", "solves observed, by engine and outcome",
            labelnames=("engine", "status")),
        "iterations": REGISTRY.counter(
            "solve_iterations_total", "CG iterations run, by engine",
            labelnames=("engine",)),
        "iters_hist": REGISTRY.histogram(
            "solve_iterations_per_solve",
            "iterations per observed solve, by engine",
            labelnames=("engine",), buckets=ITERATION_BUCKETS),
        "seconds": REGISTRY.histogram(
            "solve_seconds", "observed wall time per solve",
            labelnames=("engine",)),
    }


def note_breakdown(site: str, iterations: int,
                   status: str = "BREAKDOWN", **fields: Any) -> None:
    """One typed breakdown -> ``solve_fault`` event +
    ``solve_breakdowns_total`` counter.  The SINGLE definition every
    emission site shares (observe_solve's epilogue, the recovery
    layer, the serve dispatcher) - three hand-spelled copies of the
    counter would silently fork its help text on the next edit."""
    REGISTRY.counter(
        "solve_breakdowns_total",
        "solves that exited with a typed BREAKDOWN (non-finite "
        "recurrence or non-SPD preconditioner)",
        labelnames=("site",)).inc(site=site)
    events.emit("solve_fault", site=site, status=status,
                iterations=iterations, **fields)


class SolveObservation:
    """Handle yielded by :func:`observe_solve`."""

    def __init__(self, solve_id: str, label: str, engine: str,
                 check_every: int):
        self.solve_id = solve_id
        self.label = label
        self.engine = engine
        self.check_every = max(int(check_every), 1)
        self.timer = timing.Timer()
        self.result = None
        self.elapsed_s: Optional[float] = None
        self._finished = False

    def section(self, name: str, sync=None):
        """Named phase section on the observation's timer."""
        return self.timer.section(name, sync=sync)

    def finish(self, result, elapsed_s: Optional[float] = None,
               health=None, **extra: Any) -> Dict[str, Any]:
        """Record the solve's outcome.  ``result`` is a ``CGResult``
        (or the df64 adapter) whose scalars the CALLER has already
        synced - reading them here is a host conversion, not a new
        device round-trip.  ``health`` is an optional
        ``telemetry.health.SolveHealth`` (computed by the caller from
        the post-solve flight record); when given, the verdict is
        emitted as a ``solve_health`` event + gauges inside this
        solve's scope and embedded in the ``solve_end`` payload.
        Returns the ``solve_end`` payload."""
        self.result = result
        self.elapsed_s = elapsed_s
        iterations = int(result.iterations)
        status = result.status_enum().name
        metrics = solve_metrics()
        metrics["solves"].inc(engine=self.engine, status=status)
        metrics["iterations"].inc(iterations, engine=self.engine)
        metrics["iters_hist"].observe(iterations, engine=self.engine)
        if elapsed_s is not None:
            metrics["seconds"].observe(elapsed_s, engine=self.engine)

        if health is not None:
            from .health import emit_solve_health

            extra = dict(extra, health=emit_solve_health(
                health, engine=self.engine))
        self._emit_check_blocks(result, iterations)
        payload: Dict[str, Any] = dict(
            status=status,
            iterations=iterations,
            residual_norm=float(result.residual_norm),
            converged=bool(result.converged),
            label=self.label,
            engine=self.engine,
            sections={name: sec for name, sec in self.timer.sections},
            **extra,
        )
        if elapsed_s is not None:
            payload["elapsed_s"] = float(elapsed_s)
        if status == "BREAKDOWN":
            # typed fault detection lands in telemetry even when no
            # recovery wrapper ran (site is unknown here - the solver
            # only knows the recurrence went non-finite; an armed
            # FaultPlan's site rides the recovery layer's emission)
            note_breakdown("unknown", iterations, engine=self.engine)
        events.emit("solve_end", **payload)
        self._finished = True
        return payload

    def _emit_check_blocks(self, result, iterations: int) -> None:
        """Check-block stats, post-solve and host-side only: boundary
        residuals come out of the RECORDED history (``solver/cg.py``
        writes it on device during the solve), never from probing live
        device state."""
        if not events.active():
            return
        k = self.check_every
        n_blocks = -(-iterations // k) if iterations else 0
        hist = getattr(result, "residual_history", None)
        if hist is None:
            events.emit("check_block", iteration=iterations,
                        block=n_blocks, check_every=k, final=True)
            return
        hist = np.asarray(hist)
        boundaries = [min(j * k, iterations)
                      for j in range(1, n_blocks + 1)] or [0]
        stride = max(1, -(-len(boundaries) // MAX_CHECK_BLOCK_EVENTS))
        picked = boundaries[::stride]
        if boundaries[-1] not in picked:
            picked.append(boundaries[-1])
        for it in picked:
            if it < hist.shape[0] and np.isfinite(hist[it]):
                events.emit("check_block", iteration=it,
                            block=-(-it // k) if it else 0,
                            check_every=k,
                            residual_norm=float(hist[it]),
                            final=it == iterations)


@contextlib.contextmanager
def observe_solve(label: str, *, engine: str = "general",
                  check_every: int = 1,
                  profile_dir: Optional[str] = None,
                  **meta: Any) -> Iterator[SolveObservation]:
    """Observe one solve end to end.

    Usage::

        with observe_solve("poisson2d n=1024", engine="auto") as obs:
            with obs.section("build"):
                a, b = build_problem()
            with obs.section("solve"):
                elapsed, result = time_fn(lambda: solve(a, b))
            obs.finish(result, elapsed_s=elapsed)

    ``meta`` keys ride on the ``solve_start`` event.  When
    ``profile_dir`` is set, the whole block runs under a
    ``jax.profiler`` trace (Perfetto/TensorBoard dump).
    """
    sid = events.new_solve_id()
    with events.solve_scope(sid):
        events.emit("solve_start", label=label, engine=engine,
                    check_every=check_every, **meta)
        obs = SolveObservation(sid, label, engine, check_every)
        try:
            with timing.profile_trace(profile_dir):
                yield obs
        except BaseException as e:
            # the no-dangling-starts contract holds on the error path
            # too: close the solve's trace, then re-raise untouched
            if not obs._finished:
                events.emit("solve_end", status="error", iterations=0,
                            residual_norm=None, label=label,
                            engine=engine, error=type(e).__name__)
            raise
        if not obs._finished:
            events.emit("solve_end", status="unobserved", iterations=0,
                        residual_norm=None, label=label, engine=engine)
