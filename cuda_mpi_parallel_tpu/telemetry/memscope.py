"""Device-memory observatory: per-shard HBM footprint accounting.

Every other axis of the machine already has a ruler here - shardscope
counts slots and halo payloads, :mod:`.cost` derives wire bytes from
the traced program, roofline prices traffic against peak bandwidth -
but nothing could say how many bytes a solve actually *pins* per
device.  The PIM SpMV lesson (PAPERS: arXiv 2204.00900 - throughput is
sustained stream bandwidth over the RESIDENT bytes) and the
cluster-storage accounting of arXiv 1112.5588 both start from the
primitive this module supplies: an honest bytes-per-device model.

Three views of the same footprint, kept deliberately separate:

* **matrix bytes** (:func:`matrix_bytes_per_shard`) - the device
  arrays a partition actually holds for the life of a dispatcher:
  CSR/ELL slot planes at their real padded ``slots`` x itemsize,
  int32 column/row index planes, gather ``send_idx`` slabs, shift-ELL
  value/lane/chunk planes and the Jacobi diagonal (df64 doubles the
  value planes into (hi, lo)).  Computed from array SHAPES alone, so
  it is asserted to equal the summed ``.nbytes`` of the live device
  arrays EXACTLY (:func:`live_device_bytes` is the measured twin -
  same numbers, two derivations).
* **solver bytes** (:func:`solver_bytes_per_shard`) - the modeled
  solve-lifetime working set: b/x/r/p/Ap many-RHS k-wide stacks, the
  extended-x exchange buffer (full ``n_global_padded`` for allgather,
  ``n_local + halo_width`` for a gather schedule - sized from the
  ``GatherSchedule`` rounds, one rotating block for the ring),
  flight-recorder and recycling-basis rings, df64 (hi, lo) doubling.
* **transient peak** (:func:`jaxpr_peak_bytes`) - the high-water mark
  of the traced solve body from a liveness walk over its eqns
  (cost.py-style recursion into while/scan/cond/pjit): every output
  aval lives from its defining eqn to its last use, the peak over
  program points is reported, so the allgather's ``(P * n_local, k)``
  temporary is charged, not hidden.

``persistent = matrix + solver`` is what a registered operator costs
per chip while serving; ``peak`` bounds the solve-time spike.  Fit
classification against :class:`~.roofline.MachineModel.hbm_bytes`
(TPU table value, ``CUDA_MPI_PARALLEL_TPU_HBM_BYTES`` override) is
FITS / TIGHT (> ``TIGHT_FRACTION``) / OVERFLOW - or ``"unknown"``
when the model has no capacity number, which REPORTS and never
refuses.  :class:`MemoryBudgetError` is the typed refusal the planner
(``balance.plan_partition(hbm_budget=)``) and the serve tier's
``register()`` raise BEFORE any compile, naming the bytes and the
smallest mesh that fits.

Everything is host-side arithmetic over shapes the partitioners
already produced; the compiled solve is never perturbed (the jaxpr
bit-identity proof of tests/test_cost_accounting.py extends to this
layer, asserted by tests/test_memscope.py).
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "HBM_BYTES_ENV",
    "TIGHT_FRACTION",
    "MemoryBudgetError",
    "MemoryFootprint",
    "classify",
    "csr_slot_bytes",
    "device_memory_peak",
    "footprint_for_partition",
    "hbm_bytes_for",
    "jaxpr_peak_bytes",
    "last_memory_profile",
    "live_device_bytes",
    "matrix_bytes_per_shard",
    "note_footprint",
    "predict_footprint",
    "reset_last_memory_profile",
    "smallest_fitting_mesh",
    "solve_peak_bytes",
    "solver_bytes_per_shard",
]

#: environment override for the per-device HBM capacity (bytes) -
#: wins over any machine model's table/calibrated value
HBM_BYTES_ENV = "CUDA_MPI_PARALLEL_TPU_HBM_BYTES"

#: occupancy above this fraction of capacity classifies TIGHT: enough
#: headroom questions (fragmentation, XLA scratch, donation timing)
#: live in the last fifth that "fits on paper" stops being a promise
TIGHT_FRACTION = 0.8


class MemoryBudgetError(RuntimeError):
    """A partition/registration whose footprint cannot fit the budget.

    Raised BEFORE any device allocation or compile, so an over-budget
    operator fails at plan/registration time with numbers attached -
    never as an opaque OOM inside request latency.  ``required_bytes``
    is the worst-shard persistent footprint of the best (smallest)
    candidate considered, ``budget_bytes`` the per-device budget it
    exceeded, and ``smallest_fitting_mesh`` the first power-of-two
    shard count whose predicted footprint fits (``None`` when none
    does within the search bound).
    """

    def __init__(self, message: str, *, required_bytes: int,
                 budget_bytes: float, n_shards: int,
                 smallest_fitting_mesh: Optional[int] = None):
        super().__init__(message)
        self.required_bytes = int(required_bytes)
        self.budget_bytes = float(budget_bytes)
        self.n_shards = int(n_shards)
        self.smallest_fitting_mesh = smallest_fitting_mesh


def classify(peak_bytes: float,
             hbm_bytes: Optional[float]) -> str:
    """FITS / TIGHT / OVERFLOW against a per-device capacity, or
    ``"unknown"`` when no capacity is known (unknown REPORTS, never
    refuses - a pre-PR calibration file without ``hbm_bytes`` must not
    start failing registrations)."""
    if hbm_bytes is None or hbm_bytes <= 0:
        return "unknown"
    if peak_bytes > hbm_bytes:
        return "OVERFLOW"
    if peak_bytes > TIGHT_FRACTION * hbm_bytes:
        return "TIGHT"
    return "FITS"


def hbm_bytes_for(model=None, backend: Optional[str] = None
                  ) -> Optional[float]:
    """The per-device HBM capacity to classify against: the
    :data:`HBM_BYTES_ENV` override when set, else ``model.hbm_bytes``
    (the model defaults to ``roofline.machine_model(backend)``).
    ``None`` = unknown."""
    env = os.environ.get(HBM_BYTES_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            raise ValueError(
                f"{HBM_BYTES_ENV} must be a number of bytes, got "
                f"{env!r}")
    if model is None:
        from .roofline import machine_model

        model = machine_model(backend)
    return getattr(model, "hbm_bytes", None)


# ---------------------------------------------------------------------------
# the static model: matrix bytes (exact) + solver working set (modeled)

def _prod(shape) -> int:
    return int(math.prod(int(s) for s in shape))


def csr_slot_bytes(slots, itemsize: int):
    """Device bytes of ``slots`` CSR entry slots: one data value plus
    the int32 column and int32 local-row planes per slot - THE
    per-slot cost shared by the exact partition accounting below, the
    pre-build prediction, and ``shardscope``'s predicted
    ``persistent_bytes``.  Vectorizes over numpy ``slots``."""
    return slots * (int(itemsize) + 4 + 4)


def matrix_bytes_per_shard(parts) -> np.ndarray:
    """Per-shard device bytes of the arrays a partition pins for the
    life of a dispatcher - THE byte definition shared by the footprint
    model, ``shardscope.ShardReport.persistent_bytes`` and the
    dist_cg measured twin.

    Computed from array shapes and dtypes alone (never data), summing
    exactly what ``parallel.dist_cg`` ships to devices per family:

    * CSR (allgather/gather): ``data`` + int32 ``cols`` +
      int32 ``local_rows`` slot planes, plus the gather schedule's
      int32 ``send_idx`` slab per round;
    * ring CSR: the same three planes per ring step;
    * shift-ELL (f32/f64 and df64): value planes (df64: hi + lo),
      ``lane_idx``, ``chunk_blocks`` per step, plus the Jacobi
      diagonal plane(s).

    Uniform-shape padding makes every shard's share identical - the
    returned ``(n_shards,)`` vector is constant, kept per-shard so the
    report/gauge surface matches shardscope's.
    """
    from ..parallel import partition as part

    p = int(parts.n_shards)
    if isinstance(parts, part.PartitionedCSR):
        per = sum(np.asarray(x).dtype.itemsize * _prod(x.shape[1:])
                  for x in (parts.data, parts.cols, parts.local_rows))
        if parts.halo is not None:
            per += sum(
                np.asarray(r.send_idx).dtype.itemsize * r.m
                for r in parts.halo.rounds)
        return np.full(p, per, dtype=np.int64)
    if isinstance(parts, part.RingPartitionedCSR):
        per = sum(
            np.asarray(x).dtype.itemsize * _prod(x.shape[1:])
            for tup in (parts.data, parts.cols, parts.local_rows)
            for x in tup)
        return np.full(p, per, dtype=np.int64)
    if isinstance(parts, (part.RingPartitionedShiftELL,
                          part.RingPartitionedShiftELLDF64)):
        df64 = hasattr(parts, "vals_hi")
        planes = ((parts.vals_hi, parts.vals_lo) if df64
                  else (parts.vals,))
        per = sum(
            np.asarray(x).dtype.itemsize * _prod(x.shape[1:])
            for tup in planes + (parts.lane_idx, parts.chunk_blocks)
            for x in tup)
        diags = ((parts.diag_hi, parts.diag_lo) if df64
                 else (parts.diag,))
        per += sum(np.asarray(d).dtype.itemsize * _prod(d.shape[1:])
                   for d in diags)
        return np.full(p, per, dtype=np.int64)
    raise TypeError(f"no memory accounting for {type(parts).__name__}")


def solver_bytes_per_shard(*, n_local: int, n_shards: int,
                           itemsize: int, n_rhs: int = 1,
                           exchange: str = "allgather",
                           halo_width: int = 0, df64: bool = False,
                           flight_capacity: int = 0,
                           basis_m: int = 0) -> int:
    """Modeled per-shard bytes of the solve-lifetime working set.

    The recurrence carries b, x, r, p and the Ap product - five
    ``(n_local, n_rhs)`` stacks - plus the exchange's extended-x
    buffer: the full ``(n_shards * n_local, n_rhs)`` gathered stack
    for allgather, ``(n_local + halo_width, n_rhs)`` for a compiled
    gather schedule (``halo_width = GatherSchedule.halo_width``), and
    one extra rotating ``(n_local, n_rhs)`` block for the ring
    schedules.  ``df64`` doubles every vector entry into (hi, lo)
    planes.  ``flight_capacity`` rows of the (replicated) flight ring
    carry ``1 + 3 * n_rhs`` recorded columns each (``4`` single-RHS);
    ``basis_m`` recycling-basis vectors hold their local rows per
    shard.
    """
    vec = int(itemsize) * (2 if df64 else 1)
    k = max(int(n_rhs), 1)
    per = 5 * n_local * k * vec
    if exchange == "allgather":
        per += n_shards * n_local * k * vec
    elif exchange == "gather":
        per += (n_local + int(halo_width)) * k * vec
    elif exchange in ("ring", "ring-shiftell"):
        per += 2 * n_local * k * vec
    else:
        raise ValueError(f"unknown exchange {exchange!r}")
    if flight_capacity:
        cols = 4 if k == 1 else 1 + 3 * k
        per += int(flight_capacity) * cols * vec
    if basis_m:
        per += int(basis_m) * n_local * vec
    return int(per)


def _exchange_of(parts) -> Tuple[str, int]:
    """(exchange lane, gather halo width) of a built partition."""
    from ..parallel import partition as part

    if isinstance(parts, part.PartitionedCSR):
        if parts.halo is not None:
            return "gather", int(parts.halo.halo_width)
        return "allgather", 0
    if isinstance(parts, part.RingPartitionedCSR):
        return "ring", 0
    return "ring-shiftell", 0


def _kind_of(parts) -> str:
    from ..parallel import partition as part

    if isinstance(parts, part.PartitionedCSR):
        return ("csr-gather" if parts.halo is not None
                else "csr-allgather")
    if isinstance(parts, part.RingPartitionedCSR):
        return "csr-ring"
    return ("ring-shiftell-df64" if hasattr(parts, "vals_hi")
            else "ring-shiftell")


@dataclasses.dataclass(frozen=True)
class MemoryFootprint:
    """One partitioned solve's per-device memory account (JSON-ready).

    ``matrix_bytes`` is exact (shape-derived, measured-twin asserted);
    ``solver_bytes`` is the modeled working set;
    ``jaxpr_peak_bytes`` the liveness-walked transient high water of
    the traced shard program when a trace was available (it counts the
    program's inputs too, so it bounds matrix + working set + temps).
    ``hbm_bytes`` is the capacity classified against (``None`` =
    unknown).
    """

    kind: str
    n_shards: int
    n_rhs: int
    itemsize: int
    matrix_bytes: np.ndarray          # (P,) exact pinned bytes
    solver_bytes: np.ndarray          # (P,) modeled working set
    jaxpr_peak_bytes: Optional[int] = None
    hbm_bytes: Optional[float] = None

    @property
    def persistent_bytes(self) -> np.ndarray:
        """(P,) matrix + solver working set: what one registered,
        actively solving operator costs per chip."""
        return self.matrix_bytes + self.solver_bytes

    @property
    def peak_bytes(self) -> int:
        """Worst-shard high water: the jaxpr-walked peak when traced
        (it subsumes the persistent set), else the persistent model."""
        persistent = int(self.persistent_bytes.max()) \
            if self.n_shards else 0
        if self.jaxpr_peak_bytes is None:
            return persistent
        return max(int(self.jaxpr_peak_bytes), persistent)

    @property
    def classification(self) -> str:
        return classify(self.peak_bytes, self.hbm_bytes)

    @property
    def headroom_frac(self) -> Optional[float]:
        """Fraction of capacity left above the peak (negative =
        overflow); ``None`` when capacity is unknown."""
        if self.hbm_bytes is None or self.hbm_bytes <= 0:
            return None
        return 1.0 - self.peak_bytes / float(self.hbm_bytes)

    def to_json(self) -> dict:
        head = self.headroom_frac
        return {
            "kind": self.kind,
            "n_shards": self.n_shards,
            "n_rhs": self.n_rhs,
            "itemsize": self.itemsize,
            "matrix_bytes": [int(v) for v in self.matrix_bytes],
            "solver_bytes": [int(v) for v in self.solver_bytes],
            "persistent_bytes": [int(v) for v in self.persistent_bytes],
            "jaxpr_peak_bytes": (None if self.jaxpr_peak_bytes is None
                                 else int(self.jaxpr_peak_bytes)),
            "peak_bytes": int(self.peak_bytes),
            "hbm_bytes": (None if self.hbm_bytes is None
                          else float(self.hbm_bytes)),
            "headroom_frac": (None if head is None
                              else round(float(head), 6)),
            "classification": self.classification,
        }

    @classmethod
    def from_json(cls, data: dict) -> "MemoryFootprint":
        return cls(
            kind=str(data["kind"]), n_shards=int(data["n_shards"]),
            n_rhs=int(data["n_rhs"]), itemsize=int(data["itemsize"]),
            matrix_bytes=np.asarray(data["matrix_bytes"],
                                    dtype=np.int64),
            solver_bytes=np.asarray(data["solver_bytes"],
                                    dtype=np.int64),
            jaxpr_peak_bytes=(None
                              if data.get("jaxpr_peak_bytes") is None
                              else int(data["jaxpr_peak_bytes"])),
            hbm_bytes=(None if data.get("hbm_bytes") is None
                       else float(data["hbm_bytes"])))

    def describe(self) -> str:
        """The one-line footprint digest the CLI report embeds."""
        per = int(self.persistent_bytes.max()) if self.n_shards else 0
        parts = [f"{_fmt_bytes(per)}/shard persistent "
                 f"({_fmt_bytes(int(self.matrix_bytes.max()))} matrix "
                 f"+ {_fmt_bytes(int(self.solver_bytes.max()))} "
                 f"solver, k={self.n_rhs})",
                 f"peak {_fmt_bytes(self.peak_bytes)}"]
        if self.hbm_bytes is not None and self.hbm_bytes > 0:
            head = self.headroom_frac
            parts.append(
                f"{self.classification} on "
                f"{_fmt_bytes(self.hbm_bytes)} HBM "
                f"(headroom {head * 100:.1f}%)")
        else:
            parts.append("capacity unknown")
        return "; ".join(parts)


def _fmt_bytes(b: float) -> str:
    b = float(b)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024.0 or unit == "GiB":
            return (f"{b:.0f} {unit}" if unit == "B"
                    else f"{b:.2f} {unit}")
        b /= 1024.0
    return f"{b:.2f} GiB"


def footprint_for_partition(parts, *, n_rhs: int = 1,
                            flight_capacity: int = 0,
                            basis_m: int = 0,
                            jaxpr_peak: Optional[int] = None,
                            hbm_bytes: Optional[float] = "auto",
                            model=None) -> MemoryFootprint:
    """The footprint of a BUILT partition: exact matrix bytes from the
    arrays' own shapes, modeled solver working set for ``n_rhs``
    lanes.  ``hbm_bytes="auto"`` resolves capacity via
    :func:`hbm_bytes_for` (env override, then ``model``/backend
    table); pass ``None`` to classify as unknown or a number to pin
    it."""
    exchange, halo_width = _exchange_of(parts)
    df64 = hasattr(parts, "vals_hi")
    if df64:
        itemsize = 4           # (hi, lo) f32 planes; df64 doubles below
    elif hasattr(parts, "vals"):
        itemsize = np.asarray(parts.vals[0]).dtype.itemsize
    elif isinstance(parts.data, tuple):
        itemsize = np.asarray(parts.data[0]).dtype.itemsize
    else:
        itemsize = np.asarray(parts.data).dtype.itemsize
    if hbm_bytes == "auto":
        hbm_bytes = hbm_bytes_for(model)
    matrix = matrix_bytes_per_shard(parts)
    solver = solver_bytes_per_shard(
        n_local=int(parts.n_local), n_shards=int(parts.n_shards),
        itemsize=int(itemsize), n_rhs=n_rhs, exchange=exchange,
        halo_width=halo_width, df64=df64,
        flight_capacity=flight_capacity, basis_m=basis_m)
    return MemoryFootprint(
        kind=_kind_of(parts), n_shards=int(parts.n_shards),
        n_rhs=int(n_rhs), itemsize=int(itemsize),
        matrix_bytes=matrix,
        solver_bytes=np.full(int(parts.n_shards), solver,
                             dtype=np.int64),
        jaxpr_peak_bytes=jaxpr_peak, hbm_bytes=hbm_bytes)


# ---------------------------------------------------------------------------
# the pre-build prediction (planner gate, serve refusal, hbm_plan)

def predict_slots(n: int, n_shards: int, *, nnz: Optional[int] = None,
                  indptr=None, row_ranges=None) -> Tuple[int, int]:
    """``(n_local, slots)`` of the CSR partition that WOULD be built:
    the exact ``partition_csr`` slot count when ``indptr`` is given
    (max over shards of live entries + unit-diagonal padding rows),
    else the uniform-nnz estimate ``ceil(nnz / P)`` + padding (what a
    synthetic sweep like tools/hbm_plan.py prices)."""
    from .shardscope import _row_ranges as even_ranges

    if row_ranges is not None:
        from ..parallel.partition import ranges_n_local

        ranges = tuple((int(lo), int(hi)) for lo, hi in row_ranges)
        n_local = ranges_n_local(ranges)
    else:
        n_local = -(-int(n) // int(n_shards))
        ranges = even_ranges(int(n), n_local, int(n_shards))
    if indptr is not None:
        ip = np.asarray(indptr).astype(np.int64)
        counts = [int(ip[hi] - ip[lo]) + (n_local - (hi - lo))
                  for lo, hi in ranges]
        return n_local, max(max(counts), 1)
    if nnz is None:
        raise ValueError("predict_slots needs nnz= or indptr=")
    # uniform-nnz estimate: each shard holds ~nnz/P live entries; the
    # tail shard additionally pads its missing rows with unit diagonals
    tail_real = int(n) - (int(n_shards) - 1) * n_local
    pad_rows = max(n_local - max(tail_real, 0), 0)
    return n_local, max(-(-int(nnz) // int(n_shards)) + pad_rows, 1)


def predict_footprint(*, n: int, n_shards: int,
                      nnz: Optional[int] = None, indptr=None,
                      row_ranges=None, itemsize: int = 4,
                      n_rhs: int = 1, exchange: str = "allgather",
                      halo_width: int = 0, df64: bool = False,
                      flight_capacity: int = 0, basis_m: int = 0,
                      hbm_bytes: Optional[float] = "auto",
                      model=None) -> MemoryFootprint:
    """Geometry-only footprint of the CSR partition that WOULD be
    built - no partition arrays, no device work.  This is what
    ``balance.plan_partition(hbm_budget=)`` gates candidates on,
    what ``serve.register()`` refuses OVERFLOW with before any
    compile, and what tools/hbm_plan.py sweeps.

    ``indptr`` gives the exact even-split (or ``row_ranges``) slot
    count; ``nnz`` alone prices the uniform split a synthetic sweep
    assumes.  The gather lane's ``halo_width``/send slabs are unknown
    before the schedule is compiled, so predictions price the
    allgather layout unless the caller passes a measured
    ``halo_width`` - a conservative (upper-bound) extended-x charge.
    """
    n_local, slots = predict_slots(int(n), int(n_shards), nnz=nnz,
                                   indptr=indptr,
                                   row_ranges=row_ranges)
    if hbm_bytes == "auto":
        hbm_bytes = hbm_bytes_for(model)
    mat_itemsize = int(itemsize) * (2 if df64 else 1)
    per_matrix = int(csr_slot_bytes(slots, mat_itemsize))
    solver = solver_bytes_per_shard(
        n_local=n_local, n_shards=int(n_shards),
        itemsize=int(itemsize), n_rhs=n_rhs, exchange=exchange,
        halo_width=halo_width, df64=df64,
        flight_capacity=flight_capacity, basis_m=basis_m)
    p = int(n_shards)
    return MemoryFootprint(
        kind=f"predicted-csr-{exchange}", n_shards=p,
        n_rhs=int(n_rhs), itemsize=int(itemsize),
        matrix_bytes=np.full(p, per_matrix, dtype=np.int64),
        solver_bytes=np.full(p, solver, dtype=np.int64),
        jaxpr_peak_bytes=None, hbm_bytes=hbm_bytes)


def smallest_fitting_mesh(*, n: int, budget_bytes: float,
                          nnz: Optional[int] = None, indptr=None,
                          itemsize: int = 4, n_rhs: int = 1,
                          exchange: str = "allgather",
                          df64: bool = False,
                          flight_capacity: int = 0,
                          start: int = 1,
                          max_shards: int = 65536) -> Optional[int]:
    """The smallest power-of-two shard count >= ``start`` whose
    predicted worst-shard persistent footprint fits ``budget_bytes``
    (``None`` when none does by ``max_shards`` - e.g. an allgather
    extended-x that never shrinks with P)."""
    p = 1
    while p < start:
        p *= 2
    while p <= max_shards:
        fp = predict_footprint(
            n=n, n_shards=p, nnz=nnz, indptr=indptr,
            itemsize=itemsize, n_rhs=n_rhs, exchange=exchange,
            df64=df64, flight_capacity=flight_capacity,
            hbm_bytes=None)
        if int(fp.persistent_bytes.max()) <= budget_bytes:
            return p
        p *= 2
    return None


# ---------------------------------------------------------------------------
# the measured twin

def live_device_bytes(tree) -> int:
    """Summed ``.nbytes`` over every array leaf of ``tree`` (a sharded
    jax ``Array``'s ``nbytes`` is GLOBAL - all shards together)."""
    import jax

    return int(sum(int(v.nbytes) for v in jax.tree.leaves(tree)
                   if hasattr(v, "nbytes")))


def device_memory_peak() -> Optional[int]:
    """Backend-reported peak bytes in use on device 0, when the
    backend exposes ``memory_stats()`` (TPU/GPU do, CPU does not) -
    the allocator-level cross-check of the static model.  ``None``
    when unavailable."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    for key in ("peak_bytes_in_use", "bytes_in_use"):
        if key in stats:
            return int(stats[key])
    return None


# ---------------------------------------------------------------------------
# the jaxpr liveness walker (transient high water)

def _is_literal(v) -> bool:
    return hasattr(v, "val")     # core.Literal carries its value


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    itemsize = dtype.itemsize if dtype is not None else 0
    return _prod(shape) * int(itemsize)


def _inner(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _eqn_inner_jaxprs(eqn):
    name = eqn.primitive.name
    if name == "while":
        return [_inner(eqn.params["body_jaxpr"]),
                _inner(eqn.params["cond_jaxpr"])]
    if name == "scan":
        return [_inner(eqn.params["jaxpr"])]
    if name == "cond":
        return [_inner(b) for b in eqn.params["branches"]]
    out = []
    for value in eqn.params.values():
        for item in (value if isinstance(value, (tuple, list))
                     else (value,)):
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                out.append(_inner(item))
    return out


def _entry_bytes(jaxpr) -> int:
    return sum(_aval_bytes(v)
               for v in tuple(jaxpr.invars) + tuple(jaxpr.constvars))


def jaxpr_peak_bytes(jaxpr) -> int:
    """Liveness-walked high-water bytes of one jaxpr.

    Classic last-use liveness over the eqn list: inputs/consts are
    live from entry, every output aval lives from its defining eqn to
    its last reading eqn (jaxpr outvars to the end), and at each
    program point the inputs and outputs of the executing eqn coexist
    (XLA cannot free an operand before the op retires).  An eqn with
    inner jaxprs (while/scan/cond/pjit/shard_map/custom_*) charges its
    OWN recursive peak beyond its operands as a transient at that
    point - so an ``all_gather``'s ``(P * n_local, k)`` output, alive
    only inside the matvec, raises the peak without ever appearing in
    the persistent model.  The walk is abstract (shapes only): the
    traced program is never executed, same contract as
    :mod:`.cost`.
    """
    j = _inner(jaxpr)
    eqns = list(j.eqns)
    end = len(eqns)
    last_use: dict = {}
    for v in j.outvars:
        if not _is_literal(v):
            last_use[v] = end
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v) and last_use.get(v, -1) < end:
                last_use[v] = max(last_use.get(v, -1), i)
    alive: dict = {}
    for v in tuple(j.invars) + tuple(j.constvars):
        alive[v] = _aval_bytes(v)
    live = sum(alive.values())
    peak = live
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if v not in alive:
                alive[v] = _aval_bytes(v)
                live += alive[v]
        extra = 0
        for sub in _eqn_inner_jaxprs(eqn):
            extra = max(extra,
                        jaxpr_peak_bytes(sub) - _entry_bytes(sub))
        peak = max(peak, live + max(extra, 0))
        for v in list(eqn.invars) + list(eqn.outvars):
            if not _is_literal(v) and last_use.get(v, end) <= i \
                    and v in alive:
                live -= alive.pop(v)
    return int(peak)


def solve_peak_bytes(closed_jaxpr) -> int:
    """Per-SHARD transient high water of a traced distributed solve:
    when the program is one ``shard_map`` region (possibly under pjit
    wrappers), walk the region's BODY - its avals are the per-shard
    block shapes, so the result is bytes per device.  Anything else
    falls back to the whole-program walk."""
    j = _inner(closed_jaxpr)
    seen = 0
    while seen < 8:                  # descend through pjit wrappers
        eqns = [e for e in j.eqns]
        if len(eqns) != 1:
            break
        eqn = eqns[0]
        name = eqn.primitive.name
        if name == "shard_map":
            return jaxpr_peak_bytes(_inner(eqn.params["jaxpr"]))
        inner = _eqn_inner_jaxprs(eqn)
        if name in ("pjit", "jit", "custom_jvp_call",
                    "custom_vjp_call") and len(inner) >= 1:
            j = inner[0]
            seen += 1
            continue
        break
    return jaxpr_peak_bytes(j)


# ---------------------------------------------------------------------------
# emission + the CLI's pickup slot

#: the most recent (footprint, measured dict) noted by a solve path -
#: the CLI's --memory-report reads this, same pattern as
#: shardscope._LAST / dist_cg._LAST_COMM_COST
_LAST: list = [None]


def last_memory_profile() -> Optional[dict]:
    """``{"footprint": MemoryFootprint, ...}`` of the most recent
    distributed solve (``measured_bytes`` rides along when the solve
    path measured its live arrays), or ``None``.  Reset before
    dispatching the solve being attributed
    (:func:`reset_last_memory_profile`), like every other last-slot."""
    return _LAST[0]


def reset_last_memory_profile() -> None:
    _LAST[0] = None


def note_footprint(footprint: MemoryFootprint, *,
                   measured_bytes: Optional[int] = None,
                   device_peak: Optional[int] = None) -> MemoryFootprint:
    """Publish a freshly computed footprint: park it for the CLI and,
    when telemetry is active, emit a ``memory_profile`` event plus
    ``hbm_bytes_persistent/peak/headroom`` gauges.  ``measured_bytes``
    is the live-array twin (summed global ``.nbytes``); when present
    it is asserted against the matrix model EXACTLY - same numbers,
    two derivations - so drift between the model and what dist_cg
    actually ships fails loudly at the instrumentation site."""
    from .. import telemetry
    from .registry import REGISTRY

    if measured_bytes is not None:
        predicted = int(footprint.matrix_bytes.sum())
        if int(measured_bytes) != predicted:
            raise AssertionError(
                f"memscope model drift: partition arrays measure "
                f"{int(measured_bytes)} bytes on device but the "
                f"static model says {predicted} "
                f"({footprint.kind}, P={footprint.n_shards})")
    _LAST[0] = {
        "footprint": footprint,
        "measured_bytes": (None if measured_bytes is None
                           else int(measured_bytes)),
        "device_peak_bytes": (None if device_peak is None
                              else int(device_peak)),
    }
    if not telemetry.active():
        return footprint
    payload = footprint.to_json()
    payload["measured_bytes"] = (None if measured_bytes is None
                                 else int(measured_bytes))
    payload["device_peak_bytes"] = (None if device_peak is None
                                    else int(device_peak))
    telemetry.events.emit("memory_profile", **payload)
    persistent = footprint.persistent_bytes
    g_p = REGISTRY.gauge("hbm_bytes_persistent",
                         "modeled persistent device bytes per shard "
                         "(matrix + solver working set)",
                         labelnames=("kind", "shard"))
    for k in range(footprint.n_shards):
        g_p.set(float(persistent[k]), kind=footprint.kind,
                shard=str(k))
    REGISTRY.gauge("hbm_bytes_peak",
                   "worst-shard modeled high-water bytes of the most "
                   "recent distributed solve",
                   labelnames=("kind",)).set(
        float(footprint.peak_bytes), kind=footprint.kind)
    head = footprint.headroom_frac
    if head is not None:
        REGISTRY.gauge("hbm_headroom_frac",
                       "fraction of device HBM left above the "
                       "modeled peak (negative = overflow)",
                       labelnames=("kind",)).set(
            float(head), kind=footprint.kind)
    return footprint
