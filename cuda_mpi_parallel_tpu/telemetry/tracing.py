"""Causal request tracing for the serve tier: span trees per request.

The per-solve telemetry stack (flight recorder, phasetrace, comm cost)
answers "what happened inside THIS solve" - but a serve request's life
is longer than its solve: admission -> queue -> shed/defer -> DRR
dispatch -> batched solve -> retry -> breaker -> migration, scattered
across seven uncorrelated event types.  This module stitches them into
one causal tree per request:

* every ``SolverService.submit`` mints a ``trace_id`` (32 hex chars)
  and a root ``submit`` span;
* every decision along the way appends a typed child span
  (``admission``, ``queue_wait``, ``sched``, ``solve``, ``retry``,
  ``migration``, ``result``) carrying ``span_id`` / ``parent_span_id``;
* ``solve`` spans carry the real ``solve_id`` of the batch dispatch,
  so one trace joins the request view to the full solve-level
  telemetry already keyed by that id.

Spans ride the existing event stream as ``"span"`` events (schema'd in
``EVENT_SCHEMA``, GL108-checked, rotated, validated) - there is no
second sink.  Each span also carries a W3C-traceparent-shaped context
string (``00-{trace_id}-{span_id}-01``) so a future HTTP/gRPC shim can
inject/extract propagation context unchanged.

Everything here is host-side bookkeeping on plain Python scalars:
no jax import, no device values, and when no event sink is configured
``RequestTrace.span`` degenerates to an id increment - the
tracing-off serve path stays jaxpr-bit-identical (proved by
``tests/test_observatory.py::TestZeroPerturbation``).
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import events

__all__ = [
    "RequestTrace",
    "SPAN_NAMES",
    "build_forest",
    "format_traceparent",
    "new_span_id",
    "new_trace_id",
    "orphan_spans",
    "parse_traceparent",
    "render_tree",
    "span_events",
]

#: the typed span vocabulary - validate_trace.py rejects anything else
#: ("net" = the data-plane hop that carried a submit over HTTP:
#: serve.net hands its receive/parse timing to submit(net_hop=...))
SPAN_NAMES = ("submit", "net", "admission", "queue_wait", "sched",
              "solve", "retry", "migration", "result")

# id generation: W3C trace-context wants 16 random bytes / 8 random
# bytes rendered lowercase-hex.  A per-process random prefix (from
# os.urandom, once) + a monotonic counter gives collision-free ids
# without consuming entropy per span and without Date-like
# nondeterminism inside the hot path.
_PREFIX = os.urandom(8).hex()                  # 16 hex chars
_TRACE_COUNTER = itertools.count(1)
_SPAN_COUNTER = itertools.count(1)


def new_trace_id() -> str:
    """A 32-lowercase-hex W3C trace id, unique within the process."""
    return f"{_PREFIX}{next(_TRACE_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


def new_span_id() -> str:
    """A 16-lowercase-hex W3C span id, unique within the process."""
    return f"{next(_SPAN_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


def format_traceparent(trace_id: str, span_id: str) -> str:
    """The W3C ``traceparent`` header value for a span context:
    ``version-traceid-spanid-flags`` with version 00 and the sampled
    flag set (a span only exists because the sink sampled it)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str) -> Tuple[str, str]:
    """Parse a ``traceparent`` value back to ``(trace_id, span_id)``.

    Accepts exactly the shape :func:`format_traceparent` produces
    (version 00, lowercase hex, any flags byte); raises ``ValueError``
    otherwise - the shim boundary should reject malformed context
    loudly, not propagate garbage ids.
    """
    parts = header.split("-")
    if len(parts) != 4:
        raise ValueError(f"traceparent must have 4 '-' separated "
                         f"fields, got {header!r}")
    version, trace_id, span_id, flags = parts
    if version != "00":
        raise ValueError(f"unsupported traceparent version {version!r}")
    for name, value, width in (("trace_id", trace_id, 32),
                               ("span_id", span_id, 16),
                               ("flags", flags, 2)):
        if len(value) != width or value.strip("0123456789abcdef"):
            raise ValueError(f"traceparent {name} must be {width} "
                             f"lowercase hex chars, got {value!r}")
    if trace_id == "0" * 32 or span_id == "0" * 16:
        raise ValueError("traceparent ids must be non-zero")
    return trace_id, span_id


class RequestTrace:
    """One request's causal span chain, owned by its QueuedRequest.

    Holds the trace id, the root (submit) span id, and ``head`` - the
    most recent span in the causal chain, which the next span parents
    to by default.  ``span()`` emits one ``"span"`` event and advances
    the head; explicit ``parent=`` overrides the chain (e.g. a
    ``sched`` span parenting to its ``queue_wait``, a ``migration``
    span parenting to the root).  Thread-safe: submit-thread spans and
    worker-thread spans interleave under one lock.
    """

    __slots__ = ("trace_id", "root_span_id", "head", "request_id",
                 "_lock")

    def __init__(self, request_id: str,
                 trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.root_span_id: Optional[str] = None
        self.head: Optional[str] = None
        self.request_id = request_id
        self._lock = threading.Lock()

    def traceparent(self) -> str:
        """The propagation context of the current head span."""
        return format_traceparent(self.trace_id,
                                  self.head or "0" * 16)

    def span(self, name: str, *, start_s: float, duration_s: float,
             parent: Optional[str] = None, root: bool = False,
             **fields: Any) -> str:
        """Emit one span and return its span_id (the new head).

        ``root=True`` marks the submit span (parent_span_id None);
        otherwise the parent is ``parent`` if given, else the current
        head.  Extra ``fields`` ride the event (status, decision,
        solve_id, attempt, ...).
        """
        if name not in SPAN_NAMES:
            raise ValueError(f"unknown span name {name!r}; "
                             f"known: {SPAN_NAMES}")
        sid = new_span_id()
        with self._lock:
            parent_id = None if root else (parent or self.head)
            if root:
                self.root_span_id = sid
            self.head = sid
            events.emit(
                "span",
                trace_id=self.trace_id,
                span_id=sid,
                parent_span_id=parent_id,
                name=name,
                request_id=self.request_id,
                start_s=float(start_s),
                duration_s=float(max(duration_s, 0.0)),
                traceparent=format_traceparent(self.trace_id, sid),
                **fields)
        return sid


# ---------------------------------------------------------------------------
# forest analysis (tests + tools/validate_trace.py share one definition
# of "complete")

def span_events(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The ``"span"`` events of a parsed JSONL record list."""
    return [e for e in records if e.get("event") == "span"]


def build_forest(records: Iterable[Dict[str, Any]]
                 ) -> Dict[str, Dict[str, Any]]:
    """Group span events into per-trace trees.

    Returns ``{trace_id: {"root": span|None, "spans": {span_id: span},
    "children": {span_id: [span, ...]}}}``.  Purely structural - use
    :func:`orphan_spans` for the completeness verdict.
    """
    forest: Dict[str, Dict[str, Any]] = {}
    for e in span_events(records):
        tree = forest.setdefault(
            e["trace_id"], {"root": None, "spans": {}, "children": {}})
        tree["spans"][e["span_id"]] = e
        parent = e.get("parent_span_id")
        if parent is None:
            tree["root"] = e
        else:
            tree["children"].setdefault(parent, []).append(e)
    return forest


def orphan_spans(records: Iterable[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Spans NOT reachable from their trace's root submit span.

    A trace with no root makes every one of its spans an orphan.  The
    empty list is the trace-completeness acceptance: every span of
    every request hangs off the submit that minted its trace.
    """
    orphans: List[Dict[str, Any]] = []
    for tree in build_forest(records).values():
        root = tree["root"]
        if root is None:
            orphans.extend(tree["spans"].values())
            continue
        reached = {root["span_id"]}
        frontier = [root["span_id"]]
        while frontier:
            nxt = frontier.pop()
            for child in tree["children"].get(nxt, ()):
                if child["span_id"] not in reached:
                    reached.add(child["span_id"])
                    frontier.append(child["span_id"])
        orphans.extend(s for sid, s in tree["spans"].items()
                       if sid not in reached)
    return orphans


def render_tree(records: Iterable[Dict[str, Any]], trace_id: str,
                ) -> str:
    """An ASCII rendering of one trace's span tree (README / example
    output), children indented under parents in start order."""
    tree = build_forest(records).get(trace_id)
    if tree is None:
        return f"(no spans for trace {trace_id})"
    t0 = min((s["start_s"] for s in tree["spans"].values()),
             default=0.0)
    lines: List[str] = []

    def walk(span: Dict[str, Any], depth: int) -> None:
        extras = []
        for key in ("status", "decision", "solve_id", "attempt",
                    "reason"):
            if span.get(key) is not None:
                extras.append(f"{key}={span[key]}")
        suffix = f"  [{', '.join(extras)}]" if extras else ""
        lines.append(f"{'  ' * depth}{span['name']:<10} "
                     f"+{(span['start_s'] - t0) * 1e3:8.3f}ms "
                     f"{span['duration_s'] * 1e3:8.3f}ms{suffix}")
        kids = sorted(tree["children"].get(span["span_id"], ()),
                      key=lambda s: (s["start_s"], s["span_id"]))
        for kid in kids:
            walk(kid, depth + 1)

    if tree["root"] is not None:
        walk(tree["root"], 0)
    else:
        lines.append(f"(orphaned trace {trace_id}: no root span)")
    return "\n".join(lines)
