"""Analytic roofline model: achieved vs attainable, per solve.

The flight recorder says how the *iterates* behaved; :mod:`.cost` says
what the compiled program *does* per iteration.  This module closes the
last gap - how fast the hardware could have done it.  A CG iteration
is streaming-bound almost everywhere (BASELINE.md's whole derivation
of the reference estimate is bytes/iteration at HBM bandwidth), so the
classic roofline (Williams et al., CACM 2009) applies directly:

* a **machine model** - peak memory bytes/s, peak FLOP/s, and (for
  meshes) network bytes/s.  TPU-class numbers come from a static table
  (documented approximations of v5e-class parts); CPU hosts are
  **self-calibrated** with a tiny one-shot benchmark (a streaming
  triad for bytes/s, a small matmul for FLOP/s - a table would be
  meaningless across the zoo of CI hosts this repo tests on);
* a **traffic model** - FLOPs and memory bytes per iteration from the
  solver recurrence (``cost.analytic_solve_ops``: spmv/dot/axpy
  counts) and the operator's nnz, plus per-iteration communication
  payload bytes from the jaxpr-derived :class:`~.cost.SolveCost`;
* the **join** - measured wall time from ``observe_solve``'s sections
  against the model's per-iteration time bound, giving achieved-vs-
  peak efficiency %, arithmetic intensity, and a bound classification
  (memory- / compute- / communication-bound: whichever term dominates
  the model time).

Everything is host arithmetic on already-synced scalars - the solve is
never touched (same contract as the rest of the telemetry stack).
Efficiency can legitimately exceed 100% when the model is pessimistic
for a given shape (e.g. a VMEM-resident solve that never streams HBM);
the number is a *ruler*, not a grade.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional, Tuple

import numpy as np

from .cost import analytic_solve_ops

__all__ = [
    "CPU_MODEL_MAX_AGE_S",
    "DEFAULT_GATHER_SLOWDOWN",
    "MachineModel",
    "RooflineReport",
    "analyze",
    "machine_model",
    "operator_nnz",
    "solve_traffic",
]

#: Effective slowdown of per-slot sparse-gather work versus the
#: streaming bandwidth a machine model quotes (the per-entry x gather
#: is random access, 1-2 orders slower per element than a streamed
#: read on the repo's own benches).  8 is the deliberately conservative
#: table default; ``telemetry.calibrate`` replaces it with a measured
#: value.  Lives on :class:`MachineModel` so the planner
#: (``balance.plan``), this roofline and the calibrator share ONE
#: parameter set.
DEFAULT_GATHER_SLOWDOWN = 8.0

#: Documented approximations for TPU-class parts (the container's
#: target): v5e-class HBM ~819 GB/s, f32 vector/matrix mix ~2e13
#: FLOP/s sustained, ICI ~4.5e10 B/s per link.  Good to the factor the
#: roofline needs (the bound classification and tens-of-percent
#: efficiency), not a datasheet.
_TPU_MODEL = dict(name="tpu-v5e-class", mem_bytes_per_s=8.19e11,
                  flops_per_s=2.0e13, net_bytes_per_s=4.5e10,
                  hbm_bytes=16.0 * 2 ** 30, source="table")

#: Conservative fallback when the backend is unknown and calibration
#: is disabled - close to a modest server core.  No ``hbm_bytes``:
#: an unknown device's capacity stays unknown (memscope classifies
#: "unknown" and never refuses on it).
_GENERIC_MODEL = dict(name="generic", mem_bytes_per_s=1.0e10,
                      flops_per_s=5.0e9, net_bytes_per_s=1.0e9,
                      source="table")

#: Disk-cached CPU self-calibrations older than this are re-measured
#: (a week: host hardware does not drift, but kernels/libraries do).
CPU_MODEL_MAX_AGE_S = 7 * 24 * 3600.0


@dataclasses.dataclass(frozen=True)
class MachineModel:
    """Peak rates the roofline measures against.

    ``gather_slowdown`` prices per-slot sparse-gather work against the
    streaming ``mem_bytes_per_s`` (see :data:`DEFAULT_GATHER_SLOWDOWN`);
    ``created_at`` is the unix stamp of a measured (calibrated) model -
    ``None`` for timeless table entries - so reports can say how old
    the numbers that priced them are.
    """

    name: str
    mem_bytes_per_s: float
    flops_per_s: float
    net_bytes_per_s: Optional[float] = None
    source: str = "table"          # "table" | "calibrated"
    gather_slowdown: float = DEFAULT_GATHER_SLOWDOWN
    created_at: Optional[float] = None
    #: per-device memory CAPACITY in bytes (HBM on accelerators,
    #: available host RAM for the CPU self-calibration) - what
    #: ``telemetry.memscope`` classifies footprints against.  ``None``
    #: = unknown (pre-PR calibration cache entries load as None via
    #: the field-filtered ``from_json``): memscope then reports
    #: "unknown" and never refuses.
    hbm_bytes: Optional[float] = None
    #: optional per-link wire bandwidths measured by the phase profiler
    #: (``telemetry.phasetrace``): ``((ring shift, bytes/s), ...)``, one
    #: entry per profiled exchange round.  ``net_bytes_per_s`` stays the
    #: aggregate the planner prices today; the per-link entries are the
    #: measurement substrate for two-tier wire pricing (ROADMAP item 4)
    #: and ride the calibration cache so future processes see them.
    per_link: Optional[Tuple[Tuple[int, float], ...]] = None

    @property
    def ridge_flops_per_byte(self) -> float:
        """Arithmetic intensity where compute overtakes memory."""
        return self.flops_per_s / self.mem_bytes_per_s

    @property
    def age_s(self) -> Optional[float]:
        """Seconds since this model was measured (None for tables)."""
        if self.created_at is None:
            return None
        return max(time.time() - self.created_at, 0.0)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: dict) -> "MachineModel":
        if not isinstance(data, dict):
            # a truncated/hand-edited cache entry whose payload is JSON
            # but not an object must surface as the TypeError the cache
            # readers already treat as a miss, not an AttributeError
            # that escapes them and breaks every later solve
            raise TypeError(
                f"machine model JSON must be an object, got "
                f"{type(data).__name__}")
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in data.items() if k in fields}
        if kwargs.get("per_link") is not None:
            # JSON round-trips the tuple-of-pairs as nested lists;
            # restore the hashless-but-frozen tuple form
            kwargs["per_link"] = tuple(
                (int(s), float(b)) for s, b in kwargs["per_link"])
        return cls(**kwargs)


def _calibrate_cpu() -> MachineModel:
    """One-shot CPU self-benchmark: a streaming triad (3 arrays x 8 MB,
    well past L2 on anything this runs on) for bytes/s and a small f64
    matmul for FLOP/s.  Best-of-3, ~tens of ms total - cheap enough to
    run once per process, honest enough to rank against (a static table
    would be fiction across CI hosts)."""
    n = 2_000_000
    a = np.ones(n, dtype=np.float32)
    b = np.ones(n, dtype=np.float32)
    out = np.empty(n, dtype=np.float32)
    tri_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.multiply(a, 1.5, out=out)
        out += b
        tri_times.append(time.perf_counter() - t0)
    # triad traffic: read a, read b, write out (write-allocate ignored)
    mem_bps = 3 * n * 4 / max(min(tri_times), 1e-9)

    m = 384
    x = np.ones((m, m))
    mm_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        x @ x
        mm_times.append(time.perf_counter() - t0)
    flops = 2 * m ** 3 / max(min(mm_times), 1e-9)
    # network peak on a CPU "mesh" (virtual XLA host devices) is a
    # memcpy: model it as the measured stream bandwidth
    return MachineModel(name="cpu-calibrated", mem_bytes_per_s=mem_bps,
                        flops_per_s=flops, net_bytes_per_s=mem_bps,
                        source="calibrated",
                        hbm_bytes=_host_ram_bytes())


def _host_ram_bytes() -> Optional[float]:
    """Physical host RAM in bytes - the CPU backend's "device
    capacity" for memscope's fit classification (stdlib only;
    ``None`` where the sysconf keys are missing, e.g. non-POSIX)."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
    except (AttributeError, ValueError, OSError):
        return None
    if pages <= 0 or page <= 0:
        return None
    return float(pages) * float(page)


_CACHED_CPU: list = [None]


def _cpu_model(cache=None) -> MachineModel:
    """The CPU model, via the measured-artifact disk cache: a fresh
    (< :data:`CPU_MODEL_MAX_AGE_S`) entry for this host is reused
    across processes; otherwise the one-shot self-benchmark runs and
    its result is stored (best-effort - an unwritable cache dir only
    means re-measuring next process)."""
    from ..utils.tune import JsonCache, host_fingerprint

    if cache is None:
        cache = JsonCache()
    key = f"machine-model-cpu-{host_fingerprint()}"
    entry = cache.get(key, max_age_s=CPU_MODEL_MAX_AGE_S)
    if entry is not None:
        try:
            model = MachineModel.from_json(entry["payload"])
            if model.mem_bytes_per_s > 0 and model.flops_per_s > 0:
                return model
        except (TypeError, KeyError):
            pass  # malformed/old-format entry: re-measure
    model = dataclasses.replace(_calibrate_cpu(), created_at=time.time())
    try:
        cache.put(key, model.to_json(), created_at=model.created_at)
    except (OSError, ValueError):
        pass
    return model


def machine_model(backend: Optional[str] = None, *,
                  cache=None) -> MachineModel:
    """The machine model for ``backend`` (default: jax's default
    backend).  CPU models are self-calibrated at most once per process
    AND persisted in the ``utils.tune.JsonCache`` disk cache (keyed by
    host fingerprint, week-stale), so repeat processes on the same host
    reuse one measurement; ``cache`` overrides the cache location
    (tests)."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    if backend == "tpu":
        return MachineModel(**_TPU_MODEL)
    if backend == "cpu":
        if cache is not None:
            return _cpu_model(cache)
        if _CACHED_CPU[0] is None:
            _CACHED_CPU[0] = _cpu_model()
        return _CACHED_CPU[0]
    return MachineModel(**_GENERIC_MODEL)


def operator_nnz(a) -> int:
    """Live matrix entries of an operator, for the traffic model.

    Assembled formats expose ``nnz``; matrix-free stencils count their
    stencil points per row; anything else is modeled dense."""
    nnz = getattr(a, "nnz", None)
    if nnz is not None and not callable(nnz):
        return int(nnz)
    name = type(a).__name__
    n = int(a.shape[0])
    if "Stencil3D" in name or "3d" in name.lower():
        return 7 * n
    if "Stencil2D" in name:
        return 5 * n
    if hasattr(a, "local_grid"):   # distributed stencils
        return (7 if len(a.local_grid) == 3 else 5) * n
    return n * int(a.shape[1]) if len(a.shape) > 1 else n


def solve_traffic(n: int, nnz: int, itemsize: int, *,
                  method: str = "cg", preconditioned: bool = False,
                  precond_matvecs: int = 0, n_rhs: int = 1) -> dict:
    """Per-iteration FLOPs and memory bytes of a solver recurrence.

    Built on ``cost.analytic_solve_ops``'s per-iteration op counts with
    the standard per-op traffic: an SpMV is ``2 nnz`` FLOPs moving the
    matrix (value + column index per entry) plus the two vectors; a dot
    is ``2 n`` FLOPs over two read vectors; an axpy-class fused update
    is ``2 n`` FLOPs over two reads and one write.  A model, not a
    measurement - the jaxpr account (:mod:`.cost`) stays the source of
    truth for *communication*; this is the arithmetic/memory side the
    jaxpr cannot price.

    ``n_rhs > 1`` models the batched tier (``solver.many``): each
    matrix sweep's ``nnz * (itemsize + 4)`` bytes are paid ONCE and
    amortized over all lanes, while every per-lane vector term (the
    SpMM's in/out stacks, dots, axpys) scales by ``n_rhs`` - exactly
    the arXiv 2204.00900 argument for why extra RHS columns are nearly
    free on a memory-bound SpMV.  ``mem_bytes_per_rhs`` reports the
    amortized per-lane traffic."""
    ops = analytic_solve_ops(method, preconditioned=preconditioned,
                             precond_matvecs=precond_matvecs,
                             n_rhs=n_rhs)
    # one matrix sweep per spmv, n_rhs vector stacks riding it
    spmv_bytes = nnz * (itemsize + 4) + 2 * n * itemsize * n_rhs
    spmv_flops = 2 * nnz * n_rhs
    dot_bytes = 2 * n * itemsize
    axpy_bytes = 3 * n * itemsize
    flops = (ops["spmv"] * spmv_flops
             + ops["dot"] * 2 * n
             + ops["axpy"] * 2 * n)
    mem_bytes = (ops["spmv"] * spmv_bytes
                 + ops["dot"] * dot_bytes
                 + ops["axpy"] * axpy_bytes)
    return {"flops": float(flops), "mem_bytes": float(mem_bytes),
            "mem_bytes_per_rhs": float(mem_bytes) / n_rhs,
            "ops": ops}


@dataclasses.dataclass(frozen=True)
class RooflineReport:
    """One solve's roofline verdict (JSON-ready)."""

    model: MachineModel
    iterations: int
    measured_s: float
    flops_per_iteration: float
    mem_bytes_per_iteration: float
    comm_bytes_per_iteration: float
    arithmetic_intensity: float      # FLOP per memory byte
    t_mem_s: float                   # model per-iteration terms
    t_flop_s: float
    t_comm_s: float
    model_s_per_iteration: float     # max of the three terms
    measured_s_per_iteration: float
    efficiency_pct: float            # model bound / measured, x100
    bound: str                       # memory | compute | communication
    #: provenance of the pricing model: its ``source`` mirrored up so
    #: report JSON says which model priced it without digging into
    #: ``model``, and the model's age at analysis time (None = table)
    model_source: str = "table"
    model_age_s: Optional[float] = None
    #: batched-solve lane count; per-iteration traffic above is the
    #: WHOLE batch's, amortized per-lane traffic is mem/n_rhs
    n_rhs: int = 1

    @property
    def mem_bytes_per_iteration_per_rhs(self) -> float:
        """Amortized per-lane memory traffic: what one RHS pays when
        the matrix sweep is shared across the batch."""
        return self.mem_bytes_per_iteration / max(self.n_rhs, 1)

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["model"] = self.model.to_json()
        return out

    def describe(self) -> str:
        gbps = (self.mem_bytes_per_iteration
                / max(self.measured_s_per_iteration, 1e-30)) / 1e9
        return (f"{self.efficiency_pct:.1f}% of the "
                f"{self.bound}-bound roofline on {self.model.name} "
                f"({gbps:.2f} GB/s achieved vs "
                f"{self.model.mem_bytes_per_s / 1e9:.2f} peak; "
                f"arithmetic intensity "
                f"{self.arithmetic_intensity:.3f} flop/B)")


def analyze(*, n: int, nnz: int, itemsize: int, iterations: int,
            elapsed_s: float, method: str = "cg",
            preconditioned: bool = False, precond_matvecs: int = 0,
            comm_bytes_per_iteration: float = 0.0,
            model: Optional[MachineModel] = None,
            backend: Optional[str] = None,
            n_rhs: int = 1) -> RooflineReport:
    """Join the analytic traffic model with a measured solve.

    ``elapsed_s`` is the measured wall time of ``iterations``
    iterations (``observe_solve``'s solve section / ``time_fn``);
    ``comm_bytes_per_iteration`` comes from the jaxpr-derived
    ``SolveCost.per_iteration.comm_bytes`` on meshes (0 on one
    device).  Pass ``model`` explicitly for deterministic tests."""
    if model is None:
        model = machine_model(backend)
    traffic = solve_traffic(n, nnz, itemsize, method=method,
                            preconditioned=preconditioned,
                            precond_matvecs=precond_matvecs,
                            n_rhs=n_rhs)
    flops, mem_bytes = traffic["flops"], traffic["mem_bytes"]
    t_mem = mem_bytes / model.mem_bytes_per_s
    t_flop = flops / model.flops_per_s
    net = model.net_bytes_per_s or model.mem_bytes_per_s
    t_comm = float(comm_bytes_per_iteration) / net
    terms = {"memory": t_mem, "compute": t_flop, "communication": t_comm}
    bound = max(terms, key=terms.get)
    model_iter = max(terms.values())
    its = max(int(iterations), 1)
    measured_iter = max(float(elapsed_s), 1e-30) / its
    return RooflineReport(
        model=model, iterations=int(iterations),
        measured_s=float(elapsed_s),
        flops_per_iteration=flops,
        mem_bytes_per_iteration=mem_bytes,
        comm_bytes_per_iteration=float(comm_bytes_per_iteration),
        arithmetic_intensity=flops / max(mem_bytes, 1e-30),
        t_mem_s=t_mem, t_flop_s=t_flop, t_comm_s=t_comm,
        model_s_per_iteration=model_iter,
        measured_s_per_iteration=measured_iter,
        efficiency_pct=100.0 * model_iter / measured_iter,
        bound=bound, model_source=model.source,
        model_age_s=model.age_s, n_rhs=int(n_rhs))
