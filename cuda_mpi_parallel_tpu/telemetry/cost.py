"""jaxpr-derived op and communication accounting for solves.

Node-aware SpMV (PAPERS: arXiv 1612.08060) and GPGPU-cluster SpMV
scaling (arXiv 1112.5588) both show that communication VOLUME - not
flop count - governs distributed SpMV performance.  This module makes
that volume a first-class, *measured-from-the-program* quantity: walk
the traced solve's jaxpr, count the primitives that matter (``psum``,
``ppermute``, ``all_gather``, ``dot_general``) per loop trip, and sum
each collective's payload bytes from its input avals (a halo
``ppermute`` carries exactly one boundary plane of
``parallel/halo.exchange_halo``, so payload bytes ARE halo bytes).

The accounting is STATIC: a CG iteration issues the same collectives
every trip, so per-solve totals are ``per_iteration x
CGResult.iterations + setup``.  Nothing is ever inserted into the
compiled hot loop - no device-side counters, no host syncs - which is
what keeps the instrumented and uninstrumented jaxprs bit-identical
(asserted by tests) and graftlint GL105 clean by construction.

Terminology: a *loop trip* is one execution of a ``lax.while_loop``
body.  With ``check_every=1`` (the default) one trip is one CG
iteration; with ``check_every=k`` the main loop's trip is a k-iteration
block (``solver.cg._blocked_while``) and callers pass
``iterations_per_trip=k`` to normalize.
"""
from __future__ import annotations

import dataclasses
import math
from collections import Counter as _Counter
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import jax

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "EXCHANGE_PRIMITIVES",
    "OpCounts",
    "SolveCost",
    "analytic_solve_ops",
    "jaxpr_solve_cost",
    "stencil_halo_bytes_per_iteration",
    "trace_solve_cost",
]

#: primitive names whose payload moves over the interconnect
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter",
})

#: the DATA-MOVEMENT subset: collectives that relocate x/halo payloads
#: between devices (what an ``exchange=`` lane controls), as opposed to
#: the scalar reductions of the CG recurrence.  Only these contribute
#: to ``wire_bytes``.
EXCHANGE_PRIMITIVES = frozenset({
    "ppermute", "pshuffle", "all_gather", "all_to_all",
    "reduce_scatter",
})


@dataclasses.dataclass(frozen=True)
class OpCounts:
    """Primitive counts plus collective byte accounts for one region.

    Two byte semantics ride together:

    * ``comm_bytes`` - PAYLOAD bytes: the sum of each collective's
      input avals (the historical account; for a halo ``ppermute``
      this is exactly the boundary-slab size).
    * ``wire_bytes`` - per-device INTERCONNECT bytes of the
      data-movement collectives (:data:`EXCHANGE_PRIMITIVES`): what
      actually crosses links per device.  An ``all_gather`` is charged
      ``output - input`` bytes (the ring implementation lands
      ``(P-1) * n_local`` remote entries on every device - its input
      aval undercounts the wire ``P-1``-fold); a ``reduce_scatter``
      the mirror ``input - output``; a ``ppermute`` its payload (sent
      exactly once).  Scalar reductions (psum/pmax/pmin) are excluded:
      their O(bytes) allreduce wire stays visible in ``comm_bytes``,
      and keeping them out makes ``wire_bytes`` exactly the halo
      volume the exchange schedule promises - the number the gather
      lane's acceptance compares (shardscope-predicted == measured).
    """

    ops: Mapping[str, int]
    comm_bytes: int = 0
    wire_bytes: int = 0

    def get(self, name: str) -> int:
        return int(self.ops.get(name, 0))

    @property
    def psum(self) -> int:
        return self.get("psum")

    @property
    def ppermute(self) -> int:
        return self.get("ppermute")

    @property
    def all_gather(self) -> int:
        return self.get("all_gather")

    @property
    def dots(self) -> int:
        return self.get("dot_general")

    @property
    def collectives(self) -> int:
        return sum(v for k, v in self.ops.items()
                   if k in COLLECTIVE_PRIMITIVES)

    def scaled(self, factor: float) -> "OpCounts":
        """Counts scaled by ``factor`` (e.g. 1/check_every); exact
        integer results stay ints."""
        def scale(v):
            s = v * factor
            return int(s) if float(s).is_integer() else s

        return OpCounts(
            ops={k: scale(v) for k, v in self.ops.items()},
            comm_bytes=scale(self.comm_bytes),
            wire_bytes=scale(self.wire_bytes))

    def to_json(self) -> Dict[str, Any]:
        return {"ops": dict(sorted(self.ops.items())),
                "comm_bytes": self.comm_bytes,
                "wire_bytes": self.wire_bytes}


@dataclasses.dataclass(frozen=True)
class SolveCost:
    """The cost decomposition of one traced solve.

    ``per_iteration`` is the main loop's per-trip counts normalized by
    ``iterations_per_trip``; ``setup`` is everything outside loop
    bodies (init matvec/reductions, result assembly); ``loops`` holds
    the raw per-trip counts of every ``while`` encountered, outermost
    first (the main solve loop, then e.g. the ``check_every`` tail
    loop).
    """

    setup: OpCounts
    per_iteration: OpCounts
    loops: Tuple[OpCounts, ...]

    def totals(self, iterations: int) -> OpCounts:
        """Whole-solve counts for a solve that ran ``iterations``
        iterations: ``setup + iterations * per_iteration``."""
        ops = _Counter({k: int(v) for k, v in self.setup.ops.items()})
        for k, v in self.per_iteration.ops.items():
            ops[k] += v * iterations
        return OpCounts(
            ops=dict(ops),
            comm_bytes=self.setup.comm_bytes
            + self.per_iteration.comm_bytes * iterations,
            wire_bytes=self.setup.wire_bytes
            + self.per_iteration.wire_bytes * iterations)

    def to_json(self) -> Dict[str, Any]:
        return {"setup": self.setup.to_json(),
                "per_iteration": self.per_iteration.to_json(),
                "n_loops": len(self.loops)}


def _inner_jaxpr(j):
    """ClosedJaxpr | Jaxpr -> the core Jaxpr."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _aval_bytes(var) -> int:
    aval = var.aval
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    itemsize = dtype.itemsize if dtype is not None else 0
    return int(math.prod(shape)) * int(itemsize)


def _payload_bytes(eqn) -> int:
    """Bytes a collective moves per device: the sum of its input avals
    (for ``ppermute`` on a halo plane this is exactly the
    ``parallel/halo.exchange_halo`` boundary-slab size)."""
    return sum(_aval_bytes(v) for v in eqn.invars
               if hasattr(v, "aval"))


def _wire_bytes(eqn) -> int:
    """Per-device interconnect bytes of a data-movement collective
    (see ``OpCounts.wire_bytes``); 0 for anything else."""
    name = eqn.primitive.name
    if name not in EXCHANGE_PRIMITIVES:
        return 0
    inb = _payload_bytes(eqn)
    if name in ("all_gather", "reduce_scatter"):
        outb = sum(_aval_bytes(v) for v in eqn.outvars
                   if hasattr(v, "aval"))
        return max(outb - inb, 0) if name == "all_gather" \
            else max(inb - outb, 0)
    return inb


def _param_jaxprs(params: Mapping[str, Any]):
    """Every jaxpr-like value in an eqn's params (pjit/shard_map/
    custom_jvp/remat/... - anything not special-cased by the walker)."""
    for value in params.values():
        for item in (value if isinstance(value, (tuple, list)) else
                     (value,)):
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                yield _inner_jaxpr(item)


def _merge_scaled(dst: _Counter, bytes_box: List[int], src: _Counter,
                  src_bytes, mult: int) -> None:
    for k, v in src.items():
        dst[k] += v * mult
    bytes_box[0] += src_bytes[0] * mult
    bytes_box[1] += src_bytes[1] * mult


def _walk(jaxpr, counts: _Counter, bytes_box: List[int],
          loops: Optional[List[OpCounts]], mult: int) -> None:
    """Accumulate primitive counts and collective payload/wire bytes
    (``bytes_box`` is the two-slot ``[comm, wire]`` accumulator).

    ``loops`` records the per-trip counts of each TOP-LEVEL ``while``
    (outermost region only - a nested while's one-trip counts are
    already folded into its parent's trip, so recording it again would
    double-account it in setup subtraction); pass ``None`` to disable
    recording in nested regions.  Loop-carrying wrappers that are not
    themselves loops (``pjit``, ``shard_map``, ``custom_*``) keep
    recording enabled, so the main solve loop is found through any
    stack of them.
    """
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "while":
            body = _inner_jaxpr(eqn.params["body_jaxpr"])
            cond = _inner_jaxpr(eqn.params["cond_jaxpr"])
            trip_counts: _Counter = _Counter()
            trip_bytes = [0, 0]
            _walk(body, trip_counts, trip_bytes, None, 1)
            _walk(cond, trip_counts, trip_bytes, None, 1)
            if loops is not None:
                loops.append(OpCounts(ops=dict(trip_counts),
                                      comm_bytes=trip_bytes[0],
                                      wire_bytes=trip_bytes[1]))
            # Trip count is dynamic (that is the point of a while); the
            # TOTALS account one trip, and callers scale by the actual
            # iteration count via SolveCost.totals().
            _merge_scaled(counts, bytes_box, trip_counts, trip_bytes,
                          mult)
        elif name == "scan":
            length = int(eqn.params.get("length", 1))
            inner = _inner_jaxpr(eqn.params["jaxpr"])
            inner_counts: _Counter = _Counter()
            inner_bytes = [0, 0]
            _walk(inner, inner_counts, inner_bytes, None, 1)
            # static trip count: totals are exact
            _merge_scaled(counts, bytes_box, inner_counts,
                          inner_bytes, mult * length)
        elif name == "cond":
            # branches may differ (e.g. pipecg's periodic residual
            # replacement); account the WORST branch per op - a
            # conservative upper bound for communication budgeting.
            branch_counts: List[Tuple[_Counter, List[int]]] = []
            for branch in eqn.params["branches"]:
                c: _Counter = _Counter()
                bb = [0, 0]
                _walk(_inner_jaxpr(branch), c, bb, None, 1)
                branch_counts.append((c, bb))
            worst: _Counter = _Counter()
            for c, _ in branch_counts:
                for k, v in c.items():
                    worst[k] = max(worst[k], v)
            worst_bytes = [
                max((bb[i] for _, bb in branch_counts), default=0)
                for i in (0, 1)]
            _merge_scaled(counts, bytes_box, worst, worst_bytes, mult)
        else:
            counts[name] += mult
            if name in COLLECTIVE_PRIMITIVES:
                bytes_box[0] += _payload_bytes(eqn) * mult
                bytes_box[1] += _wire_bytes(eqn) * mult
            for sub in _param_jaxprs(eqn.params):
                _walk(sub, counts, bytes_box, loops, mult)


def jaxpr_solve_cost(closed_jaxpr, *,
                     iterations_per_trip: int = 1) -> SolveCost:
    """Decompose a traced solve's jaxpr into setup + per-iteration costs.

    ``closed_jaxpr`` is the output of ``jax.make_jaxpr(solve_fn)(args)``
    - typically a ``shard_map``-wrapped CG body whose loop contains the
    psum/ppermute collectives of interest.  ``iterations_per_trip``
    normalizes blocked loops (``check_every=k`` -> k).
    """
    if iterations_per_trip < 1:
        raise ValueError(
            f"iterations_per_trip must be >= 1, got {iterations_per_trip}")
    totals: _Counter = _Counter()
    total_bytes = [0, 0]
    loops: List[OpCounts] = []
    _walk(_inner_jaxpr(closed_jaxpr), totals, total_bytes, loops, 1)

    if loops:
        main = loops[0]
        per_iter = main.scaled(1.0 / iterations_per_trip) \
            if iterations_per_trip > 1 else main
        # setup = totals minus the ONE trip the walker merged for each
        # top-level loop (``loops`` holds exactly those)
        setup_ops = _Counter(totals)
        for loop in loops:
            for k, v in loop.ops.items():
                setup_ops[k] -= v
        setup = OpCounts(
            ops={k: v for k, v in setup_ops.items() if v},
            comm_bytes=total_bytes[0] - sum(l.comm_bytes for l in loops),
            wire_bytes=total_bytes[1] - sum(l.wire_bytes for l in loops))
    else:
        main = OpCounts(ops={})
        per_iter = main
        setup = OpCounts(ops=dict(totals), comm_bytes=total_bytes[0],
                         wire_bytes=total_bytes[1])
    return SolveCost(setup=setup, per_iteration=per_iter,
                     loops=tuple(loops))


def trace_solve_cost(fn: Callable, *args,
                     iterations_per_trip: int = 1,
                     **kwargs) -> SolveCost:
    """Trace ``fn(*args, **kwargs)`` (no execution, no compile) and
    return its :class:`SolveCost`.  The trace is the same abstract
    evaluation jit performs, so the accounted program IS the program
    that runs."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return jaxpr_solve_cost(closed, iterations_per_trip=iterations_per_trip)


def stencil_halo_bytes_per_iteration(grid: Tuple[int, ...],
                                     itemsize: int,
                                     matvecs_per_iteration: int = 1) -> int:
    """Analytic per-device halo traffic of a slab-partitioned stencil.

    One matvec exchanges one boundary plane with each neighbor
    (``parallel/halo.exchange_halo``: one forward + one backward
    ``ppermute``, payload ``grid[1:]`` each).  This is the
    cross-check for the jaxpr-derived ``comm_bytes`` - tests assert
    the two agree exactly.
    """
    plane = int(math.prod(grid[1:])) if len(grid) > 1 else 1
    return 2 * plane * itemsize * matvecs_per_iteration


#: Analytic per-iteration op model of the solver recurrences, straight
#: from the implementations in ``solver/cg.py`` (and the reference's
#: loop for "cg": 1 SpMV ``CUDACG.cu:295``, 2 reductions ``:304,328``,
#: 3 vector updates ``:314,320,342-347``).  ``axpy`` counts xpby/axpy
#: class fused vector updates.
_METHOD_OPS = {
    # method -> (spmv, dots, axpy) per iteration, unpreconditioned
    "cg": (1, 2, 3),
    "cg1": (1, 2, 4),      # dots fused into ONE reduction (s = A p axpy)
    "pipecg": (1, 2, 6),   # one fused reduction; s/q/z recurrences
    "minres": (1, 2, 5),   # Lanczos + two Givens updates
    # many-RHS tier (solver.many): same recurrence shape as "cg" per
    # lane, but ONE SpMM/exchange serves every lane; block adds the
    # k x k Gram solve (ignored here - O(k^3) host-scale flops against
    # O(nnz k) sweeps)
    "batched": (1, 2, 3),
    "block": (1, 3, 3),    # P^T A P, R^T Z and the per-lane ||r||^2
}


def analytic_solve_ops(method: str = "cg",
                       preconditioned: bool = False,
                       precond_matvecs: int = 0,
                       n_rhs: int = 1) -> Dict[str, int]:
    """Per-iteration SpMV/dot/axpy model for a solver recurrence.

    ``preconditioned`` adds the extra ``r . z`` inner product and one
    preconditioner application per iteration; ``precond_matvecs`` is
    the application's own matvec count (e.g. ``degree - 1`` for a
    Chebyshev polynomial), folded into ``spmv``.

    ``n_rhs`` is the batched-solve lane count (``solver.many``): the
    ``spmv`` count stays the number of MATRIX SWEEPS per iteration
    (one SpMM serves every lane - the whole point of the tier), while
    ``dot``/``axpy`` count per-lane vector reductions/updates and so
    scale by ``n_rhs``.  The dict stays homogeneous op counts (no
    metadata keys) so generic consumers can sum/iterate it.
    """
    if method not in _METHOD_OPS:
        raise ValueError(f"unknown method {method!r}; expected one of "
                         f"{sorted(_METHOD_OPS)}")
    if n_rhs < 1:
        raise ValueError(f"n_rhs must be >= 1, got {n_rhs}")
    spmv, dots, axpy = _METHOD_OPS[method]
    if preconditioned:
        dots += 1
        spmv += precond_matvecs
    return {"spmv": spmv, "dot": dots * n_rhs, "axpy": axpy * n_rhs}
