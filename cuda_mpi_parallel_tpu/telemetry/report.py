"""The unified solve report + Perfetto timeline exporter.

PRs 2-4 produce four telemetry streams for one solve - the JSONL event
trace, the flight record / health verdict, the per-shard profile
(:mod:`.shardscope`) and the roofline join (:mod:`.roofline`).  This
module fuses them into the two artifacts a human actually opens:

* :class:`SolveReport` - one text (or JSON) report answering "what
  ran, how fast, which shard is the straggler, how far from the
  hardware" in a screenful;
* :func:`perfetto_trace` - a Chrome-trace/Perfetto JSON timeline
  (``chrome://tracing`` / https://ui.perfetto.dev load it directly):
  one track per shard drawing the halo / spmv / reduction phases of
  each iteration, plus one track for the host-side ``Timer`` sections
  and a residual counter track from the flight record.  The per-shard
  spans come from one of two sources, named in the trace metadata's
  ``span_source`` field: ``"measured"`` when a
  ``telemetry.phasetrace.PhaseProfile`` was passed (real per-shard
  per-phase walls - the straggler is measured, and any unexplained
  iteration time shows as an honest gap before the next iteration),
  or ``"modeled"`` (the static-schedule fallback: per-shard durations
  proportional to accounted work, the iteration slot scaled to the
  measured per-iteration wall time).

:func:`validate_perfetto` is the structural contract both the tests
and ``tools/validate_trace.py`` enforce: loadable event array,
``ph``/``ts``/``pid``/``tid`` on every event, monotone ``ts`` per
track (the tool additionally requires the ``span_source`` metadata
field on every trace this repo exports).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import sanitize

__all__ = [
    "SolveReport",
    "memory_lines",
    "perfetto_trace",
    "phase_lines",
    "service_lines",
    "validate_perfetto",
    "write_perfetto",
]

#: iterations drawn in the timeline: enough to see the steady-state
#: pattern, bounded so a 30k-iteration solve does not emit a 100 MB
#: trace.  When a solve runs longer, the drawn window is the FIRST
#: ``MAX_DRAWN_ITERATIONS`` and the truncation is recorded in the
#: trace metadata (no silent caps).
MAX_DRAWN_ITERATIONS = 64

_HOST_PID = 0
_SHARD_PID = 1
_COUNTER_PID = 2
_REQUEST_PID = 3


@dataclasses.dataclass(frozen=True)
class SolveReport:
    """Everything known about one finished solve, fused.

    All fields are optional except the record: the report renders
    whatever subset exists (a single-device solve has no shard
    profile; an engine without the recorder has no flight section).
    """

    record: Dict[str, Any]                  # utils.logging.solve_record
    shard: Optional[object] = None          # shardscope.ShardReport
    roofline: Optional[object] = None       # roofline.RooflineReport
    flight_summary: Optional[dict] = None   # FlightRecord.summary()
    health: Optional[dict] = None           # SolveHealth.to_json()
    comm: Optional[dict] = None             # CLI per-solve comm account
    #: runtime calibration & drift (telemetry.calibrate): either a
    #: SequenceResult.summary() (--repeat runs) or a bare
    #: {"drift": DriftReport.to_json()} for a single planned solve
    calibration: Optional[dict] = None
    #: solver-service replay summary (serve.SolverService.stats()):
    #: request/batch counts, occupancy, padding, latency percentiles
    service: Optional[dict] = None
    #: measured phase profile (telemetry.phasetrace
    #: PhaseProfile.to_json() payload, or the phase_profile event)
    phase: Optional[dict] = None
    #: device-memory observatory (telemetry.memscope): the
    #: MemoryFootprint.to_json() payload plus ``measured_bytes`` /
    #: ``device_peak_bytes`` when the dispatch measured its twin
    memory: Optional[dict] = None
    sections: Sequence[Tuple[str, float]] = ()

    def to_json(self) -> dict:
        out: Dict[str, Any] = {"record": dict(self.record)}
        if self.shard is not None:
            out["shard_profile"] = self.shard.to_json()
        if self.roofline is not None:
            out["roofline"] = self.roofline.to_json()
        if self.flight_summary is not None:
            out["flight"] = dict(self.flight_summary)
        if self.health is not None:
            out["health"] = dict(self.health)
        if self.comm is not None:
            out["comm"] = dict(self.comm)
        if self.calibration is not None:
            out["calibration"] = dict(self.calibration)
        if self.service is not None:
            out["service"] = dict(self.service)
        if self.phase is not None:
            out["phase_profile"] = dict(self.phase)
        if self.memory is not None:
            out["memory"] = dict(self.memory)
        if self.sections:
            out["sections"] = {name: s for name, s in self.sections}
        return sanitize(out)

    def to_text(self) -> str:
        rec = self.record
        lines: List[str] = []
        lines.append(f"== solve report: {rec.get('problem', '?')} ==")
        rnorm = rec.get("residual_norm")
        rnorm_s = f"{rnorm:.6e}" if isinstance(rnorm, (int, float)) \
            else "n/a"
        lines.append(
            f"status {rec.get('status', '?')}  "
            f"iterations {rec.get('iterations', '?')}  "
            f"||r|| {rnorm_s}")
        if rec.get("elapsed_s") is not None:
            lines.append(
                f"time {rec['elapsed_s'] * 1e3:.3f} ms  "
                f"({rec.get('iters_per_sec', 0.0):.1f} iters/s)  "
                f"device {rec.get('device', '?')} "
                f"mesh={rec.get('mesh', 1)} dtype={rec.get('dtype', '?')}")
        if self.shard is not None:
            lines.append("")
            lines.append(f"-- per-shard profile ({self.shard.kind}) --")
            lines.append(self.shard.table())
        if self.comm is not None:
            lines.append("")
            lines.append(
                f"-- communication (jaxpr-derived, per device) --")
            lines.append(
                f"{self.comm.get('psum', 0)} psum, "
                f"{self.comm.get('ppermute', 0)} ppermute, "
                f"{self.comm.get('all_gather', 0)} all_gather, "
                f"{self.comm.get('comm_bytes', 0)} payload bytes total")
            if self.comm.get("wire_bytes") is not None:
                ex = self.comm.get("exchange")
                pad = self.comm.get("halo_padding_fraction")
                lines.append(
                    f"wire: {self.comm['wire_bytes']} interconnect "
                    f"bytes total"
                    + (f", exchange={ex}" if ex else "")
                    + (f", halo padding {pad * 100:.1f}%"
                       if pad is not None else ""))
            if self.comm.get("note"):
                lines.append(f"({self.comm['note']})")
        if self.roofline is not None:
            r = self.roofline
            age = getattr(r, "model_age_s", None)
            age_s = f", measured {age / 3600:.1f}h ago" \
                if age is not None else ""
            lines.append("")
            lines.append(f"-- roofline ({r.model.name}, {r.model.source}"
                         f"{age_s}) --")
            lines.append(
                f"per-iteration model: {r.flops_per_iteration:.3g} flops, "
                f"{r.mem_bytes_per_iteration:.3g} mem B, "
                f"{r.comm_bytes_per_iteration:.3g} comm B "
                f"(intensity {r.arithmetic_intensity:.3f} flop/B)")
            lines.append(
                f"bound terms: mem {r.t_mem_s * 1e6:.3g} us, compute "
                f"{r.t_flop_s * 1e6:.3g} us, comm "
                f"{r.t_comm_s * 1e6:.3g} us -> {r.bound}-bound")
            lines.append(
                f"efficiency: {r.efficiency_pct:.1f}% of roofline "
                f"({r.model_s_per_iteration * 1e6:.3g} us model vs "
                f"{r.measured_s_per_iteration * 1e6:.3g} us measured "
                f"per iteration)")
        if self.phase is not None:
            lines.append("")
            lines.append("-- phase profile (measured) --")
            lines.extend(phase_lines(self.phase))
        if self.memory is not None:
            lines.append("")
            lines.append("-- memory (per-shard HBM accounting) --")
            lines.extend(memory_lines(self.memory))
        if self.calibration is not None:
            lines.append("")
            lines.append("-- calibration & drift --")
            lines.extend(_calibration_lines(self.calibration))
        if self.service is not None:
            lines.append("")
            lines.append("-- solver service --")
            lines.extend(service_lines(self.service))
        if self.health is not None:
            lines.append("")
            lines.append(f"-- solve health --")
            lines.append(
                f"{self.health.get('classification', '?')}: "
                f"{self.health.get('message', '')}")
        if self.flight_summary is not None:
            f = self.flight_summary
            lines.append(
                f"flight: {f.get('n_records')} records @ stride "
                f"{f.get('stride')}, decay rate {f.get('decay_rate')}")
        if self.sections:
            lines.append("")
            lines.append("-- host timer sections --")
            for name, sec in self.sections:
                lines.append(f"  {name:>12}: {sec * 1e3:9.3f} ms")
        return "\n".join(lines) + "\n"


def memory_lines(mem: Dict[str, Any]) -> List[str]:
    """Render a memscope memory profile (the ``memory_profile`` event
    payload / ``MemoryFootprint.to_json()`` plus the measured twin):
    worst-shard persistent split matrix/solver, the transient peak vs
    the device HBM, and the measured device-array bytes that anchor
    the model."""
    def fmt(v) -> str:
        if not isinstance(v, (int, float)):
            return "n/a"
        for unit, scale in (("GiB", 2 ** 30), ("MiB", 2 ** 20),
                            ("KiB", 2 ** 10)):
            if abs(v) >= scale:
                return f"{v / scale:.2f} {unit}"
        return f"{int(v)} B"

    pers = mem.get("persistent_bytes") or []
    lines = [
        f"{mem.get('kind', '?')} x {mem.get('n_shards', '?')} shards, "
        f"k={mem.get('n_rhs', 1)}: persistent "
        f"{fmt(max(pers) if pers else None)}/shard worst "
        f"(matrix {fmt(max(mem.get('matrix_bytes') or [0]))}, "
        f"solver {fmt(max(mem.get('solver_bytes') or [0]))})",
    ]
    line = f"peak {fmt(mem.get('peak_bytes'))}/shard"
    if mem.get("jaxpr_peak_bytes") is not None:
        line += f" (jaxpr transient {fmt(mem['jaxpr_peak_bytes'])})"
    cls = mem.get("classification", "unknown")
    if mem.get("hbm_bytes"):
        hr = mem.get("headroom_frac")
        line += f" vs {fmt(mem['hbm_bytes'])} HBM -> {cls}"
        if isinstance(hr, (int, float)):
            line += f" ({hr * 100:.1f}% headroom)"
    else:
        line += f" -> {cls} (device HBM size unknown)"
    lines.append(line)
    if mem.get("measured_bytes") is not None:
        line = (f"measured: {fmt(mem['measured_bytes'])} device arrays "
                f"held (== model, asserted)")
        if mem.get("device_peak_bytes") is not None:
            line += f", allocator peak {fmt(mem['device_peak_bytes'])}"
        lines.append(line)
    return lines


def service_lines(stats: Dict[str, Any]) -> List[str]:
    """Render a solver-service replay summary
    (``serve.SolverService.stats()``): request disposition, batch
    occupancy/padding, bucket usage and the latency percentiles - the
    queue-side story the per-solve sections above cannot tell."""
    def ms(v) -> str:
        return f"{v * 1e3:.3f} ms" if isinstance(v, (int, float)) \
            else "n/a"

    lines = [
        f"requests: {stats.get('submitted', 0)} submitted, "
        f"{stats.get('completed', 0)} completed "
        f"({stats.get('converged', 0)} converged, "
        f"{stats.get('timeouts', 0)} timeout, "
        f"{stats.get('errors', 0)} error)"
        + (f", {stats['rejected']} rejected (backpressure)"
           if stats.get("rejected") else "")
        + f", queue depth {stats.get('queue_depth', 0)}",
        f"batches : {stats.get('batches', 0)} dispatched, occupancy "
        f"mean {stats.get('occupancy_mean', 0.0):.2f}, padding "
        f"{stats.get('padding_fraction', 0.0) * 100:.1f}% "
        f"({stats.get('padded_lanes', 0)}/"
        f"{stats.get('lanes_dispatched', 0)} lanes)",
    ]
    buckets = stats.get("bucket_counts") or {}
    if buckets:
        lines.append("buckets : " + ", ".join(
            f"k={k}: {v}" for k, v in sorted(
                buckets.items(), key=lambda kv: int(kv[0]))))
    # the self-healing story (retry policy / circuit breaker /
    # tolerance degradation), only when any of it actually fired
    if stats.get("retries") or stats.get("refused") \
            or stats.get("degraded") or stats.get("breakers") \
            or stats.get("migrations"):
        open_b = stats.get("breakers") or {}
        lines.append(
            f"robust  : {stats.get('retries', 0)} retried, "
            f"{stats.get('refused', 0)} refused (breaker), "
            f"{stats.get('degraded', 0)} tolerance-degraded"
            + (f", {stats['migrations']} handle(s) migrated"
               if stats.get("migrations") else "")
            + (f"; breakers not closed: "
               f"{', '.join(f'{k}={v}' for k, v in sorted(open_b.items()))}"
               if open_b else ""))
    # the overload story (shed ladder / admission / per-tenant and
    # per-class disposition), only when the service actually has one
    shed = stats.get("shed")
    if shed:
        cap = shed.get("capacity_rhs_per_s")
        lines.append(
            f"shed    : level {shed.get('level', 0)} "
            f"({shed.get('name', 'ok')}), "
            f"{shed.get('transitions', 0)} transition(s), "
            f"{shed.get('deferred_flows', 0)} deferred flow(s), "
            f"{shed.get('admission_rejected', 0)} admission-rejected"
            + (f"; capacity ~{cap:.1f} RHS/s"
               if isinstance(cap, (int, float)) else ""))
    for tenant, row in sorted((stats.get("tenants") or {}).items()):
        lines.append(
            f"tenant  : {tenant}: {row.get('submitted', 0)} submitted, "
            f"{row.get('completed', 0)} completed, "
            f"{row.get('rejected', 0)} rejected, "
            f"{row.get('timeouts', 0)} timeout, "
            f"depth {row.get('depth', 0)}")
    for name, row in sorted((stats.get("classes") or {}).items()):
        target = row.get("target_latency_s")
        lines.append(
            f"class   : {name}: {row.get('submitted', 0)} submitted, "
            f"{row.get('in_slo', 0)}/{row.get('completed', 0)} in SLO"
            + (f" (target {ms(target)})"
               if isinstance(target, (int, float)) else "")
            + f", {row.get('timeouts', 0)} timeout, "
            f"{row.get('rejected', 0)} rejected, p99 "
            f"{ms(row.get('p99_s'))}")
    lat = stats.get("latency") or {}
    lines.append(
        f"latency : p50 {ms(lat.get('p50_s'))}  "
        f"p95 {ms(lat.get('p95_s'))}  p99 {ms(lat.get('p99_s'))}  "
        f"(max {ms(lat.get('max_s'))})")
    # wait-vs-solve split (queueing delay vs batched solve wall): the
    # two levers are different - wait is tuned with max_wait/max_batch,
    # solve with the operator/bucket - so the report separates them
    for key, label in (("wait", "wait    "), ("solve", "solve   ")):
        sub = stats.get(key)
        if sub:
            lines.append(
                f"{label}: p50 {ms(sub.get('p50_s'))}  "
                f"p95 {ms(sub.get('p95_s'))}  "
                f"p99 {ms(sub.get('p99_s'))}")
    if stats.get("solved_rhs_per_sec") is not None:
        lines.append(
            f"throughput: {stats['solved_rhs_per_sec']:.1f} solved "
            f"RHS/s over {stats.get('replay_window_s', 0.0):.3f} s "
            f"replay window")
    if stats.get("dist_cache_misses_postwarm") is not None:
        lines.append(
            f"zero-retrace: dist_cache_miss after warmup = "
            f"{int(stats['dist_cache_misses_postwarm'])}")
    return lines


def phase_lines(phase: Dict[str, Any]) -> List[str]:
    """Render a measured phase profile (``telemetry.phasetrace``
    ``PhaseProfile.to_json()`` payload, or the ``phase_profile`` event
    - same shape): per-phase walls, the per-shard SpMV row with its
    measured stall factor, per-link wire bandwidths, and the
    explained-fraction residual check."""
    def us(v) -> str:
        return f"{float(v) * 1e6:.1f} us" if isinstance(v, (int, float)) \
            else "n/a"

    ph = phase.get("phases") or {}
    stall = phase.get("stall_factors") or {}
    reds = int(phase.get("reductions_per_iteration", 2))
    lines = [
        f"exchange {phase.get('exchange', '?')} on "
        f"{phase.get('n_shards', '?')} shards, "
        f"{phase.get('repeats', '?')} chained reps/phase "
        f"[plan: {phase.get('plan', 'even')}]",
        f"halo {us(ph.get('halo_s'))} + spmv {us(ph.get('spmv_s'))} + "
        f"{reds} x reduction {us(ph.get('reduction_s'))} vs measured "
        f"iteration core {us(phase.get('step_s'))}",
    ]
    spmv = phase.get("spmv_s")
    if spmv:
        lines.append(
            "per-shard spmv: ["
            + ", ".join(f"{float(v) * 1e6:.1f}" for v in spmv)
            + f"] us, stall factor {float(stall.get('spmv', 1.0)):.3f}")
    for link in phase.get("links") or ():
        lines.append(
            f"link shift {link.get('shift')}: {link.get('bytes')} "
            f"B/round @ "
            f"{float(link.get('bytes_per_s', 0.0)) / 1e6:.2f} MB/s")
    ef = phase.get("explained_fraction")
    if ef is not None:
        lines.append(f"explained: phase sum = {float(ef) * 100:.1f}% "
                     f"of the measured iteration core")
    efs = phase.get("explained_fraction_vs_solve")
    if efs is not None:
        lines.append(
            f"           {float(efs) * 100:.1f}% of the solve's "
            f"measured per-iteration wall "
            f"({float(phase.get('solve_s_per_iteration', 0.0)) * 1e6:.1f}"
            f" us/iter)")
    return lines


def _calibration_lines(calib: Dict[str, Any]) -> List[str]:
    """Render the calibration/drift payload (tolerant of both shapes:
    a SequenceResult.summary() or a bare single-solve drift dict)."""
    lines: List[str] = []
    fit = calib.get("calibration")
    if isinstance(fit, dict) and isinstance(fit.get("model"), dict):
        m = fit["model"]
        net = m.get("net_bytes_per_s") or 0.0
        lines.append(
            f"model {m.get('name', '?')}: gather slowdown "
            f"x{m.get('gather_slowdown', 0.0):.2f}, net "
            f"{net / 1e9:.3f} GB/s, fit {fit.get('method', '?')} "
            f"(residual {fit.get('residual_rel', 0.0) * 100:.1f}%, "
            f"{'confident' if fit.get('confident') else 'LOW CONFIDENCE'}"
            f", {fit.get('n_observations', 0)} obs)")
    drift = calib.get("drift")
    if isinstance(drift, dict):
        lines.append(
            f"drift: model error {drift.get('drift_pct', 0.0):+.1f}% "
            f"(predicted "
            f"{drift.get('predicted_s_per_iteration', 0.0) * 1e6:.3g} "
            f"us/iter vs measured "
            f"{drift.get('measured_s_per_iteration', 0.0) * 1e6:.3g}, "
            f"model {drift.get('model', '?')}, plan "
            f"{drift.get('plan', '?')})")
    for dec in calib.get("decisions") or ():
        lines.append(
            f"replan: {dec.get('decision', '?')} for solve "
            f"{dec.get('solve_index', 0) + 1} (predicted gain "
            f"{dec.get('predicted_gain_pct', 0.0):+.1f}% on "
            f"{dec.get('model', '?')})")
    for s in calib.get("solves") or ():
        lines.append(
            f"solve {s.get('index', 0) + 1}: "
            f"{s.get('iterations', '?')} iters, "
            f"{s.get('elapsed_s', 0.0) * 1e3:.3f} ms, plan "
            f"{s.get('plan', '?')}"
            + (f" [{s['scored_by']}]" if s.get("scored_by") else ""))
    if not lines:
        lines.append("(no calibration data)")
    return lines


# ---------------------------------------------------------------------------
# Perfetto / Chrome-trace export

def _meta(pid: int, tid: int, name: str, value: str) -> dict:
    # metadata events carry ts=0 so the structural contract (every
    # event has ph/ts/pid/tid) holds for them too
    return {"ph": "M", "ts": 0, "pid": pid, "tid": tid, "name": name,
            "args": {"name": value}}


def _x(pid: int, tid: int, name: str, ts: float, dur: float,
       **args: Any) -> dict:
    ev = {"ph": "X", "ts": round(float(ts), 3),
          "dur": round(max(float(dur), 0.001), 3),
          "pid": pid, "tid": tid, "name": name}
    if args:
        ev["args"] = args
    return ev


def _shard_phase_weights(shard, k: int) -> Tuple[float, float, float]:
    """Model 'seconds' (arbitrary units) of one iteration's halo /
    spmv / reduction phases on shard ``k``, from the static per-shard
    accounting: halo time ~ payload bytes, spmv time ~ live entries
    plus padding slots (padding multiplies like real work), reduction
    a small fixed cost.  Only RATIOS matter - the iteration slot is
    rescaled to measured wall time."""
    halo = float(shard.halo_send_bytes[k] + shard.halo_recv_bytes[k])
    spmv = float(shard.slots[k]) * 12.0   # ~bytes per slot (val+idx)
    red = 0.02 * float(shard.slots.max()) * 12.0 + 1.0
    return halo, spmv, red


def perfetto_trace(*, iterations: int, elapsed_s: float,
                   shard=None, n_shards: Optional[int] = None,
                   sections: Sequence[Tuple[str, float]] = (),
                   flight_history: Optional[np.ndarray] = None,
                   phase_profile=None,
                   request_spans: Sequence[dict] = (),
                   label: str = "solve") -> dict:
    """Build the Chrome-trace JSON dict (see module docstring).

    ``iterations``/``elapsed_s``: the measured solve.  ``shard``: a
    ``shardscope.ShardReport`` (its per-shard work sizes the modeled
    phase durations); without one, ``n_shards`` uniform tracks are
    drawn.  ``phase_profile``: a ``telemetry.phasetrace.PhaseProfile``
    (or its ``to_json()`` dict) - when given, the per-shard spans are
    the MEASURED per-phase walls and the metadata carries
    ``span_source: "measured"``; otherwise the static-schedule model
    renders them (``span_source: "modeled"``).  ``sections``: host
    ``Timer.sections``.  ``flight_history``: a ``(maxiter + 1,)``
    ||r|| array (``FlightRecord.to_history``) drawn as a counter
    track.  ``request_spans``: ``"span"`` event records from a traced
    serve replay (``telemetry.tracing.span_events``) - drawn as a
    fourth process ("requests"), one thread per trace with the request
    id as the thread name, so per-request causal chains sit on the
    same timeline as the solve phases.  Timestamps are microseconds
    (the trace-event convention).
    """
    prof = None
    if phase_profile is not None:
        prof = phase_profile.to_json() \
            if hasattr(phase_profile, "to_json") else dict(phase_profile)

    events: List[dict] = []
    events.append(_meta(_HOST_PID, 0, "process_name", "host"))
    events.append(_meta(_SHARD_PID, 0, "process_name",
                        f"shards ({label})"))

    # host timer sections, laid back-to-back (the Timer records
    # durations, not start stamps; ordering is the recording order)
    t = 0.0
    for name, sec in sections:
        dur = max(float(sec), 0.0) * 1e6
        events.append(_x(_HOST_PID, 0, name, t, dur))
        t += dur

    shards = shard.n_shards if shard is not None else (n_shards or 1)
    if prof is not None:
        shards = int(prof["n_shards"])
    its = max(int(iterations), 1)
    drawn = min(its, MAX_DRAWN_ITERATIONS)
    iter_us = max(float(elapsed_s), 1e-9) * 1e6 / its

    if prof is not None:
        iter_us = _measured_shard_tracks(events, prof, iter_us, drawn)
    else:
        _modeled_shard_tracks(events, shard, shards, iter_us, drawn)

    if request_spans:
        _request_tracks(events, request_spans)

    if flight_history is not None:
        hist = np.asarray(flight_history, dtype=np.float64).reshape(-1)
        events.append(_meta(_COUNTER_PID, 0, "process_name",
                            "residual (flight record)"))
        idx = np.nonzero(np.isfinite(hist))[0]
        for i in idx:
            # same truncation as the shard tracks: a 30k-iteration
            # dense history must not blow the documented size cap
            if i > drawn:
                break
            events.append({
                "ph": "C", "ts": round(float(i) * iter_us, 3),
                "pid": _COUNTER_PID, "tid": 0, "name": "log10_residual",
                "args": {"log10_residual":
                         float(np.log10(max(hist[i], 1e-300)))}})

    metadata = {
        "label": label,
        "iterations": int(iterations),
        "drawn_iterations": int(drawn),
        "elapsed_s": float(elapsed_s),
        "truncated": bool(its > drawn),
        # the structured successor of the old free-text "not a device
        # profile" note: every exported timeline says which renderer
        # produced its per-shard spans, and tools/validate_trace.py
        # requires the field
        "span_source": "measured" if prof is not None else "modeled",
    }
    if prof is not None:
        metadata["explained_fraction"] = prof.get("explained_fraction")
        metadata["phase_exchange"] = prof.get("exchange")
    if request_spans:
        metadata["n_request_traces"] = len(
            {s.get("trace_id") for s in request_spans
             if isinstance(s, dict) and s.get("trace_id")})
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": metadata,
    }
    return sanitize(trace)


def _modeled_shard_tracks(events, shard, shards: int, iter_us: float,
                          drawn: int) -> None:
    """The static-schedule fallback renderer: per-shard durations
    proportional to accounted work, iteration slot scaled to the
    measured per-iteration wall."""
    weights = []
    for k in range(shards):
        if shard is not None:
            weights.append(_shard_phase_weights(shard, k))
        else:
            weights.append((1.0, 8.0, 1.0))
    totals = [sum(w) for w in weights]
    scale = iter_us / max(max(totals), 1e-30)

    for k in range(shards):
        events.append(_meta(_SHARD_PID, k, "thread_name", f"shard {k}"))
        halo_us, spmv_us, red_us = (w * scale for w in weights[k])
        for i in range(drawn):
            base = i * iter_us
            ts = base
            if halo_us > 0:
                events.append(_x(_SHARD_PID, k, "halo", ts, halo_us,
                                 iteration=i))
                ts += halo_us
            events.append(_x(_SHARD_PID, k, "spmv", ts, spmv_us,
                             iteration=i))
            ts += spmv_us
            # the psum barrier: every shard's iteration ends together,
            # so a balanced shard's "reduction" includes its wait on
            # the straggler - that wedge IS the imbalance cost
            events.append(_x(_SHARD_PID, k, "reduction", ts,
                             max(base + iter_us - ts, red_us),
                             iteration=i))


def _measured_shard_tracks(events, prof: dict, iter_us: float,
                           drawn: int) -> float:
    """The measured renderer: spans are the phase profiler's walls.
    Each shard's iteration draws halo (whole-mesh wall), its own
    measured SpMV seconds, and the reduction barriers; time the spans
    do not cover is left as a visible gap - unexplained iteration cost
    is a gap, never a stretched span.  Returns the iteration slot
    actually used (grown if the measured spans exceed the solve's
    per-iteration wall, so track timestamps stay monotone)."""
    ph = prof.get("phases") or {}
    reds = int(prof.get("reductions_per_iteration", 2))
    halo_us = float(ph.get("halo_s", 0.0)) * 1e6
    red_us = float(ph.get("reduction_s", 0.0)) * 1e6 * reds
    spmv_us = [float(v) * 1e6 for v in prof.get("spmv_s") or ()]
    n = int(prof["n_shards"])
    if len(spmv_us) < n:
        spmv_us += [0.0] * (n - len(spmv_us))
    span_max = max(halo_us + s + red_us for s in spmv_us)
    slot = max(iter_us, span_max)
    for k in range(n):
        events.append(_meta(_SHARD_PID, k, "thread_name", f"shard {k}"))
        for i in range(drawn):
            ts = i * slot
            if halo_us > 0:
                events.append(_x(_SHARD_PID, k, "halo", ts, halo_us,
                                 iteration=i, span_source="measured"))
                ts += halo_us
            events.append(_x(_SHARD_PID, k, "spmv", ts, spmv_us[k],
                             iteration=i, span_source="measured"))
            ts += spmv_us[k]
            if red_us > 0:
                events.append(_x(_SHARD_PID, k, "reduction", ts,
                                 red_us, iteration=i,
                                 span_source="measured"))
    return slot


def _request_tracks(events, request_spans: Sequence[dict]) -> None:
    """The per-request track family: one thread per trace_id under the
    "requests" process, every span an X event.  Span timestamps are
    service-clock seconds; they are rebased to the earliest span so
    the family starts at t=0 like the solve tracks, and emitted in
    (ts, dur) order per track to satisfy ``validate_perfetto``'s
    monotonicity contract."""
    spans = [s for s in request_spans
             if isinstance(s, dict) and s.get("trace_id")]
    if not spans:
        return
    events.append(_meta(_REQUEST_PID, 0, "process_name", "requests"))
    t0 = min(float(s.get("start_s", 0.0)) for s in spans)
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(str(s["trace_id"]), []).append(s)
    for tid, (trace_id, group) in enumerate(sorted(by_trace.items())):
        rid = next((s.get("request_id") for s in group
                    if s.get("request_id")), trace_id[:8])
        events.append(_meta(_REQUEST_PID, tid, "thread_name", str(rid)))
        group.sort(key=lambda s: (float(s.get("start_s", 0.0)),
                                  float(s.get("duration_s", 0.0))))
        for s in group:
            args = {"trace_id": trace_id,
                    "span_id": s.get("span_id")}
            for key in ("status", "decision", "solve_id", "attempt",
                        "reason", "tenant", "slo_class"):
                if s.get(key) is not None:
                    args[key] = s[key]
            events.append(_x(
                _REQUEST_PID, tid, str(s.get("name", "span")),
                (float(s.get("start_s", 0.0)) - t0) * 1e6,
                float(s.get("duration_s", 0.0)) * 1e6, **args))


def write_perfetto(path: str, trace: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, allow_nan=False)


def validate_perfetto(trace) -> dict:
    """Structural contract of an exported timeline; returns the trace.

    Raises ``ValueError`` unless: ``traceEvents`` is a non-empty list
    (a bare top-level list is also accepted - Chrome does); every
    event carries ``ph``/``ts``/``pid``/``tid``; per ``(pid, tid)``
    track the non-metadata timestamps are monotone non-decreasing; and
    at least one complete (``ph == "X"``) event exists.
    """
    if isinstance(trace, list):
        events = trace
    elif isinstance(trace, dict):
        events = trace.get("traceEvents")
    else:
        raise ValueError(f"perfetto trace must be an object or array, "
                         f"got {type(trace).__name__}")
    if not isinstance(events, list) or not events:
        raise ValueError("perfetto trace has no traceEvents array (or "
                         "it is empty)")
    tracks: Dict[Tuple[Any, Any], float] = {}
    saw_complete = False
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        for field in ("ph", "ts", "pid", "tid"):
            if field not in ev:
                raise ValueError(
                    f"traceEvents[{i}] missing required key {field!r}")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"traceEvents[{i}] ts is not numeric")
        if ev["ph"] == "M":
            continue
        if ev["ph"] == "X":
            saw_complete = True
            if "dur" not in ev or not isinstance(ev["dur"], (int, float)):
                raise ValueError(
                    f"traceEvents[{i}] complete event missing numeric "
                    f"'dur'")
        key = (ev["pid"], ev["tid"])
        prev = tracks.get(key)
        if prev is not None and ev["ts"] < prev:
            raise ValueError(
                f"traceEvents[{i}] timestamp {ev['ts']} goes backwards "
                f"on track pid={ev['pid']} tid={ev['tid']} (prev "
                f"{prev})")
        tracks[key] = ev["ts"]
    if not saw_complete:
        raise ValueError("perfetto trace contains no complete (ph='X') "
                         "events")
    return trace
