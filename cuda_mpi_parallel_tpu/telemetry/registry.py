"""Process-wide metrics registry: counters, gauges, histograms.

A deliberately small, dependency-free subset of the Prometheus client
model - enough for the north star ("serves heavy traffic") without
pulling a client library the container does not ship.  Metrics are
host-side Python state only: incrementing a counter never touches a
device value, so instrumentation can never force a sync into a solve
(graftlint GL105).

Exposition formats:

* ``REGISTRY.snapshot()`` - a JSON-serializable dict (embedded in
  ``bench_results.json`` and the CLI's ``--metrics`` output);
* ``REGISTRY.to_prometheus()`` - the Prometheus text format, one
  ``name{labels} value`` line per child, for scrape endpoints.

Thread-safe: one process-wide lock guards child creation and updates
(solves may be issued from serving threads).
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MAX_LABEL_SETS",
           "MetricsRegistry", "PERCENTILES", "REGISTRY",
           "quantile_from_buckets"]

#: default histogram buckets (seconds-flavored, matching solve times
#: from sub-ms resident kernels to multi-minute 256^3 streaming runs)
DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0, 300.0)

#: the percentile readout every histogram exposes (JSON ``percentiles``
#: and ``{name}_p50/_p95/_p99`` Prometheus gauges) - the latency
#: summary the solver service's SLO reporting consumes
PERCENTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

#: per-metric label-cardinality cap.  Per-tenant labels made series
#: count caller-controlled: an adversarial (or merely enthusiastic)
#: tenant id stream must not grow exposition without bound.  Once a
#: metric holds this many DISTINCT label sets, updates for new sets
#: collapse into one ``__other__`` bucket (every label position set to
#: ``"__other__"``) and the metric's overflow counter increments -
#: aggregate mass is preserved, per-series attribution is dropped,
#: memory stays bounded.  Existing series keep updating normally.
#: Read at update time (not bound at construction) so tests can
#: monkeypatch a tiny cap.
MAX_LABEL_SETS = 256


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]) -> Tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"metric labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[name]) for name in labelnames)


def _format_labels(labelnames: Sequence[str], key: Tuple,
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = list(zip(labelnames, key))
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    # Exposition-format label escaping: backslash FIRST (later rules
    # insert backslashes), then double-quote and newline - the three
    # characters the Prometheus text format requires escaped inside
    # label values.  An unescaped newline splits the sample line in
    # two and poisons the whole scrape.
    body = ",".join(
        '{}="{}"'.format(
            n,
            str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))
        for n, v in pairs)
    return "{" + body + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), *, lock=None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock if lock is not None else threading.Lock()
        self._children: Dict[Tuple, float] = {}
        self._label_overflow = 0

    def _bounded_key(self, key: Tuple) -> Tuple:
        """Route a NEW label set past ``MAX_LABEL_SETS`` into the
        ``__other__`` bucket (lock held).  Known sets and unlabeled
        metrics pass through untouched; the overflow bucket itself is
        not counted against the cap."""
        if not self.labelnames or key in self._children:
            return key
        other = ("__other__",) * len(self.labelnames)
        distinct = len(self._children) - (other in self._children)
        if distinct >= MAX_LABEL_SETS:
            self._label_overflow += 1
            return other
        return key

    @property
    def label_overflow(self) -> int:
        """How many updates landed in ``__other__`` because the metric
        was at its label-cardinality cap."""
        with self._lock:
            return self._label_overflow

    def _update(self, labels: Dict[str, str], fn) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            key = self._bounded_key(key)
            self._children[key] = fn(self._children.get(key))

    def value(self, **labels: str) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._children.get(key, 0.0)

    def snapshot(self):
        with self._lock:
            return [
                {"labels": dict(zip(self.labelnames, key)), "value": val}
                for key, val in sorted(self._children.items())
            ]

    def _overflow_lines(self) -> List[str]:
        """The ``{name}_label_overflow`` companion counter (emitted
        only once the cap engaged - a quiet metric stays quiet)."""
        with self._lock:
            n = self._label_overflow
        if n <= 0:
            return []
        return [f"# TYPE {self.name}_label_overflow counter",
                f"{self.name}_label_overflow {n}"]

    def prometheus_lines(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            for key, val in sorted(self._children.items()):
                lines.append(
                    f"{self.name}{_format_labels(self.labelnames, key)} "
                    f"{_format_value(val)}")
        lines.extend(self._overflow_lines())
        return lines


def _format_value(v: float) -> str:
    # Prometheus text format supports the NaN/+Inf/-Inf literals; a
    # non-finite observation must render, not poison every later scrape
    # (int(nan) raises).
    if math.isnan(v):
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    as_int = int(v)
    return str(as_int) if v == as_int else repr(float(v))


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (amount={amount})")
        self._update(labels, lambda old: (old or 0.0) + amount)


class Gauge(_Metric):
    """A value that can go up and down (or be set outright)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        self._update(labels, lambda old: float(value))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        self._update(labels, lambda old: (old or 0.0) + amount)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)


def quantile_from_buckets(bounds: Sequence[float],
                          cumulative_counts: Sequence[float],
                          total: float, q: float) -> Optional[float]:
    """``histogram_quantile`` semantics over cumulative bucket counts:
    find the bucket the q-th observation landed in and interpolate
    linearly inside it (lower bound of the first bucket is 0).
    Observations past the last finite bound clamp to that bound - the
    honest answer a bucketed histogram can give.  ``None`` when
    nothing was observed.

    THE one quantile definition: :class:`Histogram` readouts and the
    fleet-merge aggregation (``telemetry.fleet``) both call this, so a
    merged histogram's p99 is exactly the p99 this registry would
    report for the union stream.
    """
    if total <= 0:
        return None
    target = q * total
    prev = 0.0
    for i, bound in enumerate(bounds):
        if cumulative_counts[i] >= target:
            lower = 0.0 if i == 0 else bounds[i - 1]
            within = cumulative_counts[i] - prev
            if within <= 0:
                return bound
            return lower + (bound - lower) * (target - prev) / within
        prev = cumulative_counts[i]
    return bounds[-1]


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; ``+Inf`` is implicit)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS, *, lock=None):
        super().__init__(name, help, labelnames, lock=lock)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        # children: key -> [bucket_counts..., count, sum]
        self._children: Dict[Tuple, List[float]] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(self.labelnames, labels)
        value = float(value)
        with self._lock:
            key = self._bounded_key(key)
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = \
                    [0.0] * (len(self.buckets) + 2)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child[i] += 1
            child[-2] += 1
            child[-1] += value

    def value(self, **labels: str):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                return {"count": 0, "sum": 0.0}
            return {"count": int(child[-2]), "sum": child[-1]}

    def _quantile_locked(self, child, q: float) -> Optional[float]:
        return quantile_from_buckets(self.buckets, child[:-2],
                                     child[-2], q)

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """The q-th latency quantile (0 < q < 1) of one child, derived
        from the cumulative buckets; ``None`` when nothing was
        observed.  Used by the solver service's p50/p95/p99 readout."""
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                return None
            return self._quantile_locked(child, q)

    def snapshot(self):
        with self._lock:
            out = []
            for key, child in sorted(self._children.items()):
                out.append({
                    "labels": dict(zip(self.labelnames, key)),
                    "buckets": {
                        _format_value(b): int(child[i])
                        for i, b in enumerate(self.buckets)},
                    "count": int(child[-2]),
                    "sum": child[-1],
                    "percentiles": {
                        name: self._quantile_locked(child, q)
                        for name, q in PERCENTILES},
                })
            return out

    def prometheus_lines(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            for key, child in sorted(self._children.items()):
                for i, bound in enumerate(self.buckets):
                    lab = _format_labels(self.labelnames, key,
                                         ("le", _format_value(bound)))
                    lines.append(f"{self.name}_bucket{lab} {int(child[i])}")
                lab = _format_labels(self.labelnames, key, ("le", "+Inf"))
                lines.append(f"{self.name}_bucket{lab} {int(child[-2])}")
                lab = _format_labels(self.labelnames, key)
                lines.append(f"{self.name}_count{lab} {int(child[-2])}")
                lines.append(
                    f"{self.name}_sum{lab} {_format_value(child[-1])}")
            # bucket-derived percentile gauges: scrape consumers get
            # p50/p95/p99 without running histogram_quantile themselves
            # (and the CLI's --metrics text is readable as-is).  Gauge-
            # typed companions, never part of the histogram series.
            for pname, q in PERCENTILES:
                lines.append(f"# TYPE {self.name}_{pname} gauge")
                for key, child in sorted(self._children.items()):
                    v = self._quantile_locked(child, q)
                    if v is None:
                        continue
                    lab = _format_labels(self.labelnames, key)
                    lines.append(
                        f"{self.name}_{pname}{lab} {_format_value(v)}")
        lines.extend(self._overflow_lines())
        return lines


class MetricsRegistry:
    """Named home for every metric in the process.

    ``counter``/``gauge``/``histogram`` are get-or-create: a second
    registration with the same name returns the SAME child (so
    instrument sites need no import-order coordination), but a name
    collision across metric kinds or label sets is a programming error
    and raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls \
                        or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}"
                        f"{existing.labelnames}, cannot re-register as "
                        f"{cls.__name__}{tuple(labelnames)}")
                return existing
            metric = cls(name, help, labelnames, lock=self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets)
        # same loud-collision policy as kind/labelnames: silently
        # landing observations in someone else's buckets is invisible
        want = tuple(sorted(float(b) for b in buckets))
        if h.buckets != want:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, cannot re-register with {want}")
        return h

    def metrics(self) -> Iterable[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, dict]:
        """JSON-serializable view of every metric's current state."""
        out: Dict[str, dict] = {}
        for m in sorted(self.metrics(), key=lambda m: m.name):
            entry = {"kind": m.kind, "help": m.help,
                     "series": m.snapshot()}
            if isinstance(m, Histogram):
                # the bucket EDGES, explicit: a fleet merge
                # (telemetry.fleet) sums bucket counts bucket-wise and
                # must never re-derive the bounds from formatted keys
                entry["bucket_bounds"] = [float(b) for b in m.buckets]
            if m.labelnames:
                entry["labelnames"] = list(m.labelnames)
            overflow = m.label_overflow
            if overflow:
                entry["label_overflow"] = overflow
            out[m.name] = entry
        return out

    def to_json(self, **dumps_kwargs) -> str:
        return json.dumps(self.snapshot(), allow_nan=False, **dumps_kwargs)

    def to_prometheus(self) -> str:
        lines: List[str] = []
        for m in sorted(self.metrics(), key=lambda m: m.name):
            lines.extend(m.prometheus_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests; a process never needs this)."""
        with self._lock:
            self._metrics.clear()


#: The process-wide default registry every instrumentation site uses.
REGISTRY = MetricsRegistry()
