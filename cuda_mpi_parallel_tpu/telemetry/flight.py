"""Convergence flight recorder: in-loop telemetry with zero host syncs.

The reference checks convergence every iteration but reports nothing
(``CUDACG.cu:333,365`` - "Success" unconditionally, SURVEY Q4/Q7).
The general solver's ``record_history`` closes that gap for dense
per-iteration traces, but it allocates ``maxiter + 1`` slots and only
records ``||r||`` - and the distributed one-kernel engines have no
history at all, so exactly the pod-scale solves the ROADMAP cares
about were flying blind.

The flight recorder is the fixed-cost answer: a **fixed-size,
stride-decimated ring buffer** of ``(iteration, ||r||^2, alpha, beta)``
rows carried in the ``lax.while_loop`` state of every recorder-capable
engine.  Properties the design guarantees:

* **Zero host round-trips.**  Rows are written with on-device masked
  ring updates; the buffer is fetched ONCE post-solve, by a consumer
  that already synced (the CLI / ``FlightRecord.from_buffer``).  The
  hot loop never sees a callback, transfer, or sync (graftlint GL105
  clean by construction).
* **Bit-identical when off.**  With ``flight=None`` the solver code
  path is UNTOUCHED - the buffer never enters the loop state, so the
  traced jaxpr is bit-identical to a build without the recorder
  (extends the telemetry-off proof in tests/test_cost_accounting.py).
* **Bounded cost when on.**  One ``(capacity, 4)`` array in the carry
  and one masked row write per iteration, independent of ``maxiter``
  and stride; distributed solves record the already-psum'd scalars,
  so the rows are replicated and no extra collective is issued.

On top of the record, :mod:`.health` reconstructs the CG-Lanczos
tridiagonal from the alpha/beta columns to estimate the extreme Ritz
values and condition number, and classifies stagnation / plateau /
divergence - see ``health.assess_solve_health``.

The VMEM-resident engines (single kernel per chip) cannot carry an XLA
ring buffer, but their kernels already maintain a check-block-granular
``||r||^2`` trace in SMEM for the convergence decision; that trace is
also fetched exactly once post-solve and adapts into the same
``FlightRecord`` surface via :func:`buffer_from_block_history`
(alpha/beta columns NaN - the kernel's scalars never leave the chip).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = [
    "COLUMNS",
    "FlightConfig",
    "FlightRecord",
    "buffer_from_block_history",
    "flight_init",
    "flight_init_many",
    "flight_record",
    "flight_record_many",
    "lanes_from_buffer",
    "many_columns",
    "maybe_heartbeat",
]

#: Column layout of one recorder row.
COLUMNS = ("iteration", "residual_sq", "alpha", "beta")

#: Default ring capacity: 1024 rows x 4 f32 = 16 KiB of loop state.
DEFAULT_CAPACITY = 1024

#: Hard cap on ``FlightConfig.for_solve``-derived capacities: 4096 rows
#: keep the carried buffer at 64 KiB and the host-side spectral window
#: (health.py) cheap.
CAPACITY_LIMIT = 4096


@dataclasses.dataclass(frozen=True)
class FlightConfig:
    """Static recorder configuration (hashable - rides jit static args
    and compiled-solver cache keys).

    ``capacity``: ring rows; once ``capacity * stride`` iterations have
    run, the oldest rows are overwritten (the record keeps the LAST
    ``capacity`` sampled iterations).
    ``stride``: decimation - record every ``stride``-th iteration.
    ``heartbeat``: iterations between sampled host heartbeats
    (``jax.debug.callback`` -> a ``flight_heartbeat`` event); 0 (the
    default) compiles the hot loop with NO callback at all.
    """

    capacity: int = DEFAULT_CAPACITY
    stride: int = 1
    heartbeat: int = 0

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {self.stride}")
        if self.heartbeat < 0:
            raise ValueError(
                f"heartbeat must be >= 0 (0 = off), got {self.heartbeat}")

    @classmethod
    def for_solve(cls, maxiter: int, stride: int = 1, heartbeat: int = 0,
                  limit: int = CAPACITY_LIMIT) -> "FlightConfig":
        """Capacity sized so a ``maxiter``-iteration solve at ``stride``
        never wraps (bounded by ``limit``): lossless up to
        ``limit * stride`` iterations, last-window beyond."""
        capacity = max(1, min(maxiter // max(stride, 1) + 1, limit))
        return cls(capacity=capacity, stride=stride, heartbeat=heartbeat)

    def without_heartbeat(self) -> "FlightConfig":
        """This config with the heartbeat stripped.  shard_map'd loops
        suppress the heartbeat (one callback per shard per sample would
        multiply the stream); distributed entry points normalize through
        this so their compiled-solver caches never fork on a field that
        cannot affect the executable."""
        if not self.heartbeat:
            return self
        return dataclasses.replace(self, heartbeat=0)


def flight_init(cfg: FlightConfig, dtype, k0, rr0):
    """Fresh device ring buffer with the solve's initial state recorded
    (iteration ``k0``, residual ``rr0``, alpha/beta NaN - no step has
    run yet).  Unwritten rows are NaN."""
    import jax.numpy as jnp

    buf = jnp.full((cfg.capacity, len(COLUMNS)), jnp.nan, dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    return flight_record(buf, cfg, k0, rr0, nan, nan)


def flight_record(buf, cfg: FlightConfig, k, rr, alpha, beta):
    """One masked ring write: when ``k % stride == 0``, row
    ``(k // stride) % capacity`` becomes ``(k, rr, alpha, beta)``;
    otherwise the buffer passes through unchanged.  Pure device ops
    (dynamic slice read + write of one 4-wide row) - no sync, no
    callback, loop-carry friendly."""
    import jax.numpy as jnp

    dtype = buf.dtype
    k = jnp.asarray(k)
    write = (k % cfg.stride) == 0
    slot = (k // cfg.stride) % cfg.capacity
    row = jnp.stack([
        k.astype(dtype),
        jnp.asarray(rr).astype(dtype),
        jnp.asarray(alpha).astype(dtype),
        jnp.asarray(beta).astype(dtype),
    ])
    return buf.at[slot].set(jnp.where(write, row, buf[slot]))


def _heartbeat_host(k, rr) -> None:
    """Host side of the sampled heartbeat (runs under
    ``jax.debug.callback``; values arrive as tiny host arrays - reading
    them here is NOT a device sync inside the loop, the runtime
    delivers them asynchronously).  This executes on jax's callback
    thread, where the event module's contextvars are empty - the
    solve_id/phase correlation comes from ``events.ambient_scope()``
    (the dispatch-time snapshot) instead."""
    from . import events
    from .registry import REGISTRY

    iteration = int(np.asarray(k))
    residual_sq = float(np.asarray(rr))
    REGISTRY.gauge(
        "solve_heartbeat_iteration",
        "most recent in-flight heartbeat iteration (sampled; only "
        "emitted when FlightConfig.heartbeat > 0)").set(iteration)
    if events.active():
        events.emit("flight_heartbeat", iteration=iteration,
                    residual_sq=residual_sq, **events.ambient_scope())


def maybe_heartbeat(cfg: FlightConfig, k, rr) -> None:
    """Sampled in-flight heartbeat for long solves.

    STATIC no-op when ``cfg.heartbeat == 0`` (the default): the traced
    loop body contains no callback at all, so the compiled solve is
    untouched.  When enabled, every ``heartbeat``-th iteration posts
    ``(k, ||r||^2)`` to the host via ``jax.debug.callback`` (unordered,
    loop-safe - the device never blocks on delivery) and emits a
    ``flight_heartbeat`` event when a sink is configured.
    """
    if not cfg.heartbeat:
        return
    import jax
    from jax import lax

    lax.cond(
        (k % cfg.heartbeat) == 0,
        lambda: jax.debug.callback(_heartbeat_host, k, rr),
        lambda: None)


# ---------------------------------------------------------------------------
# Many-RHS (batched) recorder: one ring buffer carrying every lane
#
# A masked batched CG (solver.many) runs k solves through one loop; its
# recorder rows are ``(iteration, rr_0..rr_{k-1}, alpha_0..alpha_{k-1},
# beta_0..beta_{k-1})`` - per-lane ||r||^2 and recurrence scalars in ONE
# (capacity, 1 + 3k) carry, written with the same masked ring update as
# the single-RHS buffer.  ``lanes_from_buffer`` slices the fetched
# buffer back into k standard FlightRecords, so health classification
# and --history work per lane with zero new downstream machinery.


def many_columns(n_rhs: int) -> int:
    """Row width of a batched flight buffer: iteration + 3 per-lane
    scalar columns (rr, alpha, beta)."""
    if n_rhs < 1:
        raise ValueError(f"n_rhs must be >= 1, got {n_rhs}")
    return 1 + 3 * n_rhs


def flight_init_many(cfg: FlightConfig, dtype, k0, rr0):
    """Fresh batched ring buffer (``rr0`` is the per-lane ``(k,)``
    initial residual; alpha/beta lanes NaN - no step has run)."""
    import jax.numpy as jnp

    n_rhs = int(rr0.shape[0])
    buf = jnp.full((cfg.capacity, many_columns(n_rhs)), jnp.nan, dtype)
    nan = jnp.full((n_rhs,), jnp.nan, dtype)
    return flight_record_many(buf, cfg, k0, rr0, nan, nan)


def flight_record_many(buf, cfg: FlightConfig, k, rr, alpha, beta):
    """One masked ring write of a batched row (``rr``/``alpha``/``beta``
    are ``(k,)`` per-lane scalars) - same write cadence and slot rule
    as :func:`flight_record`."""
    import jax.numpy as jnp

    dtype = buf.dtype
    k = jnp.asarray(k)
    write = (k % cfg.stride) == 0
    slot = (k // cfg.stride) % cfg.capacity
    row = jnp.concatenate([
        k.astype(dtype)[None],
        jnp.asarray(rr).astype(dtype),
        jnp.asarray(alpha).astype(dtype),
        jnp.asarray(beta).astype(dtype),
    ])
    return buf.at[slot].set(jnp.where(write, row, buf[slot]))


def lanes_from_buffer(buf, n_rhs: int, stride: Optional[int] = None):
    """Slice a fetched batched buffer into ``n_rhs`` standard
    :class:`FlightRecord` views (lane ``j``: iteration, ``rr_j``,
    ``alpha_j``, ``beta_j``).  Host-side numpy, once, post-solve."""
    arr = np.asarray(buf, dtype=np.float64)
    expect = many_columns(n_rhs)
    if arr.ndim != 2 or arr.shape[1] != expect:
        raise ValueError(
            f"batched flight buffer must be (capacity, {expect}) for "
            f"n_rhs={n_rhs}, got {arr.shape}")
    records = []
    for j in range(n_rhs):
        lane = np.stack([arr[:, 0], arr[:, 1 + j],
                         arr[:, 1 + n_rhs + j],
                         arr[:, 1 + 2 * n_rhs + j]], axis=1)
        records.append(FlightRecord.from_buffer(lane, stride=stride))
    return records


def buffer_from_block_history(block_rr, check_every: int,
                              cap: Optional[int] = None) -> np.ndarray:
    """Adapt a resident kernel's block trace to the recorder layout.

    ``block_rr``: the ``(nblocks + 1,)`` ``||r||^2`` trace the resident
    kernels keep in SMEM (slot 0 = initial, slot j = after block j,
    ``-1.0`` sentinel for never-run blocks).  Returns a standard
    ``(rows, 4)`` flight buffer: iteration ``min(j * check_every,
    cap)``, the block residual, NaN alpha/beta (the kernel's recurrence
    scalars never leave the chip).  Host-side numpy - called once
    post-solve on the already-fetched trace.
    """
    arr = np.asarray(block_rr, dtype=np.float64).reshape(-1)
    n = arr.shape[0]
    its = np.arange(n, dtype=np.float64) * float(check_every)
    if cap is not None:
        its = np.minimum(its, float(cap))
    buf = np.full((n, len(COLUMNS)), np.nan)
    valid = arr >= 0.0  # ||r||^2 >= 0; -1.0 is the never-ran sentinel
    buf[valid, 0] = its[valid]
    buf[valid, 1] = arr[valid]
    return buf


@dataclasses.dataclass(frozen=True)
class FlightRecord:
    """Host-side view of a fetched flight buffer: rows sorted by
    iteration, unwritten (NaN) slots dropped, duplicates (ring slots
    that share a capped iteration) resolved to the last write."""

    iterations: np.ndarray   # (m,) int64, strictly increasing
    residual_sq: np.ndarray  # (m,) float64
    alphas: np.ndarray       # (m,) float64 (NaN where not recorded)
    betas: np.ndarray        # (m,) float64
    stride: int = 1

    @classmethod
    def from_buffer(cls, buf, stride: Optional[int] = None
                    ) -> "FlightRecord":
        """The post-solve fetch: ONE host conversion of the device ring
        buffer (the solve itself is already complete and synced)."""
        arr = np.asarray(buf, dtype=np.float64).reshape(-1, len(COLUMNS))
        mask = np.isfinite(arr[:, 0])
        rows = arr[mask]
        # stable sort + keep-last dedupe: a capped final block can land
        # on an iteration an earlier ring pass also wrote
        order = np.argsort(rows[:, 0], kind="stable")
        rows = rows[order]
        if rows.shape[0]:
            keep = np.append(rows[1:, 0] != rows[:-1, 0], True)
            rows = rows[keep]
        its = rows[:, 0].astype(np.int64)
        if stride is None:
            # infer from the LEADING diffs: the final row may be
            # cap-clamped (a resident block trace whose last block hit
            # iter_cap mid-block), so the last diff can be a remainder
            # smaller than the true granularity
            diffs = np.diff(its)
            if diffs.size > 1:
                stride = int(diffs[:-1].min())
            elif diffs.size == 1:
                stride = int(diffs[0])
            else:
                stride = 1
        return cls(iterations=its, residual_sq=rows[:, 1],
                   alphas=rows[:, 2], betas=rows[:, 3],
                   stride=max(int(stride), 1))

    @classmethod
    def from_history(cls, history, stride: Optional[int] = None
                     ) -> "FlightRecord":
        """Adapt a ``residual_history`` array (``||r||`` at finite
        indices, NaN elsewhere - the dense general-solver trace or the
        resident engines' expanded block trace) into a record with NaN
        alpha/beta columns."""
        hist = np.asarray(history, dtype=np.float64).reshape(-1)
        idx = np.nonzero(np.isfinite(hist))[0]
        buf = np.full((idx.shape[0], len(COLUMNS)), np.nan)
        buf[:, 0] = idx
        buf[:, 1] = hist[idx] ** 2
        return cls.from_buffer(buf, stride=stride)

    def __len__(self) -> int:
        return int(self.iterations.shape[0])

    @property
    def residuals(self) -> np.ndarray:
        """``||r||`` per recorded iteration (sqrt of the stored
        ``||r||^2``)."""
        return np.sqrt(np.maximum(self.residual_sq, 0.0))

    def to_history(self, maxiter: int, dtype=np.float64) -> np.ndarray:
        """Expand into the solvers' ``(maxiter + 1,)``
        ``residual_history`` layout: ``||r||`` at recorded iterations,
        NaN elsewhere - how ``--history`` prints a decimated trace for
        engines with no dense history."""
        hist = np.full(maxiter + 1, np.nan, dtype=dtype)
        keep = self.iterations <= maxiter
        hist[self.iterations[keep]] = self.residuals[keep].astype(dtype)
        return hist

    def decay_rate(self, tail: Optional[int] = None) -> Optional[float]:
        """Least-squares slope of ``log10 ||r||`` per iteration over the
        (optionally last-``tail``-rows of the) record; negative means
        converging, ~0 means flatlined.  ``None`` with < 2 usable
        points (zero/non-finite residuals are excluded)."""
        its = self.iterations.astype(np.float64)
        res = self.residuals
        if tail is not None and tail < its.shape[0]:
            its, res = its[-tail:], res[-tail:]
        ok = np.isfinite(res) & (res > 0.0)
        if int(ok.sum()) < 2 or its[ok][-1] == its[ok][0]:
            return None
        slope = np.polyfit(its[ok], np.log10(res[ok]), 1)[0]
        return float(slope)

    def summary(self) -> dict:
        """Compact JSON-ready digest (what bench.py embeds per
        section)."""
        out = {
            "n_records": len(self),
            "stride": int(self.stride),
            "first_iteration": (int(self.iterations[0]) if len(self)
                                else None),
            "last_iteration": (int(self.iterations[-1]) if len(self)
                               else None),
            "decay_rate": self.decay_rate(),
        }
        if len(self):
            res = self.residuals
            ok = np.isfinite(res)
            out["residual_first"] = float(res[0]) if ok[0] else None
            out["residual_last"] = float(res[-1]) if ok[-1] else None
            out["residual_min"] = (float(res[ok].min()) if ok.any()
                                   else None)
        return out

    def to_json(self) -> dict:
        """Full record as strict-JSON-ready lists (non-finite values
        are the consumer's to sanitize - ``utils.logging.sanitize``)."""
        return {
            "stride": int(self.stride),
            "iterations": [int(v) for v in self.iterations],
            "residual_sq": list(self.residual_sq),
            "alpha": list(self.alphas),
            "beta": list(self.betas),
        }
