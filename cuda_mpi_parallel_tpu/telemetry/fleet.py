"""Cross-replica metric aggregation: merge N registry snapshots into
one fleet view.

One serve replica's :meth:`MetricsRegistry.snapshot` answers "what did
THIS process do"; a replicated fleet (ROADMAP item 2) needs the same
answer across processes, and the merge must not lie:

* **counters** are summed series-wise - total requests across the
  fleet is the sum of per-replica totals, exactly (same float
  addition a single registry would have performed);
* **histogram buckets** are summed bucket-wise against their
  serialized ``bucket_bounds`` (never re-derived from formatted
  keys), so quantiles of the merged view are EXACTLY the quantiles
  the registry would report for the union observation stream - the
  same :func:`registry.quantile_from_buckets` interpolation over the
  summed cumulative counts;
* **gauges** are point-in-time per-process readings that do NOT sum
  (two replicas' queue depths are two facts, not one); each replica's
  gauge series keeps its identity under an added ``replica`` label.

The algebra is **pure** (inputs never mutated) and **associative**:
``merge_two(merge_two(a, b), c) == merge_two(a, merge_two(b, c))`` for
lifted snapshots, so a fleet-of-fleets rollup (scrape aggregators,
then aggregate the aggregators) reports the same numbers as one flat
merge.  :func:`merge_snapshots` is the convenience entry point
``tools/fleet_scrape.py`` drives against live ``/snapshot`` endpoints.

Plain-Python host-side code: no jax import, no device values.
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from .registry import PERCENTILES, _format_value, quantile_from_buckets

__all__ = ["lift", "merge_snapshots", "merge_two"]


def _label_key(labels: Mapping[str, str]) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def lift(snapshot: Mapping[str, dict], replica: str) -> Dict[str, dict]:
    """Tag one replica's snapshot for merging: every gauge series gains
    a ``replica`` label (series already carrying one - an upstream
    aggregate - pass through unchanged, which is what makes repeated
    lifting harmless).  Counters and histograms are copied verbatim:
    their merge is a sum, which needs no provenance.  Pure: the input
    snapshot is never mutated."""
    replica = str(replica)
    out: Dict[str, dict] = {}
    for name, entry in snapshot.items():
        new = {k: v for k, v in entry.items() if k != "series"}
        series = [dict(s) for s in entry.get("series", ())]
        if entry.get("kind") == "gauge":
            for s in series:
                labels = dict(s.get("labels", {}))
                labels.setdefault("replica", replica)
                s["labels"] = labels
            names = list(new.get("labelnames",
                                 _series_labelnames(series)))
            if "replica" not in names:
                names.append("replica")
            new["labelnames"] = names
        out[name] = {**new, "series": series}
    return out


def _series_labelnames(series: List[dict]) -> List[str]:
    for s in series:
        return sorted(s.get("labels", {}))
    return []


def merge_two(a: Mapping[str, dict],
              b: Mapping[str, dict]) -> Dict[str, dict]:
    """Merge two LIFTED snapshots (see :func:`lift`).  Pure and
    associative; raises ``ValueError`` on a metric registered with
    different kinds or different histogram bucket bounds across the
    inputs - the fleet must never silently mix incompatible series."""
    out: Dict[str, dict] = {}
    for name in sorted(set(a) | set(b)):
        ea, eb = a.get(name), b.get(name)
        if ea is None or eb is None:
            src = ea if ea is not None else eb
            out[name] = _copy_entry(src)
            continue
        if ea.get("kind") != eb.get("kind"):
            raise ValueError(
                f"metric {name!r} has kind {ea.get('kind')!r} on one "
                f"replica and {eb.get('kind')!r} on another - refusing "
                f"to merge")
        kind = ea.get("kind")
        if kind == "counter":
            out[name] = _merge_summed(name, ea, eb)
        elif kind == "gauge":
            out[name] = _merge_gauges(name, ea, eb)
        elif kind == "histogram":
            out[name] = _merge_histograms(name, ea, eb)
        else:
            raise ValueError(
                f"metric {name!r}: cannot merge kind {kind!r}")
    return out


def merge_snapshots(snapshots: Mapping[str, Mapping[str, dict]]
                    ) -> Dict[str, dict]:
    """Merge ``{replica_name: registry_snapshot}`` into one fleet view.

    Each snapshot is lifted under its replica name, then folded through
    :func:`merge_two` in sorted-replica order (the fold order is
    irrelevant by associativity; sorting just makes the output
    deterministic).  An empty mapping merges to ``{}``.
    """
    merged: Dict[str, dict] = {}
    for replica in sorted(snapshots):
        merged = merge_two(merged, lift(snapshots[replica], replica))
    return merged


# ---------------------------------------------------------------------------
# per-kind series merges

def _copy_entry(entry: Mapping[str, Any]) -> Dict[str, Any]:
    new = {k: v for k, v in entry.items() if k != "series"}
    new["series"] = [dict(s) for s in entry.get("series", ())]
    return new


def _merged_meta(name: str, ea: Mapping, eb: Mapping) -> Dict[str, Any]:
    meta: Dict[str, Any] = {"kind": ea.get("kind"),
                            "help": ea.get("help") or eb.get("help", "")}
    names_a = ea.get("labelnames")
    names_b = eb.get("labelnames")
    if names_a is not None or names_b is not None:
        la, lb = list(names_a or []), list(names_b or [])
        if la and lb and la != lb:
            raise ValueError(
                f"metric {name!r} has labelnames {la} on one replica "
                f"and {lb} on another - refusing to merge")
        meta["labelnames"] = la or lb
    overflow = int(ea.get("label_overflow", 0)) \
        + int(eb.get("label_overflow", 0))
    if overflow:
        meta["label_overflow"] = overflow
    return meta


def _merge_summed(name: str, ea: Mapping, eb: Mapping) -> Dict[str, Any]:
    """Counters: series with the same label set sum their values."""
    acc: Dict[Tuple, Dict[str, Any]] = {}
    for entry in (ea, eb):
        for s in entry.get("series", ()):
            key = _label_key(s.get("labels", {}))
            if key in acc:
                acc[key]["value"] = acc[key]["value"] + s["value"]
            else:
                acc[key] = {"labels": dict(s.get("labels", {})),
                            "value": s["value"]}
    out = _merged_meta(name, ea, eb)
    out["series"] = [acc[k] for k in sorted(acc)]
    return out


def _merge_gauges(name: str, ea: Mapping, eb: Mapping) -> Dict[str, Any]:
    """Gauges: the union of per-replica series.  A label-set collision
    means the same replica was merged in twice - a provenance bug the
    merge refuses to paper over."""
    acc: Dict[Tuple, Dict[str, Any]] = {}
    for entry in (ea, eb):
        for s in entry.get("series", ()):
            key = _label_key(s.get("labels", {}))
            if key in acc:
                raise ValueError(
                    f"gauge {name!r}: duplicate series "
                    f"{dict(s.get('labels', {}))} across merge inputs "
                    f"(same replica merged twice?)")
            acc[key] = dict(s)
    out = _merged_meta(name, ea, eb)
    out["series"] = [acc[k] for k in sorted(acc)]
    return out


def _merge_histograms(name: str, ea: Mapping,
                      eb: Mapping) -> Dict[str, Any]:
    """Histograms: bucket counts sum bucket-wise against identical
    serialized bounds; count and sum add; percentiles are recomputed
    from the MERGED cumulative counts with the registry's own
    interpolation - so merged quantiles equal union-stream quantiles."""
    bounds_a = ea.get("bucket_bounds")
    bounds_b = eb.get("bucket_bounds")
    if bounds_a is None or bounds_b is None:
        raise ValueError(
            f"histogram {name!r}: snapshot carries no bucket_bounds "
            f"(pre-fleet snapshot format?) - cannot merge without "
            f"explicit bucket edges")
    bounds = [float(x) for x in bounds_a]
    if bounds != [float(x) for x in bounds_b]:
        raise ValueError(
            f"histogram {name!r} has bucket bounds {bounds_a} on one "
            f"replica and {bounds_b} on another - refusing to merge "
            f"(summed buckets would be meaningless)")
    keys = [_format_value(b) for b in bounds]
    acc: Dict[Tuple, Dict[str, Any]] = {}
    for entry in (ea, eb):
        for s in entry.get("series", ()):
            key = _label_key(s.get("labels", {}))
            if key in acc:
                tgt = acc[key]
                tgt["buckets"] = {
                    k: tgt["buckets"].get(k, 0) + s["buckets"].get(k, 0)
                    for k in keys}
                tgt["count"] = tgt["count"] + s["count"]
                tgt["sum"] = tgt["sum"] + s["sum"]
            else:
                acc[key] = {"labels": dict(s.get("labels", {})),
                            "buckets": {k: s["buckets"].get(k, 0)
                                        for k in keys},
                            "count": s["count"], "sum": s["sum"]}
    for tgt in acc.values():
        cum = [tgt["buckets"][k] for k in keys]
        tgt["percentiles"] = {
            pname: quantile_from_buckets(bounds, cum, tgt["count"], q)
            for pname, q in PERCENTILES}
    out = _merged_meta(name, ea, eb)
    out["bucket_bounds"] = bounds
    out["series"] = [acc[k] for k in sorted(acc)]
    return out
