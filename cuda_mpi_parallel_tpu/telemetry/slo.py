"""Rolling-window SLO accounting per (tenant, slo_class).

The serve tier's ``stats()`` percentiles are point-in-time: they say
how fast requests were, not whether the service is KEEPING ITS
PROMISE over time.  This module adds the standard SRE error-budget
view on the service clock (so the fake-clock test idiom drives it
deterministically):

* every terminal request outcome is an observation - in-SLO
  (converged within its class's target latency) or out (missed
  target, TIMEOUT, ERROR, or turned away: REFUSED /
  ADMISSION_REJECTED burn budget too - a rejected request is a broken
  promise from the caller's seat);
* per (tenant, slo_class) the tracker keeps a pruned deque of
  ``(t, ok)`` over the longest configured window and reports the
  in-SLO goodput ratio, the **burn rate** per window
  (``bad_ratio / budget`` - 1.0 means burning exactly the allowed
  budget, >1 means the budget exhausts early), and error-budget
  remaining;
* when a window's burn rate crosses its threshold a typed
  ``slo_burn`` event fires (edge-triggered, re-arming when the burn
  drops back below) - the classic fast/slow multi-window alert pair.

Observe-only by design: nothing here throttles anything.  But
:meth:`SLOTracker.burn_rate` is the documented hook the shed ladder
MAY consume later (``ShedConfig`` growing a burn-rate rung would call
it with the fast window) - the signal is exposed, the policy is not
presumed.

Host-side plain-Python only (no jax import): observations are made
from the service's post-solve bookkeeping with host scalars, so
``slo=None`` (the default) is free and the solve body stays
jaxpr-bit-identical.
"""
from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import events
from .registry import REGISTRY

__all__ = ["SLOConfig", "SLOTracker", "SLOWindow"]


@dataclass(frozen=True)
class SLOWindow:
    """One rolling alert window: ``seconds`` of lookback and the burn
    rate past which it trips.  The conventional pair is a fast window
    (minutes, high threshold - page on a cliff) and a slow window
    (hours, low threshold - ticket on a leak); the serve tests drive
    scaled-down versions through the fake clock."""
    name: str
    seconds: float
    burn_threshold: float

    def __post_init__(self):
        if self.seconds <= 0:
            raise ValueError(f"window {self.name!r}: seconds must be "
                             f"> 0, got {self.seconds}")
        if self.burn_threshold <= 0:
            raise ValueError(f"window {self.name!r}: burn_threshold "
                             f"must be > 0, got {self.burn_threshold}")


@dataclass(frozen=True)
class SLOConfig:
    """SLO accounting policy for a SolverService.

    ``budget`` is the allowed bad fraction (0.01 = 99% objective);
    ``min_samples`` keeps a near-empty window from tripping on its
    first bad request (burn is 0 until the window holds that many
    observations).
    """
    windows: Tuple[SLOWindow, ...] = (
        SLOWindow("fast", 60.0, 14.4),
        SLOWindow("slow", 3600.0, 1.0),
    )
    budget: float = 0.01
    min_samples: int = 8

    def __post_init__(self):
        if not self.windows:
            raise ValueError("SLOConfig needs at least one window")
        if not (0.0 < self.budget < 1.0):
            raise ValueError(f"budget must be in (0, 1), got "
                             f"{self.budget}")
        if self.min_samples < 1:
            raise ValueError(f"min_samples must be >= 1, got "
                             f"{self.min_samples}")


@dataclass
class _FlowState:
    """Per-(tenant, slo_class) rolling state."""
    samples: deque = field(default_factory=deque)   # (t, ok) pairs
    good: int = 0
    bad: int = 0
    tripped: Dict[str, bool] = field(default_factory=dict)


class SLOTracker:
    """Rolling-window SLO accounting; one per SolverService.

    Thread-safe: worker threads observe concurrently.  All times come
    from the caller (the service clock), never from wall time - the
    fake-clock drill is bit-deterministic.
    """

    def __init__(self, config: SLOConfig):
        self.config = config
        self._max_window = max(w.seconds for w in config.windows)
        self._flows: Dict[Tuple[str, str], _FlowState] = {}
        self._lock = threading.Lock()
        self._burn_events = 0
        labelnames = ("tenant", "slo_class", "window")
        self._g_ratio = REGISTRY.gauge(
            "slo_goodput_ratio",
            "in-SLO fraction of terminal outcomes over the window",
            labelnames=labelnames)
        self._g_burn = REGISTRY.gauge(
            "slo_burn_rate",
            "bad_ratio / budget over the window (1.0 = on budget)",
            labelnames=labelnames)
        self._g_budget = REGISTRY.gauge(
            "slo_error_budget_remaining",
            "fraction of the window's error budget still unspent",
            labelnames=labelnames)

    # -- observation --------------------------------------------------

    def observe(self, tenant: str, slo_class: str, t: float,
                in_slo: bool) -> None:
        """Record one terminal outcome at service-clock time ``t``.

        Prunes everything older than the longest window, recomputes
        every window's burn, updates the gauges, and emits one
        ``slo_burn`` event per window on the below->above threshold
        edge.
        """
        cfg = self.config
        key = (str(tenant), str(slo_class))
        trips = []
        with self._lock:
            flow = self._flows.setdefault(key, _FlowState())
            flow.samples.append((float(t), bool(in_slo)))
            if in_slo:
                flow.good += 1
            else:
                flow.bad += 1
            horizon = float(t) - self._max_window
            while flow.samples and flow.samples[0][0] < horizon:
                _, ok = flow.samples.popleft()
                if ok:
                    flow.good -= 1
                else:
                    flow.bad -= 1
            for window in cfg.windows:
                burn, ratio, n = self._window_burn_locked(
                    flow, float(t), window)
                labels = {"tenant": key[0], "slo_class": key[1],
                          "window": window.name}
                self._g_ratio.set(ratio, **labels)
                self._g_burn.set(burn, **labels)
                self._g_budget.set(max(0.0, 1.0 - burn), **labels)
                was = flow.tripped.get(window.name, False)
                now_tripped = burn >= window.burn_threshold
                flow.tripped[window.name] = now_tripped
                if now_tripped and not was:
                    self._burn_events += 1
                    trips.append((window, burn, ratio, n))
        for window, burn, ratio, n in trips:
            events.emit(
                "slo_burn", tenant=key[0], slo_class=key[1],
                window=window.name, burn_rate=round(burn, 6),
                burn_threshold=window.burn_threshold,
                window_s=window.seconds, budget=cfg.budget,
                goodput_ratio=round(ratio, 6), n_samples=n, t_service=t)

    def _window_burn_locked(self, flow: _FlowState, now: float,
                            window: SLOWindow
                            ) -> Tuple[float, float, int]:
        """(burn, goodput_ratio, n) for one window (lock held).

        The longest window is O(1) off the running counters; shorter
        windows scan the pruned deque from the new end (bounded by the
        longest window's population).
        """
        if window.seconds >= self._max_window:
            good, bad = flow.good, flow.bad
        else:
            horizon = now - window.seconds
            good = bad = 0
            for ts, ok in reversed(flow.samples):
                if ts < horizon:
                    break
                if ok:
                    good += 1
                else:
                    bad += 1
        n = good + bad
        if n < self.config.min_samples or n == 0:
            return 0.0, 1.0, n
        bad_ratio = bad / n
        return bad_ratio / self.config.budget, good / n, n

    # -- the documented shed-ladder hook -------------------------------

    def burn_rate(self, tenant: str, slo_class: str, now: float,
                  window: Optional[str] = None) -> float:
        """Current burn rate for one flow (default: fastest window).

        THE hook a future shed-ladder rung consumes: observe-only
        today, but ``ShedConfig`` may call this with the service clock
        and shed the classes below gold when the gold flow burns hot.
        Returns 0.0 for unknown flows (no data = no alarm).
        """
        cfg = self.config
        if window is None:
            win = min(cfg.windows, key=lambda w: w.seconds)
        else:
            matches = [w for w in cfg.windows if w.name == window]
            if not matches:
                raise ValueError(
                    f"unknown SLO window {window!r}; configured: "
                    f"{[w.name for w in cfg.windows]}")
            win = matches[0]
        with self._lock:
            flow = self._flows.get((str(tenant), str(slo_class)))
            if flow is None:
                return 0.0
            burn, _, _ = self._window_burn_locked(flow, float(now), win)
            return burn

    def burning(self, now: float) -> List[Dict[str, Any]]:
        """Every (flow, window) currently burning over its threshold.

        The readiness gate's view of this tracker: read-only (no
        events, no trip-latch mutation - :meth:`observe` owns those),
        computed at the caller's clock so a fake-clock ops test can
        drive it deterministically.  Empty list = no flow is burning.
        """
        now = float(now)
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (tenant, slo_class), flow in sorted(self._flows.items()):
                for window in self.config.windows:
                    burn, _, n = self._window_burn_locked(
                        flow, now, window)
                    if burn > window.burn_threshold:
                        out.append({
                            "tenant": tenant,
                            "slo_class": slo_class,
                            "window": window.name,
                            "burn_rate": round(burn, 4),
                            "burn_threshold": window.burn_threshold,
                            "n": n,
                        })
        return out

    # -- reporting -----------------------------------------------------

    def snapshot(self, now: float) -> Dict[str, Any]:
        """The stats() section: per-flow per-window burn/goodput plus
        the trip counter."""
        out: Dict[str, Any] = {"burn_events": self._burn_events,
                               "budget": self.config.budget,
                               "flows": {}}
        with self._lock:
            for (tenant, slo_class), flow in sorted(self._flows.items()):
                entry: Dict[str, Any] = {}
                for window in self.config.windows:
                    burn, ratio, n = self._window_burn_locked(
                        flow, float(now), window)
                    entry[window.name] = {
                        "burn_rate": round(burn, 4),
                        "goodput_ratio": round(ratio, 4),
                        "budget_remaining": round(
                            max(0.0, 1.0 - burn), 4),
                        "n": n,
                        "tripped": flow.tripped.get(window.name,
                                                    False),
                    }
                out["flows"][f"{tenant}/{slo_class}"] = entry
        return out
