"""Structured solve-trace events: one JSON object per line (JSONL).

Every solve can emit a typed trace of what the framework decided and
measured on its behalf - which engine ran, why a fast path was
rejected, whether the distributed solver cache hit, what the
communication cost model says, and how the solve ended.  The reference
records none of this (its only output is the solution vector,
``CUDACG.cu:361-365``); a serving deployment cannot be debugged without
it.

Design rules:

* **Opt-in and free when off.**  ``emit()`` with no sink configured
  and no subscriber attached is a dict-build away from a no-op; no
  file handle, no formatting.  Consumers are a JSONL sink
  (:func:`configure`) and/or bounded in-process subscriber rings
  (:func:`subscribe` - the ops plane's live event bus; drop-oldest,
  never blocking the emitter).
* **Host-side only.**  Events carry host scalars.  Emission never
  reads a device value, so instrumentation can never force a transfer
  into (or a sync after) a solve - results are read only by consumers
  that already synced (``session.observe_solve``'s epilogue, the CLI's
  post-``time_fn`` reporting).
* **Strict JSON.**  Payloads pass through ``utils.logging.sanitize``
  (non-finite floats -> ``null``) and are serialized with
  ``allow_nan=False``, so a trace file is always parseable by strict
  readers (jq/BigQuery) - the same bug class fixed in
  ``utils.logging.emit_json``.

Event schema (``EVENT_SCHEMA``): each event has ``event`` (type name),
``t`` (monotonic seconds, ``time.perf_counter`` - durations between
events are meaningful, absolute values are not), ``solve_id`` (opaque
string tying one solve's events together; ``None`` outside a solve
scope), plus per-type required fields listed below.  Unknown extra
fields are allowed - the schema floor is what consumers may rely on.
"""
from __future__ import annotations

import contextlib
import contextvars
import io
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, IO, Iterator, Optional, Tuple, Union

from ..utils.logging import sanitize

__all__ = [
    "EVENT_SCHEMA",
    "EventStream",
    "Subscription",
    "active",
    "ambient_scope",
    "configure",
    "current_solve_id",
    "emit",
    "new_solve_id",
    "read_events",
    "scoped",
    "solve_scope",
    "subscribe",
    "unsubscribe",
    "validate_event",
]

#: event type -> field names REQUIRED beyond the common envelope
#: (event, t, solve_id).  Extra fields are always permitted.
EVENT_SCHEMA: Dict[str, tuple] = {
    # a solve was requested: problem/config description
    "solve_start": ("label",),
    # which engine/method actually runs the solve
    "engine_selected": ("engine", "method"),
    # a fast path was considered and declined (engine= the declined one)
    "eligibility_rejected": ("engine", "reason"),
    # the distributed compiled-solver cache was consulted
    "dist_cache_hit": ("key",),
    "dist_cache_miss": ("key",),
    # one convergence-check block boundary (post-solve, from the
    # recorded residual history - NOT emitted from inside the hot loop)
    "check_block": ("iteration",),
    # jaxpr-derived communication cost of the compiled solve body
    "comm_cost": ("psum_per_iteration", "ppermute_per_iteration",
                  "comm_bytes_per_iteration"),
    # static per-shard load/communication accounting computed at
    # partition time (telemetry.shardscope.ShardReport.to_json payload)
    "shard_profile": ("kind", "n_shards", "rows", "nnz",
                      "halo_send_bytes"),
    # an imbalance-aware partition plan (balance.PartitionPlan) was
    # applied to a distributed solve: the chosen reorder/split lane plus
    # the planner's predicted imbalance digest joined to the measured
    # one of the partition actually built - the shardscope feedback
    # loop, closed, in one event.  A second, EXTENDED emission with
    # stage="drift" (telemetry.calibrate.note_drift) follows a measured
    # solve and additionally carries drift_pct /
    # predicted_s_per_iteration / measured_s_per_iteration - the
    # model-error % of the plan's cost prediction
    "partition_plan": ("reorder", "split", "n_shards", "measured"),
    # measured per-shard per-phase timing of a partitioned operator
    # (telemetry.phasetrace.PhaseProfile.to_json payload): phase
    # seconds (halo/spmv/reduction + the composed step), per-shard
    # spmv seconds, per-link wire bandwidths ("links"), and the
    # explained-fraction residual check
    "phase_profile": ("n_shards", "exchange", "phases",
                      "explained_fraction"),
    # a sequence replan decision (dist_cg.solve_sequence): whether
    # solve k+1 kept or switched its partition plan based on the model
    # calibrated from solve k, with the predicted gain of the choice
    "replan": ("solve_index", "decision"),
    # a compiled distributed solver was evicted from the bounded LRU
    # cache (parallel.dist_cg; a long-running service on many
    # operators must not leak traces) - key is the evicted entry's
    # digest, the same id its dist_cache_hit/miss events carried
    "dist_cache_evict": ("key",),
    # solver-service request lifecycle (serve.SolverService): a request
    # entered its microbatch queue; a batch was cut and dispatched onto
    # solve_many / solve_distributed_many (the batch's events share the
    # dispatch's solve_id - the request->solve linkage); a request left
    # the service with a typed terminal status (CONVERGED/.../TIMEOUT)
    "request_enqueued": ("request_id", "handle", "queue_depth"),
    "batch_dispatch": ("handle", "bucket", "n_requests", "reason"),
    "request_done": ("request_id", "status", "wait_s"),
    # sampled in-flight heartbeat (FlightConfig.heartbeat > 0 only;
    # posted from the hot loop via an unordered jax.debug.callback)
    "flight_heartbeat": ("iteration",),
    # flight-recorder health verdict (telemetry.health): trace
    # classification + decay rates + Ritz condition estimate
    "solve_health": ("classification", "converged", "iterations"),
    # a solve exited with a typed BREAKDOWN (robust/): site names the
    # faulted recurrence site when a chaos FaultPlan was armed
    # ("unknown" for organically detected breakdowns), iterations the
    # step the health predicate caught it at
    "solve_fault": ("site", "status", "iterations"),
    # a recovery action after a breakdown (robust.solve_with_recovery):
    # action is "restart" (re-seeded re-dispatch), "recovered" (final
    # solve converged after >= 1 restart) or "exhausted" (budget spent,
    # typed BREAKDOWN returned)
    "solve_recovery": ("attempt", "action"),
    # serve retry/breaker lifecycle: a failed (ERROR/BREAKDOWN) request
    # was re-enqueued with backoff; a handle's circuit breaker changed
    # state (closed/open/half_open)
    "request_retry": ("request_id", "attempt", "status"),
    "breaker_transition": ("handle", "state"),
    # multi-tenant overload protection (serve.admission/serve.sched):
    # a submit was REFUSED at the door (token bucket exhausted, or the
    # shed ladder's reject rung - reason says which; retry_after_s is
    # the typed hint the caller gets); the weighted-fair dispatcher
    # picked a flow ("dispatch", with the priced cost) or held a
    # dispatch-ready flow under the defer rung ("defer", throttled to
    # one event per flow per ladder episode); the shed ladder changed
    # level (0 ok / 1 degrade / 2 defer / 3 reject, with the queue
    # depth that drove it)
    "admission": ("request_id", "tenant", "slo_class", "decision"),
    "sched_dispatch": ("tenant", "slo_class", "decision"),
    "shed": ("level", "queue_depth"),
    # Krylov recycling (solver.recycle): a RecycleSpace was harvested
    # from a solve's basis ring + flight tridiagonal (k columns kept,
    # window = tridiagonal rows used, iterations = source solve's);
    # a solve consulted a recycled space (iters_saved vs the
    # undeflated baseline rides when the consumer knows one)
    "recycle_harvest": ("k", "window", "iterations"),
    "recycle_applied": ("k", "iterations"),
    # elastic solves (robust.elastic / robust.watchdog): the straggler
    # watchdog found one shard's measured phase timing (or one link's
    # measured bandwidth) degraded past its threshold vs the
    # calibration-cache EWMA baseline; a checkpoint was migrated to a
    # different mesh shape (reason: "resume_mesh_change" for a
    # cross-run elastic resume, "shard_degraded"/"shard_loss" for the
    # in-run checkpoint-now-and-migrate triggers); a live serve handle
    # was migrated onto a new mesh (queued requests preserved, buckets
    # re-warmed off the request path)
    "shard_degraded": ("shard", "phase", "ratio"),
    "solve_migration": ("n_shards_from", "n_shards_to", "reason"),
    "handle_migrated": ("handle", "n_shards_from", "n_shards_to"),
    # request observatory (telemetry.tracing / telemetry.slo /
    # serve.usage): one causal span of a request's life in the serve
    # tier (name in {submit, admission, queue_wait, sched, solve,
    # retry, migration, result}; parent_span_id None only for the
    # root submit span; traceparent is the W3C-shaped context string
    # a future HTTP/gRPC shim injects/extracts unchanged); a rolling
    # SLO error-budget window tripped its burn-rate threshold for one
    # (tenant, slo_class, window); one dispatched batch's metered
    # usage totals with the per-tenant apportionment that must
    # reconcile with them
    "span": ("trace_id", "span_id", "parent_span_id", "name",
             "request_id", "start_s", "duration_s"),
    "slo_burn": ("tenant", "slo_class", "window", "burn_rate"),
    "usage": ("n_requests", "device_seconds", "wire_bytes",
              "batch_iterations"),
    # device-memory footprint of a partitioned solve
    # (telemetry.memscope.MemoryFootprint.to_json payload, plus the
    # measured live-array twin and backend allocator peak when known):
    # per-shard persistent bytes (exact matrix + modeled solver working
    # set), the jaxpr-liveness transient peak, and the FITS / TIGHT /
    # OVERFLOW / unknown classification against MachineModel.hbm_bytes
    "memory_profile": ("kind", "n_shards", "n_rhs", "matrix_bytes",
                       "persistent_bytes", "peak_bytes",
                       "classification"),
    # the solve finished (converged or not) and was synced
    "solve_end": ("status", "iterations", "residual_norm"),
}

_COUNTER = itertools.count(1)
_SOLVE_ID: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "cuda_mpi_parallel_tpu_solve_id", default=None)
_SCOPE_FIELDS: contextvars.ContextVar[Dict[str, Any]] = \
    contextvars.ContextVar("cuda_mpi_parallel_tpu_event_fields",
                           default={})

#: Thread-visible mirror of the contextvar scope.  jax delivers
#: ``debug.callback``s (the flight recorder's heartbeat) on its own
#: runtime thread where the contextvars above are empty; emitting there
#: would lose the solve_id/phase correlation.  Closing over the scope at
#: trace time is no better - jit caching would bake the FIRST solve's id
#: into the compiled callback.  So the scope managers keep this plain
#: snapshot current at dispatch time and callbacks read it via
#: ``ambient_scope()``.  Single in-flight solve per process assumed
#: (true of the CLI/bench/tests; concurrent solves would interleave).
_AMBIENT: Dict[str, Any] = {}


def _sync_ambient() -> None:
    snap: Dict[str, Any] = {}
    sid = _SOLVE_ID.get()
    if sid is not None:
        snap["solve_id"] = sid
    snap.update(_SCOPE_FIELDS.get())
    global _AMBIENT
    _AMBIENT = snap


def ambient_scope() -> Dict[str, Any]:
    """The current solve scope (solve_id + ``scoped`` fields) as seen
    from ANY thread - what host-side callbacks pass to ``emit`` so
    their events stay correlated with the solve that is in flight."""
    return dict(_AMBIENT)


def _drain_callbacks() -> None:
    """Flush jax's pending host callbacks (the flight recorder's
    heartbeat) before a scope is torn down: delivery is asynchronous,
    so without this barrier a solve's trailing heartbeats could be
    stamped with the NEXT scope's fields (or none).  Runs at scope
    exit - post-solve, outside any hot loop - and is a no-op when jax
    was never imported or has nothing in flight."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return
    try:
        jax_mod.effects_barrier()
    except Exception:
        pass  # ancient jax without effects_barrier: best-effort only


@contextlib.contextmanager
def scoped(**fields: Any) -> Iterator[None]:
    """Attach ``fields`` to every event emitted inside the block.

    The honest answer to double-dispatch: a CLI solve runs once for
    compile warmup and once timed, and BOTH dispatches really happen -
    so both emit, but the warmup's events carry ``phase="warmup"`` and
    consumers filter rather than miscount.  Explicit emit() fields win
    over scope fields on collision.
    """
    merged = dict(_SCOPE_FIELDS.get())
    merged.update(fields)
    token = _SCOPE_FIELDS.set(merged)
    _sync_ambient()
    try:
        yield
    finally:
        _drain_callbacks()
        _SCOPE_FIELDS.reset(token)
        _sync_ambient()


def scope_phase() -> str:
    """The current emission scope's phase ("solve" unless inside
    ``scoped(phase=...)``).  Metric-updating instrumentation uses this
    as a label so dispatch counters can be split the same way the
    event stream is (e.g. the CLI's compile-warmup dispatch)."""
    return str(_SCOPE_FIELDS.get().get("phase", "solve"))


def new_solve_id() -> str:
    """Process-unique opaque id: monotonic counter + coarse timestamp."""
    return f"s{next(_COUNTER):06d}-{int(time.time())}"


def current_solve_id() -> Optional[str]:
    return _SOLVE_ID.get()


@contextlib.contextmanager
def solve_scope(solve_id: Optional[str] = None) -> Iterator[str]:
    """Bind a solve id so every ``emit`` inside the block carries it."""
    sid = solve_id if solve_id is not None else new_solve_id()
    token = _SOLVE_ID.set(sid)
    _sync_ambient()
    try:
        yield sid
    finally:
        _drain_callbacks()
        _SOLVE_ID.reset(token)
        _sync_ambient()


class EventStream:
    """A JSONL sink.  ``path_or_stream`` is a filesystem path (opened
    append, line-buffered flushes) or any ``.write()``-able object.

    ``rotate_bytes``: size-based rotation for long-running sinks (a
    serve process on ``--trace-events`` must never fill the disk).
    After any write that leaves the file at or past the threshold the
    file is atomically renamed to ``PATH.1`` (``os.replace`` - the
    same one-predecessor pattern as checkpoint ``keep_last``) and a
    fresh ``PATH`` is opened, so at most ~2x ``rotate_bytes`` is ever
    on disk.  Path sinks only; ignored for stream objects, which have
    no name to rename.
    """

    def __init__(self, path_or_stream: Union[str, IO[str]],
                 rotate_bytes: Optional[int] = None):
        if isinstance(path_or_stream, (str, bytes)):
            self._path: Optional[str] = os.fspath(path_or_stream)
            self._fh: IO[str] = open(path_or_stream, "a", encoding="utf-8")
            self._owns = True
        else:
            self._path = None
            self._fh = path_or_stream
            self._owns = False
        self._rotate_bytes = (int(rotate_bytes)
                              if rotate_bytes and self._path else None)
        self._lock = threading.Lock()

    def emit(self, event_type: str, **fields: Any) -> Dict[str, Any]:
        record = _build_event(event_type, fields)
        line = json.dumps(sanitize(record), allow_nan=False,
                          sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            if (self._rotate_bytes is not None
                    and self._fh.tell() >= self._rotate_bytes):
                self._rotate_locked()
        return record

    def _rotate_locked(self) -> None:
        """Rename the full file to ``.1`` and reopen fresh (lock held)."""
        assert self._path is not None
        self._fh.close()
        os.replace(self._path, self._path + ".1")
        self._fh = open(self._path, "a", encoding="utf-8")

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self) -> "EventStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _build_event(event_type: str, fields: Dict[str, Any]) -> Dict[str, Any]:
    if event_type not in EVENT_SCHEMA:
        raise ValueError(
            f"unknown event type {event_type!r}; known: "
            f"{sorted(EVENT_SCHEMA)}")
    record = {"event": event_type, "t": time.perf_counter(),
              "solve_id": current_solve_id()}
    record.update(_SCOPE_FIELDS.get())
    record.update(fields)
    missing = [f for f in EVENT_SCHEMA[event_type] if f not in record]
    if missing:
        raise ValueError(
            f"event {event_type!r} missing required fields: {missing}")
    return record


def validate_event(record: Dict[str, Any]) -> Dict[str, Any]:
    """Check one parsed JSONL record against the schema; returns it.

    Raises ``ValueError`` on an unknown type, a missing envelope or
    required field, or a payload that is not strict JSON (tested by
    re-serializing with ``allow_nan=False``).
    """
    if not isinstance(record, dict):
        raise ValueError(f"event record must be an object, got "
                         f"{type(record).__name__}")
    etype = record.get("event")
    if etype not in EVENT_SCHEMA:
        raise ValueError(f"unknown event type {etype!r}")
    for field in ("t", "solve_id") + EVENT_SCHEMA[etype]:
        if field not in record:
            raise ValueError(f"event {etype!r} missing field {field!r}")
    if not isinstance(record["t"], (int, float)):
        raise ValueError(f"event timestamp must be numeric, got "
                         f"{record['t']!r}")
    json.dumps(record, allow_nan=False)   # strict-JSON payload check
    return record


def read_events(path: str) -> list:
    """Parse and schema-validate a solve-trace JSONL file.

    The single reader every consumer of ``--trace-events`` output goes
    through (tools/solve_report.py, tools/validate_trace.py), so "which
    traces are acceptable" has one definition.  Blank lines are
    skipped; any other violation raises ``ValueError`` naming
    ``path:lineno``.  An event-free file is an error - for a trace
    consumer there is nothing to do, and for the CI gate silence means
    the instrumentation broke.
    """
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(validate_event(json.loads(line)))
            except (ValueError, json.JSONDecodeError) as e:
                raise ValueError(f"{path}:{lineno}: {e}") from e
    if not out:
        raise ValueError(f"{path}: no events")
    return out


# ---------------------------------------------------------------------------
# in-process subscribers (the ops plane's live event bus)

class Subscription:
    """A bounded in-process event ring one consumer drains.

    The emitter side (:func:`emit`, any thread, possibly mid-solve
    epilogue) NEVER blocks on a subscriber: ``_offer`` is O(1) under
    the subscription's own lock, and when the ring is full the OLDEST
    event is dropped and counted - in :attr:`dropped` and in the
    process-wide ``events_dropped_total`` counter - so a stalled
    consumer (a slow SSE client, a wedged scraper) can never apply
    backpressure to the serving path.  Consumers drain with
    :meth:`pop` (blocking, timeout) or :meth:`drain` (everything
    buffered, non-blocking).
    """

    def __init__(self, maxlen: int = 1024):
        if maxlen < 1:
            raise ValueError(f"subscription maxlen must be >= 1, got "
                             f"{maxlen}")
        self.maxlen = int(maxlen)
        self._ring: deque = deque()
        self._cond = threading.Condition()
        self.dropped = 0
        self.closed = False

    def _offer(self, record: Dict[str, Any]) -> None:
        """Emitter side: append without ever blocking (drop-oldest)."""
        dropped = False
        with self._cond:
            if self.closed:
                return
            if len(self._ring) >= self.maxlen:
                self._ring.popleft()
                self.dropped += 1
                dropped = True
            self._ring.append(record)
            self._cond.notify_all()
        if dropped:
            # registry import deferred: events must stay importable
            # without pulling the metrics module at module-import time
            from .registry import REGISTRY

            REGISTRY.counter(
                "events_dropped_total",
                "events dropped by full in-process subscriber rings "
                "(bounded bus, never blocks the emitter)").inc()

    def pop(self, timeout: Optional[float] = None
            ) -> Optional[Dict[str, Any]]:
        """Oldest buffered event, waiting up to ``timeout`` seconds
        (``None`` = wait forever).  ``None`` on timeout or once the
        subscription is closed and drained."""
        with self._cond:
            while not self._ring:
                if self.closed:
                    return None
                if not self._cond.wait(timeout=timeout):
                    return None
            return self._ring.popleft()

    def drain(self) -> list:
        """Everything buffered right now (non-blocking, FIFO)."""
        with self._cond:
            out = list(self._ring)
            self._ring.clear()
            return out

    def close(self) -> None:
        """Detach: stops receiving and wakes any blocked ``pop``."""
        with self._cond:
            self.closed = True
            self._cond.notify_all()


_SUBS_LOCK = threading.Lock()
_SUBS: Tuple["Subscription", ...] = ()


def subscribe(maxlen: int = 1024) -> Subscription:
    """Attach a bounded in-process subscriber to the event stream.

    Subscribers receive every event :func:`emit` produces - sink or no
    sink - as sanitized strict-JSON-ready dicts.  A live subscriber
    makes :func:`active` true, so derived instrumentation (spans, the
    jaxpr cost walk) runs for it exactly as it would for a file sink;
    the solve body itself stays bit-identical (everything here is
    host-side, proved by ``tests/test_ops_plane.py``).
    """
    global _SUBS
    sub = Subscription(maxlen=maxlen)
    with _SUBS_LOCK:
        _SUBS = _SUBS + (sub,)
    return sub


def unsubscribe(sub: Subscription) -> None:
    """Detach and close a subscription (idempotent)."""
    global _SUBS
    with _SUBS_LOCK:
        _SUBS = tuple(s for s in _SUBS if s is not sub)
    sub.close()


# ---------------------------------------------------------------------------
# module-level default sink (what instrumentation sites talk to)

_SINK: Optional[EventStream] = None


def configure(path_or_stream: Union[str, IO[str], None],
              rotate_bytes: Optional[int] = None) -> None:
    """Install (or with ``None`` remove) the process-default event sink.

    Instrumented call sites all emit through this module-level sink, so
    one ``configure("trace.jsonl")`` - or the CLI's
    ``--trace-events PATH`` - traces every solve in the process.
    ``rotate_bytes`` passes through to :class:`EventStream` (path
    sinks only): long-running serve processes rotate to ``PATH.1``
    instead of growing without bound.
    """
    global _SINK
    if _SINK is not None:
        _SINK.close()
        _SINK = None
    if path_or_stream is not None:
        _SINK = EventStream(path_or_stream, rotate_bytes=rotate_bytes)


def active() -> bool:
    """True when anyone is listening: a default sink is installed or
    at least one in-process subscriber is attached."""
    return _SINK is not None or bool(_SUBS)


def emit(event_type: str, **fields: Any) -> Optional[Dict[str, Any]]:
    """Emit to the default sink and every attached subscriber; a cheap
    no-op when nobody is listening.

    Returns the emitted record (or ``None`` when inactive) so call
    sites can reuse the payload.  Subscribers receive the SANITIZED
    record (non-finite floats -> ``None``) - exactly what the JSONL
    sink would have serialized, so SSE consumers and file readers see
    one payload shape.
    """
    sink, subs = _SINK, _SUBS
    if sink is None and not subs:
        return None
    if sink is not None:
        record = sink.emit(event_type, **fields)
    else:
        record = _build_event(event_type, fields)
    if subs:
        clean = sanitize(record)
        for sub in subs:
            sub._offer(clean)
    return record


@contextlib.contextmanager
def capture() -> Iterator[io.StringIO]:
    """Route the default sink into an in-memory buffer for the block
    (tests; restores the previous sink on exit)."""
    global _SINK
    prev = _SINK
    buf = io.StringIO()
    _SINK = EventStream(buf)
    try:
        yield buf
    finally:
        _SINK = prev
