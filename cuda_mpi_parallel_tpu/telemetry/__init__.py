"""solve-trace: the framework's observability subsystem.

The reference's entire observability story is a ``printf`` of the
solution vector (``CUDACG.cu:361-365``, SURVEY quirk Q7) - no iteration
count, no timing, no communication accounting.  This package closes
that gap with four composable parts:

* :mod:`.registry` - a process-wide metrics registry (counters, gauges,
  histograms with labels; JSON and Prometheus-text exposition);
* :mod:`.events` - a JSONL solve-trace emitter with typed events
  (``solve_start``, ``engine_selected``, ``eligibility_rejected``,
  ``dist_cache_hit``/``dist_cache_miss``, ``check_block``,
  ``comm_cost``, ``solve_end``) carrying monotonic timestamps and a
  solve id;
* :mod:`.cost` - jaxpr-derived op accounting: walk a traced solve to
  count SpMV/dot/psum/ppermute per loop trip and derive halo bytes
  from the collective payload avals, so per-solve communication volume
  is STATIC per iteration x ``CGResult.iterations`` - the compiled hot
  loop is never perturbed and never forced to sync (graftlint GL105
  clean by construction);
* :mod:`.session` - ``observe_solve(...)``, a context manager that
  composes ``utils.timing.Timer`` phase sections with ``jax.profiler``
  traces and the event stream;
* :mod:`.flight` - the convergence flight recorder: a fixed-size,
  stride-decimated device-side ring buffer of ``(iteration, ||r||^2,
  alpha, beta)`` carried in the solvers' ``lax.while_loop`` state and
  fetched once post-solve (zero host round-trips in the hot loop);
* :mod:`.health` - solve-health diagnostics over the flight record:
  CG-Lanczos Ritz/condition estimates and stagnation / plateau /
  divergence classification, emitted as ``solve_health`` events and
  decay-rate / kappa gauges;
* :mod:`.shardscope` - static per-shard load/imbalance accounting
  computed at partition time (rows, nnz, padding overhead, halo bytes
  per neighbor), emitted as ``shard_profile`` events and
  ``shard="k"``-labeled gauges;
* :mod:`.memscope` - the device-memory observatory: per-shard HBM
  footprint accounting (exact partition bytes + modeled solver working
  set + jaxpr-liveness transient peak), FITS/TIGHT/OVERFLOW
  classification against ``MachineModel.hbm_bytes``, and the typed
  ``MemoryBudgetError`` the planner and serve tier refuse over-budget
  work with before any compile;
* :mod:`.roofline` - the analytic machine model (table-sourced TPU
  numbers, self-calibrated CPU) joined with measured wall time:
  achieved-vs-peak efficiency %, arithmetic intensity, memory- vs
  comm-bound classification;
* :mod:`.report` - the fusion layer: one human-readable solve report
  (text + JSON) over all of the above, and the Chrome-trace/Perfetto
  timeline exporter (one track per shard, one for host phases);
* :mod:`.phasetrace` - the measured phase profiler: phase-isolated
  step functions compiled from a partitioned operator's own building
  blocks (halo exchange alone - per round, local SpMV alone - per
  shard, dot+psum reduction alone), timed under the real mesh; feeds
  measured Perfetto spans, per-link wire bandwidths and the
  phase-resolved calibration observations;
* :mod:`.calibrate` - the runtime-measured machine model: fit the
  planner/roofline cost parameters (gather slowdown, net bandwidth)
  from observed solves, track predicted-vs-measured drift as gauges
  and extended ``partition_plan`` events, and persist calibrations in
  the on-disk measured-artifact cache so ``solve_sequence`` replans
  solve k+1 on the model calibrated from solve k.

Everything is opt-in: with no event sink configured and metrics
untouched, every instrumentation hook in the solver/parallel layers is
a cheap host-side no-op, and the traced computation is bit-identical
either way (asserted by tests/test_cost_accounting.py).
"""
from __future__ import annotations

from . import (
    calibrate,
    cost,
    events,
    fleet,
    flight,
    health,
    memscope,
    phasetrace,
    registry,
    report,
    roofline,
    session,
    shardscope,
    slo,
    tracing,
)
from .phasetrace import PhaseProfile
from .calibrate import CalibrationFit, DriftReport
from .events import EventStream, configure, emit, validate_event
from .flight import FlightConfig, FlightRecord
from .health import SolveHealth, assess_solve_health
from .memscope import MemoryBudgetError, MemoryFootprint
from .registry import REGISTRY, MetricsRegistry
from .report import SolveReport, perfetto_trace, validate_perfetto
from .roofline import MachineModel, RooflineReport
from .session import observe_solve
from .shardscope import ShardReport, shard_report
from .slo import SLOConfig, SLOTracker, SLOWindow
from .tracing import RequestTrace


#: set by force_active(): opts into the build-time cost accounting even
#: with no event sink (the CLI's --metrics does this - comm gauges are
#: useful without a trace file)
_FORCED = [False]


def force_active(on: bool = True) -> None:
    """Opt into telemetry-driven derived work (the build-time jaxpr cost
    walk) without configuring an event sink.  Metrics counters always
    run; this flag only gates the extras that cost something."""
    _FORCED[0] = bool(on)


def active() -> bool:
    """True when any telemetry consumer is attached (an event sink is
    configured, or ``force_active`` was called).  Instrumentation sites
    use this to skip work - e.g. the build-time jaxpr cost walk in
    ``parallel.dist_cg`` - that only exists to feed telemetry."""
    return _FORCED[0] or events.active()


__all__ = [
    "CalibrationFit",
    "DriftReport",
    "EventStream",
    "FlightConfig",
    "FlightRecord",
    "MachineModel",
    "MemoryBudgetError",
    "MemoryFootprint",
    "MetricsRegistry",
    "PhaseProfile",
    "REGISTRY",
    "RequestTrace",
    "RooflineReport",
    "SLOConfig",
    "SLOTracker",
    "SLOWindow",
    "ShardReport",
    "SolveHealth",
    "SolveReport",
    "active",
    "assess_solve_health",
    "calibrate",
    "configure",
    "cost",
    "emit",
    "events",
    "fleet",
    "flight",
    "health",
    "memscope",
    "observe_solve",
    "perfetto_trace",
    "phasetrace",
    "registry",
    "report",
    "roofline",
    "session",
    "shard_report",
    "shardscope",
    "slo",
    "tracing",
    "validate_event",
    "validate_perfetto",
]
