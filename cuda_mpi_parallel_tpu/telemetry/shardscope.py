"""Per-shard load and communication accounting at partition time.

The node-aware SpMV literature (PAPERS: arXiv 1612.08060, 1112.5588)
is unanimous about what kills row-partitioned solvers at scale: not
total work but *skew* - one shard with fatter rows or a heavier halo
stalls every ``psum`` for the whole mesh, every iteration.  PRs 2-3
made the repo's telemetry per-*solve* (aggregate collective counts,
flight-recorded convergence); this module makes it per-*shard*.

Everything here is **static and host-side**: the numbers are computed
from the partition layout the moment it is built (``numpy`` over the
same arrays the partitioner just produced), never from device state -
so the accounting can never perturb a compiled solve (the jaxpr-
identity proof in tests/test_cost_accounting.py covers this layer
too).  A :class:`ShardReport` answers, per shard ``k``:

* how many real (unpadded) rows and live matrix entries it owns;
* how many entry *slots* it was allocated (uniform-shape padding -
  XLA needs identical local shapes, unlike ragged MPI ranks - plus
  the shift-ELL packers' sheet geometry), i.e. wasted multiply work;
* how many bytes it sends/receives per matvec, to which neighbor
  (ring ``ppermute`` schedules are neighbor-resolved; ``all_gather``
  is attributed to the mesh at large).

Byte semantics match :mod:`.cost`: **payload bytes per device per
matvec** - what the collective's input avals carry, not wire-level
algorithm bytes (an all_gather's ring implementation may move more).

Imbalance is summarized two ways, following the SpMV-skew papers:
``max/mean`` (the stall factor: a psum waits for the heaviest shard)
and the Gini coefficient (how concentrated the load is overall).

Emission: :func:`note_report` publishes a ``shard_profile`` event and
per-shard labeled gauges (``shard="k"``) when telemetry is active, and
always parks the report in a module slot for the CLI's ``--report``
(mirroring ``dist_cg.last_comm_cost``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "ShardReport",
    "gather_wire_bytes",
    "gini",
    "last_shard_report",
    "max_over_mean",
    "note_report",
    "report_for_ranges",
    "report_gather_csr",
    "report_partition_csr",
    "report_ring_csr",
    "report_ring_shiftell",
    "report_stencil",
    "reset_last_shard_report",
    "shard_report",
]


def max_over_mean(values) -> float:
    """The stall factor of a per-shard quantity: ``max / mean``.

    1.0 is perfect balance; a psum-synchronized loop runs at the speed
    of the max shard, so this factor IS the slowdown versus a
    perfectly rebalanced partition.  Zero-mean (empty) inputs report
    1.0 - nothing to stall on."""
    arr = np.asarray(values, dtype=np.float64)
    if arr.size == 0:
        return 1.0
    mean = float(arr.mean())
    if mean == 0.0:
        return 1.0
    return float(arr.max()) / mean


def gini(values) -> float:
    """Gini coefficient of a nonnegative per-shard quantity.

    0 = perfectly even, ->1 = all load on one shard.  The standard
    mean-absolute-difference form, O(P^2) - P is a device count, never
    large."""
    arr = np.asarray(values, dtype=np.float64)
    if arr.size == 0:
        return 0.0
    mean = float(arr.mean())
    if mean == 0.0:
        return 0.0
    diff_sum = float(np.abs(arr[:, None] - arr[None, :]).sum())
    return diff_sum / (2.0 * arr.size * arr.size * mean)


@dataclasses.dataclass(frozen=True)
class ShardReport:
    """Static per-shard accounting of one partitioned operator.

    ``halo_send_bytes``/``halo_recv_bytes`` are per matvec per shard
    (payload semantics, see module docstring); multiply by the
    method's matvecs/iteration and the solve's iteration count for
    whole-solve volume.  ``neighbors[k]`` lists ``(peer, bytes)``
    sends - ``peer`` is a shard index, or ``-1`` for an unattributed
    collective (all_gather).
    """

    kind: str                     # partition family (csr-allgather, ...)
    n_shards: int
    n_global: int
    n_global_padded: int
    n_local: int                  # padded rows per shard
    rows: np.ndarray              # (P,) real rows owned
    nnz: np.ndarray               # (P,) live matrix entries owned
    slots: np.ndarray             # (P,) allocated entry slots
    halo_send_bytes: np.ndarray   # (P,) bytes sent per matvec
    halo_recv_bytes: np.ndarray   # (P,) bytes received per matvec
    neighbors: Tuple[Tuple[Tuple[int, int], ...], ...]
    #: which partition plan produced this layout ("even" = the legacy
    #: uniform row split; planned partitions label reports with their
    #: reorder+split lane, e.g. "rcm+nnz")
    plan: str = "even"
    #: (P,) per-shard device bytes the partition pins for the life of
    #: a dispatcher - ``telemetry.memscope``'s numbers (ONE shared
    #: definition: ``matrix_bytes_per_shard`` for built partitions,
    #: ``csr_slot_bytes(slots)`` for the planner's predicted report),
    #: so shard_profile events carry bytes alongside nnz/slots.
    #: ``None`` for reports rebuilt from pre-memscope event files.
    persistent_bytes: Optional[np.ndarray] = None

    # ---- derived -----------------------------------------------------
    def padding_overhead(self) -> np.ndarray:
        """Per-shard wasted-slot fraction: ``(slots - nnz) / slots``.

        The fraction of allocated multiply work that is padding (zero
        entries plus synthetic unit-diagonal padding rows).  0.0 when a
        shard has no slots at all."""
        slots = self.slots.astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            frac = (slots - self.nnz) / slots
        return np.where(slots > 0, frac, 0.0)

    def imbalance(self) -> dict:
        """The skew digest: max/mean + Gini for each load axis."""
        return {
            "rows_max_over_mean": max_over_mean(self.rows),
            "nnz_max_over_mean": max_over_mean(self.nnz),
            "nnz_gini": gini(self.nnz),
            "halo_send_max_over_mean": max_over_mean(self.halo_send_bytes),
            "halo_send_gini": gini(self.halo_send_bytes),
            "padding_overhead_total": float(
                (self.slots.sum() - self.nnz.sum())
                / max(int(self.slots.sum()), 1)),
        }

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "plan": self.plan,
            "n_shards": self.n_shards,
            "n_global": self.n_global,
            "n_global_padded": self.n_global_padded,
            "n_local": self.n_local,
            "rows": [int(v) for v in self.rows],
            "nnz": [int(v) for v in self.nnz],
            "slots": [int(v) for v in self.slots],
            "halo_send_bytes": [int(v) for v in self.halo_send_bytes],
            "halo_recv_bytes": [int(v) for v in self.halo_recv_bytes],
            "padding_overhead": [round(float(v), 6)
                                 for v in self.padding_overhead()],
            "neighbors": [[[int(p), int(b)] for p, b in ns]
                          for ns in self.neighbors],
            "imbalance": self.imbalance(),
            "persistent_bytes": (
                None if self.persistent_bytes is None
                else [int(v) for v in self.persistent_bytes]),
        }

    @classmethod
    def from_json(cls, data: dict) -> "ShardReport":
        """Rebuild from :meth:`to_json` output (what a ``shard_profile``
        event carries - tools/solve_report.py's input)."""
        return cls(
            kind=str(data["kind"]), n_shards=int(data["n_shards"]),
            n_global=int(data["n_global"]),
            n_global_padded=int(data["n_global_padded"]),
            n_local=int(data["n_local"]),
            rows=np.asarray(data["rows"], dtype=np.int64),
            nnz=np.asarray(data["nnz"], dtype=np.int64),
            slots=np.asarray(data["slots"], dtype=np.int64),
            halo_send_bytes=np.asarray(data["halo_send_bytes"],
                                       dtype=np.int64),
            halo_recv_bytes=np.asarray(data["halo_recv_bytes"],
                                       dtype=np.int64),
            neighbors=tuple(tuple((int(p), int(b)) for p, b in ns)
                            for ns in data.get("neighbors", [])),
            plan=str(data.get("plan", "even")),
            persistent_bytes=(
                None if data.get("persistent_bytes") is None
                else np.asarray(data["persistent_bytes"],
                                dtype=np.int64)),
        )

    def table(self) -> str:
        """The per-shard text table the CLI report embeds."""
        head = (f"{'shard':>5}  {'rows':>9}  {'nnz':>11}  {'pad%':>6}  "
                f"{'halo out B/mv':>13}  {'halo in B/mv':>12}")
        pad = self.padding_overhead() * 100.0
        lines = [head]
        for k in range(self.n_shards):
            lines.append(
                f"{k:>5}  {int(self.rows[k]):>9}  {int(self.nnz[k]):>11}  "
                f"{pad[k]:>6.1f}  {int(self.halo_send_bytes[k]):>13}  "
                f"{int(self.halo_recv_bytes[k]):>12}")
        imb = self.imbalance()
        lines.append(
            f"imbalance: nnz max/mean {imb['nnz_max_over_mean']:.3f} "
            f"(gini {imb['nnz_gini']:.3f}), halo max/mean "
            f"{imb['halo_send_max_over_mean']:.3f}, padding overhead "
            f"{imb['padding_overhead_total'] * 100:.1f}% "
            f"[plan: {self.plan}]")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# builders (one per partition family)

def _row_ranges(n: int, n_local: int, n_shards: int,
                row_ranges=None) -> Tuple[Tuple[int, int], ...]:
    """The contiguous row ranges of a partition: the planner's explicit
    ranges when present, else the legacy even split they generalize."""
    if row_ranges is not None:
        return tuple((int(lo), int(hi)) for lo, hi in row_ranges)
    return tuple((min(s * n_local, n), min((s + 1) * n_local, n))
                 for s in range(n_shards))


def _real_rows(n: int, n_local: int, n_shards: int,
               row_ranges=None) -> np.ndarray:
    ranges = _row_ranges(n, n_local, n_shards, row_ranges)
    return np.array([hi - lo for lo, hi in ranges], dtype=np.int64)


def _csr_shard_nnz(a, n_local: int, n_shards: int,
                   row_ranges=None) -> np.ndarray:
    """Exact live entries per row block, from the global indptr (the
    partitioners' padded arrays cannot distinguish a real unit diagonal
    from a synthetic padding-row one; the source matrix can)."""
    indptr = np.asarray(a.indptr).astype(np.int64)
    ranges = _row_ranges(a.shape[0], n_local, n_shards, row_ranges)
    return np.array([int(indptr[hi] - indptr[lo]) if hi > lo else 0
                     for lo, hi in ranges], dtype=np.int64)


def _partition_persistent_bytes(parts) -> np.ndarray:
    """memscope's exact pinned-bytes account of a built partition -
    imported lazily (memscope also consumes this module)."""
    from .memscope import matrix_bytes_per_shard

    return matrix_bytes_per_shard(parts)


def _plan_label(parts, plan) -> str:
    if plan is not None:
        return str(plan)
    return "planned" if getattr(parts, "row_ranges", None) is not None \
        else "even"


def _ring_halo(n_shards: int, payload: int):
    """Ring x-rotation traffic: ``n_shards - 1`` ppermute steps per
    matvec, each carrying ``payload`` bytes; shard ``k`` sends to
    ``(k - 1) % P`` and receives from ``(k + 1) % P`` (the schedule in
    ``parallel.operators.DistCSRRing``)."""
    total = (n_shards - 1) * payload
    send = np.full(n_shards, total, dtype=np.int64)
    recv = send.copy()
    neighbors = tuple(
        (((k - 1) % n_shards, total),) if n_shards > 1 else ()
        for k in range(n_shards))
    return send, recv, neighbors


def gather_wire_bytes(report: "ShardReport") -> int:
    """Per-device per-matvec interconnect bytes of the gather halo
    exchange (``parallel.exchange``) on the layout ``report``
    describes - REQUIRES coupling semantics (``report_for_ranges``),
    whose ``neighbors`` list the distinct coupled-entry bytes per
    (owner, reader) pair.

    The schedule packs pair ``j -> (j + r) % P`` into rotation round
    ``r`` and pads each round to the max over senders (``shard_map``
    needs one static shape per collective), so the wire is
    ``sum_r max_j bytes(j -> (j + r) % P)`` - exactly what
    ``exchange.GatherSchedule.wire_bytes_per_matvec`` reports for the
    built schedule, here computable from the report alone (what the
    planner scores before anything is built).  Rounds with no coupled
    pair contribute zero (they are dropped from the wire entirely).
    """
    p = report.n_shards
    if p <= 1:
        return 0
    pair = {}
    for k, ns in enumerate(report.neighbors):
        for peer, b in ns:
            if peer >= 0:
                pair[(k, peer)] = int(b)
    total = 0
    for shift in range(1, p):
        total += max(pair.get((k, (k + shift) % p), 0)
                     for k in range(p))
    return total


def report_gather_csr(a, parts, plan=None) -> ShardReport:
    """Accounting for ``partition.partition_csr(exchange='gather')``
    output (the ``DistCSRGather`` packed-ppermute schedule).

    Unlike every fixed-payload schedule, the wire here IS the coupled
    halo: per round ``r`` shard ``k`` sends its padded slab
    (``m_r * itemsize`` bytes, the round's max live count over
    senders) to ``(k + r) % P`` and receives the same from
    ``(k - r) % P`` - so sends and receives are uniform across shards
    and ``neighbors`` resolves per rotation peer.  These are the REAL
    per-matvec wire bytes (padding included: padded slots ride the
    links too), matching the jaxpr-derived ``wire_bytes`` account of
    ``telemetry.cost`` exactly."""
    sched = parts.halo
    n_shards, n_local = parts.n_shards, parts.n_local
    ranges = getattr(parts, "row_ranges", None)
    itemsize = np.asarray(parts.data).dtype.itemsize
    nnz = _csr_shard_nnz(a, n_local, n_shards, ranges)
    slots = np.full(n_shards, parts.data.shape[1], dtype=np.int64)
    per_device = sched.wire_bytes_per_matvec(itemsize)
    send = np.full(n_shards, per_device, dtype=np.int64)
    recv = send.copy()
    neighbors = tuple(
        tuple(((k + r.shift) % n_shards, r.m * itemsize)
              for r in sched.rounds)
        for k in range(n_shards))
    return ShardReport(
        kind="csr-gather", n_shards=n_shards, n_global=parts.n_global,
        n_global_padded=parts.n_global_padded, n_local=n_local,
        rows=_real_rows(parts.n_global, n_local, n_shards, ranges),
        nnz=nnz,
        slots=slots, halo_send_bytes=send, halo_recv_bytes=recv,
        neighbors=neighbors, plan=_plan_label(parts, plan),
        persistent_bytes=_partition_persistent_bytes(parts))


def report_partition_csr(a, parts, plan=None) -> ShardReport:
    """Accounting for ``partition.partition_csr`` output (the
    ``all_gather`` ``DistCSR`` schedule; gather-exchange partitions
    dispatch to :func:`report_gather_csr`)."""
    if getattr(parts, "halo", None) is not None:
        return report_gather_csr(a, parts, plan=plan)
    n_shards, n_local = parts.n_shards, parts.n_local
    ranges = getattr(parts, "row_ranges", None)
    itemsize = np.asarray(parts.data).dtype.itemsize
    nnz = _csr_shard_nnz(a, n_local, n_shards, ranges)
    slots = np.full(n_shards, parts.data.shape[1], dtype=np.int64)
    # all_gather payload: each shard contributes its own x block and
    # receives every other shard's (payload semantics - see module doc)
    send = np.full(n_shards, n_local * itemsize, dtype=np.int64)
    recv = np.full(n_shards, (n_shards - 1) * n_local * itemsize,
                   dtype=np.int64)
    neighbors = tuple(((-1, int(send[k])),) if n_shards > 1 else ()
                      for k in range(n_shards))
    return ShardReport(
        kind="csr-allgather", n_shards=n_shards, n_global=parts.n_global,
        n_global_padded=parts.n_global_padded, n_local=n_local,
        rows=_real_rows(parts.n_global, n_local, n_shards, ranges),
        nnz=nnz,
        slots=slots, halo_send_bytes=send, halo_recv_bytes=recv,
        neighbors=neighbors, plan=_plan_label(parts, plan),
        persistent_bytes=_partition_persistent_bytes(parts))


def report_ring_csr(a, parts, plan=None) -> ShardReport:
    """Accounting for ``partition.ring_partition_csr`` output (the
    ``ppermute`` x-rotation ``DistCSRRing`` schedule)."""
    n_shards, n_local = parts.n_shards, parts.n_local
    ranges = getattr(parts, "row_ranges", None)
    itemsize = np.asarray(parts.data[0]).dtype.itemsize
    nnz = _csr_shard_nnz(a, n_local, n_shards, ranges)
    slots = np.full(n_shards,
                    sum(d.shape[1] for d in parts.data), dtype=np.int64)
    send, recv, neighbors = _ring_halo(n_shards, n_local * itemsize)
    return ShardReport(
        kind="csr-ring", n_shards=n_shards, n_global=parts.n_global,
        n_global_padded=parts.n_global_padded, n_local=n_local,
        rows=_real_rows(parts.n_global, n_local, n_shards, ranges),
        nnz=nnz,
        slots=slots, halo_send_bytes=send, halo_recv_bytes=recv,
        neighbors=neighbors, plan=_plan_label(parts, plan),
        persistent_bytes=_partition_persistent_bytes(parts))


def report_ring_shiftell(a, parts, plan=None) -> ShardReport:
    """Accounting for ``partition.ring_partition_shiftell`` (f32/f64)
    AND ``ring_partition_shiftell_df64`` output.

    Slot counts are the packed sheet geometry per shard: each step's
    value planes hold ``C_t * kc * (h + 1) * 128`` slots (identical
    across owners per step - shard_map's uniform-shape constraint).
    The df64 packer rotates BOTH x planes in one stacked ppermute, so
    its per-step payload doubles."""
    n_shards, n_local = parts.n_shards, parts.n_local
    ranges = getattr(parts, "row_ranges", None)
    df64 = hasattr(parts, "vals_hi")
    vals = parts.vals_hi if df64 else parts.vals
    per_shard_slots = sum(
        int(np.prod(v.shape[1:])) for v in vals)
    nnz = _csr_shard_nnz(a, n_local, n_shards, ranges)
    slots = np.full(n_shards, per_shard_slots, dtype=np.int64)
    payload = n_local * (8 if df64 else np.asarray(vals[0]).dtype.itemsize)
    send, recv, neighbors = _ring_halo(n_shards, payload)
    return ShardReport(
        kind="ring-shiftell-df64" if df64 else "ring-shiftell",
        n_shards=n_shards, n_global=parts.n_global,
        n_global_padded=parts.n_global_padded, n_local=n_local,
        rows=_real_rows(parts.n_global, n_local, n_shards, ranges),
        nnz=nnz,
        slots=slots, halo_send_bytes=send, halo_recv_bytes=recv,
        neighbors=neighbors, plan=_plan_label(parts, plan),
        persistent_bytes=_partition_persistent_bytes(parts))


def report_stencil(local_grid, n_shards: int, itemsize: int,
                   points: int, kind: str) -> ShardReport:
    """Accounting for a slab-partitioned matrix-free stencil.

    Rows and (implicit) entries are uniform by construction; the per-
    shard variation is the halo - interior shards exchange one boundary
    plane with BOTH neighbors, edge shards with one (``lax.ppermute``'s
    fill-with-zeros edge is the Dirichlet boundary,
    ``parallel.halo.exchange_halo``)."""
    n_rows = int(np.prod(local_grid))
    plane = int(np.prod(local_grid[1:])) if len(local_grid) > 1 else 1
    plane_bytes = plane * itemsize
    rows = np.full(n_shards, n_rows, dtype=np.int64)
    nnz = np.full(n_shards, points * n_rows, dtype=np.int64)
    send = np.zeros(n_shards, dtype=np.int64)
    neighbors = []
    for k in range(n_shards):
        ns = []
        if k + 1 < n_shards:   # forward shift: k's last plane -> k+1
            ns.append((k + 1, plane_bytes))
        if k > 0:              # backward shift: k's first plane -> k-1
            ns.append((k - 1, plane_bytes))
        send[k] = sum(b for _, b in ns)
        neighbors.append(tuple(ns))
    # the shift pairs are symmetric: bytes received == bytes sent
    return ShardReport(
        kind=kind, n_shards=n_shards,
        n_global=n_rows * n_shards, n_global_padded=n_rows * n_shards,
        n_local=n_rows, rows=rows, nnz=nnz, slots=nnz.copy(),
        halo_send_bytes=send, halo_recv_bytes=send.copy(),
        neighbors=tuple(neighbors))


def shard_report(a, parts, plan=None) -> ShardReport:
    """Dispatch on the partition family (the four partitioner output
    types in ``parallel.partition``)."""
    from ..parallel import partition as part

    if isinstance(parts, part.PartitionedCSR):
        return report_partition_csr(a, parts, plan=plan)
    if isinstance(parts, part.RingPartitionedCSR):
        return report_ring_csr(a, parts, plan=plan)
    if isinstance(parts, (part.RingPartitionedShiftELL,
                          part.RingPartitionedShiftELLDF64)):
        return report_ring_shiftell(a, parts, plan=plan)
    raise TypeError(f"no shard accounting for {type(parts).__name__}")


def report_for_ranges(a, row_ranges, *, itemsize=None,
                      plan: str = "ranges") -> ShardReport:
    """Static accounting for an ARBITRARY contiguous row split of a CSR
    matrix - the shared code path between the partition planner
    (scoring candidate splits before any partition is built) and the
    post-hoc profiler (re-reporting a split that already ran).

    Differences from the schedule-specific builders above:

    * ``slots`` is what ``partition.partition_csr`` WOULD allocate for
      these ranges: every shard padded to the max of (nnz + padding
      rows) - the uniform-shape cost of the split, before any packer
      geometry;
    * halo bytes are COUPLING-based, not schedule-based: shard ``k``
      receives one x entry per *distinct* off-range column its rows
      reference and sends one per distinct local row referenced by
      another shard's rows.  The allgather/ring schedules move a fixed
      payload regardless of sparsity; the coupling volume is the part a
      reordering can actually shrink, which is what the planner needs
      to rank candidate permutations (a gather-based halo exchange
      would move exactly these bytes).

    ``neighbors[k]`` lists ``(peer, bytes)`` sends per matvec.
    """
    indptr = np.asarray(a.indptr).astype(np.int64)
    indices = np.asarray(a.indices).astype(np.int64)
    n = int(a.shape[0])
    n_shards = len(row_ranges)
    ranges = tuple((int(lo), int(hi)) for lo, hi in row_ranges)
    if itemsize is None:
        itemsize = np.asarray(a.data).dtype.itemsize
    rows = np.array([hi - lo for lo, hi in ranges], dtype=np.int64)
    nnz = _csr_shard_nnz(a, 0, n_shards, ranges)
    n_local = max(int(rows.max()) if n_shards else 0, 1)
    counts = nnz + (n_local - rows)  # padding rows carry a unit diagonal
    slots = np.full(n_shards, int(counts.max()) if n_shards else 0,
                    dtype=np.int64)

    # shard id of every row (and so of every column, SPD => square)
    starts = np.array([lo for lo, _ in ranges] + [n], dtype=np.int64)
    shard_of = np.repeat(np.arange(n_shards, dtype=np.int64),
                         np.diff(starts))
    entry_rows = np.repeat(np.arange(n, dtype=np.int64),
                           np.diff(indptr))
    row_shard = shard_of[entry_rows]
    col_shard = shard_of[indices]
    off = row_shard != col_shard
    send = np.zeros(n_shards, dtype=np.int64)
    recv = np.zeros(n_shards, dtype=np.int64)
    pair_counts: dict = {}
    if off.any():
        # distinct (referencing shard, column) pairs: one x entry each
        # (all vectorized - the planner calls this per candidate lane,
        # and a 1M-row FEM matrix has millions of cross-shard pairs)
        keys = row_shard[off] * np.int64(n) + indices[off]
        uniq = np.unique(keys)
        u_reader = uniq // n          # the shard that needs the entry
        u_owner = shard_of[uniq % n]  # the shard that owns the column
        np.add.at(recv, u_reader, itemsize)
        np.add.at(send, u_owner, itemsize)
        pair_keys, counts = np.unique(
            u_owner * np.int64(n_shards) + u_reader, return_counts=True)
        pair_counts = {
            (int(k // n_shards), int(k % n_shards)): int(c) * itemsize
            for k, c in zip(pair_keys, counts)}
    neighbors = tuple(
        tuple(sorted((peer, b) for (owner, peer), b in pair_counts.items()
                     if owner == k))
        for k in range(n_shards))
    from .memscope import csr_slot_bytes

    return ShardReport(
        kind="ranges", n_shards=n_shards, n_global=n,
        n_global_padded=n_local * n_shards, n_local=n_local,
        rows=rows, nnz=nnz, slots=slots,
        halo_send_bytes=send, halo_recv_bytes=recv,
        neighbors=neighbors, plan=plan,
        persistent_bytes=csr_slot_bytes(slots, itemsize).astype(
            np.int64))


# ---------------------------------------------------------------------------
# emission + the CLI's pickup slot

#: the most recent report noted by a partition site (None before any) -
#: the CLI's --report reads this, same pattern as dist_cg._LAST_COMM_COST
_LAST: list = [None]


def last_shard_report() -> Optional[ShardReport]:
    return _LAST[0]


def reset_last_shard_report() -> None:
    _LAST[0] = None


def note_report(report: ShardReport) -> ShardReport:
    """Publish a freshly computed report: park it for the CLI, and when
    telemetry is active emit a ``shard_profile`` event plus per-shard
    labeled gauges.  Host-side only; call sites gate the (cheap, but
    not free) report computation itself on ``telemetry.active()``."""
    from .. import telemetry
    from .registry import REGISTRY

    _LAST[0] = report
    if not telemetry.active():
        return report
    imb = report.imbalance()
    telemetry.events.emit("shard_profile", **report.to_json())
    for gname, help_, values in (
            ("shard_rows", "real rows owned per shard", report.rows),
            ("shard_nnz", "live matrix entries per shard", report.nnz),
            ("shard_halo_send_bytes",
             "halo payload bytes sent per matvec per shard",
             report.halo_send_bytes)):
        g = REGISTRY.gauge(gname, help_, labelnames=("kind", "shard"))
        for k in range(report.n_shards):
            g.set(float(values[k]), kind=report.kind, shard=str(k))
    REGISTRY.gauge(
        "shard_nnz_imbalance",
        "per-partition nnz max/mean stall factor",
        labelnames=("kind",)).set(imb["nnz_max_over_mean"],
                                  kind=report.kind)
    REGISTRY.gauge(
        "shard_halo_imbalance",
        "per-partition halo-send max/mean stall factor",
        labelnames=("kind",)).set(imb["halo_send_max_over_mean"],
                                  kind=report.kind)
    return report
