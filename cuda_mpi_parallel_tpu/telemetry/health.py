"""Solve-health diagnostics on top of the flight record.

The reference prints "Success" whether CG converged or silently hit
maxit (``CUDACG.cu:365``, SURVEY Q4/Q7); this module is the layer that
turns "the solve returned MAXITER" into "the solve stagnated at
iteration 412 with kappa ~ 3e6, residual decay flatlined at 1e-9".

Two independent diagnostics, both computed HOST-SIDE from the
once-fetched :class:`~.flight.FlightRecord` (the compiled solve is
never touched):

* **Spectral estimate** (:func:`estimate_condition`): CG is Lanczos in
  disguise - the recurrence scalars define the Lanczos tridiagonal

      T[j, j]     = 1/alpha_j + beta_{j-1}/alpha_{j-1}
      T[j, j + 1] = sqrt(beta_j) / alpha_j

  whose extreme eigenvalues (Ritz values) converge to A's extreme
  eigenvalues (Golub & Van Loan SS10.2; the standard CG condition
  estimator).  The recorder's alpha/beta columns at stride 1 are
  exactly these scalars, so kappa ~ lmax/lmin comes free with the
  trace.  Needs a consecutive (stride-1) run of rows; decimated or
  resident-kernel records (NaN alpha/beta) skip the estimate and
  return ``None``.
* **Trace classification** (:func:`classify_trace`): the residual
  column distinguishes a solve that was still converging when the
  budget ran out (MAXITER), one whose decay flatlined above tolerance
  (STAGNATED - f32 attainable-accuracy floors, loss of orthogonality),
  and one whose residual grew away from its minimum (DIVERGED -
  indefinite operator/preconditioner).  The new ``CGStatus`` codes
  carry ``describe()`` text like the solver-produced ones.

The verdict flows out through the PR-2 observability stack: a
``solve_health`` event (``EVENT_SCHEMA``), a residual-decay-rate gauge
and a kappa-estimate gauge in the metrics registry
(:func:`emit_solve_health`), and the per-solve iteration histogram
observed by ``session.observe_solve``.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..solver.status import CGStatus
from .flight import FlightRecord

__all__ = [
    "SolveHealth",
    "assess_lanes",
    "assess_solve_health",
    "classify_trace",
    "emit_solve_health",
    "estimate_condition",
    "lanczos_tridiagonal",
    "ritz_values",
]

#: |d log10 ||r|| / d iteration| below which a tail is "flatlined":
#: less than one decade per 1000 iterations is indistinguishable from
#: a rounding-noise floor for every solver configuration in this repo
#: (the slowest healthy tail measured - unpreconditioned 256^3 f32 -
#: decays ~1 decade per ~150 iterations).
STAGNATION_RATE = 1e-3

#: Residual growth factor over the recorded minimum that reads as
#: divergence rather than plateau noise.
DIVERGENCE_FACTOR = 10.0

#: Rows of the spectral window: the tridiagonal eigenproblem is dense
#: O(w^2) memory / O(w^3) time on the fallback path; 512 rows resolve
#: the extreme Ritz values to percent level long before this cap.
SPECTRAL_WINDOW = 512


@dataclasses.dataclass(frozen=True)
class SolveHealth:
    """One solve's health verdict (JSON-ready via :meth:`to_json`)."""

    classification: CGStatus
    converged: bool
    iterations: int
    decay_rate: Optional[float]        # log10 ||r|| per iteration, full
    tail_decay_rate: Optional[float]   # same, last window
    kappa_estimate: Optional[float]    # lmax/lmin Ritz ratio (stride 1)
    ritz_min: Optional[float]
    ritz_max: Optional[float]
    plateau_iteration: Optional[int]   # where the trace flatlined
    residual_min: Optional[float]
    residual_last: Optional[float]
    message: str

    def describe(self) -> str:
        return self.message

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["classification"] = self.classification.name
        return out


def lanczos_tridiagonal(record: FlightRecord,
                        window: int = SPECTRAL_WINDOW):
    """``(diag, off, residual_iterations)`` - the exact principal
    submatrix of the CG-Lanczos tridiagonal over the record's trailing
    consecutive run, aligned to the RESIDUAL indices the Lanczos basis
    vectors carry.

    This is the Krylov-recycling harvest's half of the spectral story
    (``solver.recycle``): row ``i`` of the returned tridiagonal is the
    Rayleigh-quotient row of the normalized residual at iteration
    ``residual_iterations[i]``, so ``V_w^T A V_w`` for a basis-ring
    window ``V_w`` of those residuals is EXACTLY this matrix -
    eigenvectors of it are Ritz-vector coefficients, not just Ritz
    values.  Unlike :func:`ritz_values` (a diagnostic inner bound that
    tolerates a truncated first row), every entry here carries its full
    cross term, which is why the first recorded step of the run is
    consumed as a coefficient source but not given a row.

    Raises ``ValueError`` - loudly, never junk - when the record
    cannot support the reconstruction:

    * **stride-decimated records** (``record.stride != 1``): the
      tridiagonal couples CONSECUTIVE iterations; decimated alpha/beta
      rows would assemble a matrix whose eigenpairs belong to no
      operator.  Re-record with ``--flight-record 1`` / a stride-1
      ``FlightConfig`` (the stride-1 requirement is also stated in the
      README's "Krylov recycling" section).
    * records with fewer than 3 usable consecutive rows (nothing to
      window), or whose alpha/beta columns are NaN (resident block
      traces record no recurrence scalars).
    """
    if record.stride != 1:
        raise ValueError(
            f"Lanczos/Ritz harvesting needs a stride-1 flight record "
            f"(consecutive alpha/beta rows assemble the tridiagonal); "
            f"this record is stride-{record.stride} decimated and "
            f"would silently produce junk Ritz values. Re-record at "
            f"stride 1 (--flight-record 1 / FlightConfig(stride=1)).")
    if len(record) < 3:
        raise ValueError(
            f"Lanczos/Ritz harvesting needs >= 3 recorded iterations, "
            f"got {len(record)} (solve too short, or the ring was "
            f"overwritten)")
    its = record.iterations
    breaks = np.nonzero(np.diff(its) != 1)[0]
    start = int(breaks[-1]) + 1 if breaks.size else 0
    its = its[start:]
    alphas = record.alphas[start:]
    betas = record.betas[start:]
    ok = np.isfinite(alphas) & np.isfinite(betas)
    its, alphas, betas = its[ok], alphas[ok], betas[ok]
    bad = np.nonzero((alphas <= 0.0) | (betas < 0.0))[0]
    if bad.size:
        its = its[:bad[0]]
        alphas, betas = alphas[:bad[0]], betas[:bad[0]]
    if alphas.shape[0] > window:
        its = its[-window:]
        alphas, betas = alphas[-window:], betas[-window:]
    m = alphas.shape[0]
    if m < 2:
        raise ValueError(
            "Lanczos/Ritz harvesting found < 2 usable consecutive "
            "alpha/beta rows (NaN columns - a resident block trace? - "
            "or non-SPD scalars truncated the run)")
    # row i describes the residual BEFORE the step recorded at its[i]:
    # alpha/beta recorded at iteration j are the textbook alpha_{j-1}/
    # beta_{j-1}, so residual index t = j - 1.  diag(t) = 1/alpha_t +
    # beta_{t-1}/alpha_{t-1}; the previous-step term for row 0 comes
    # from the run's FIRST recorded row (consumed, not given a row)
    # unless the run starts at the solve's first step (t = 0, no
    # previous term exists).
    if int(its[0]) == 1:
        res_its = its - 1
        diag = 1.0 / alphas
        diag[1:] += betas[:-1] / alphas[:-1]
        off = np.sqrt(np.maximum(betas[:-1], 0.0)) / alphas[:-1]
    else:
        res_its = its[1:] - 1
        diag = 1.0 / alphas[1:] + betas[:-1] / alphas[:-1]
        off = np.sqrt(np.maximum(betas[1:-1], 0.0)) / alphas[1:-1]
    if diag.shape[0] < 2:
        raise ValueError(
            "Lanczos/Ritz harvesting found < 2 tridiagonal rows after "
            "aligning to residual indices (solve too short)")
    return diag, off, res_its.astype(np.int64)


def ritz_values(record: FlightRecord,
                window: int = SPECTRAL_WINDOW) -> Optional[np.ndarray]:
    """Eigenvalues of the CG-Lanczos tridiagonal reconstructed from the
    record's trailing consecutive stride-1 rows (up to ``window`` of
    them), or ``None`` when the record cannot support it (stride > 1,
    NaN alpha/beta columns, or < 2 usable rows before the first
    non-SPD scalar)."""
    if record.stride != 1 or len(record) < 3:
        return None
    its = record.iterations
    # trailing run of consecutive iterations (the ring keeps the last
    # capacity rows, so after a wrap the tail is still consecutive)
    breaks = np.nonzero(np.diff(its) != 1)[0]
    start = int(breaks[-1]) + 1 if breaks.size else 0
    alphas = record.alphas[start:]
    betas = record.betas[start:]
    # the initial row (alpha NaN - no step ran) contributes nothing
    ok = np.isfinite(alphas) & np.isfinite(betas)
    alphas, betas = alphas[ok], betas[ok]
    # non-SPD scalars (alpha <= 0 / beta < 0) poison the recurrence from
    # that step on - pipecg in particular records a run of negative
    # alphas once it hits its rounding floor.  The rows BEFORE the first
    # such step still define a valid tridiagonal, so truncate there
    # rather than voiding the whole estimate.
    bad = np.nonzero((alphas <= 0.0) | (betas < 0.0))[0]
    if bad.size:
        alphas, betas = alphas[:bad[0]], betas[:bad[0]]
    if alphas.shape[0] > window:
        alphas, betas = alphas[-window:], betas[-window:]
    m = alphas.shape[0]
    if m < 2:
        return None
    diag = 1.0 / alphas
    diag[1:] += betas[:-1] / alphas[:-1]
    off = np.sqrt(betas[:-1]) / alphas[:-1]
    try:
        from scipy.linalg import eigh_tridiagonal

        return np.asarray(eigh_tridiagonal(diag, off,
                                           eigvals_only=True))
    except Exception:  # scipy absent/old: dense fallback, window-capped
        t = np.diag(diag) + np.diag(off, 1) + np.diag(off, -1)
        return np.linalg.eigvalsh(t)


def estimate_condition(record: FlightRecord,
                       window: int = SPECTRAL_WINDOW):
    """``(lmin_est, lmax_est, kappa_est)`` from the Ritz values, or
    ``(None, None, None)`` when the record cannot support the
    reconstruction.  Ritz intervals are INNER bounds: lmax_est <= lmax
    and lmin_est >= lmin, so kappa_est is a lower bound that tightens
    as the recorded window grows."""
    ritz = ritz_values(record, window=window)
    if ritz is None or ritz.shape[0] == 0:
        return None, None, None
    lmin, lmax = float(ritz.min()), float(ritz.max())
    if lmin <= 0.0 or not np.isfinite(lmin) or not np.isfinite(lmax):
        return None, None, None
    return lmin, lmax, lmax / lmin


def classify_trace(record: FlightRecord, *, converged: bool,
                   status: Optional[int] = None):
    """``(classification, tail_decay_rate, plateau_iteration, message)``.

    Solver-reported outcomes win where they are specific (CONVERGED,
    BREAKDOWN); the trace refines the unspecific one (MAXITER) into
    still-converging / STAGNATED / DIVERGED.
    """
    res = record.residuals
    ok = np.isfinite(res) & (res > 0.0)
    tail_n = max(8, len(record) // 4)
    tail_rate = record.decay_rate(tail=tail_n)
    if converged:
        return CGStatus.CONVERGED, tail_rate, None, "converged"
    if status is not None and int(status) == int(CGStatus.BREAKDOWN):
        return (CGStatus.BREAKDOWN, tail_rate, None,
                CGStatus.BREAKDOWN.describe())
    if int(ok.sum()) < 3:
        return (CGStatus.MAXITER, tail_rate, None,
                "iteration budget exhausted (trace too short to "
                "classify)")
    its = record.iterations[ok]
    r = res[ok]
    i_min = int(np.argmin(r))
    r_min = float(r[i_min])
    plateau_it = int(its[i_min])
    if float(r[-1]) > DIVERGENCE_FACTOR * r_min:
        return (CGStatus.DIVERGED, tail_rate, plateau_it,
                f"residual grew {float(r[-1]) / r_min:.1f}x from its "
                f"minimum {r_min:.3e} at iteration {plateau_it}")
    if tail_rate is not None and abs(tail_rate) < STAGNATION_RATE:
        return (CGStatus.STAGNATED, tail_rate, plateau_it,
                f"residual decay flatlined near {r_min:.3e} after the "
                f"plateau at iteration {plateau_it}")
    return (CGStatus.MAXITER, tail_rate, None,
            "iteration budget exhausted while still converging "
            f"(tail decay {0.0 if tail_rate is None else tail_rate:.2e} "
            f"decades/iteration)")


def assess_solve_health(record: FlightRecord, *, converged: bool,
                        status: Optional[int] = None,
                        iterations: Optional[int] = None) -> SolveHealth:
    """The full verdict: classification + decay rates + spectral
    estimate, all from the once-fetched record."""
    classification, tail_rate, plateau_it, message = classify_trace(
        record, converged=converged, status=status)
    lmin, lmax, kappa = estimate_condition(record)
    res = record.residuals
    ok = np.isfinite(res) & (res > 0.0)
    r_min = float(res[ok].min()) if ok.any() else None
    r_last = float(res[-1]) if len(record) and np.isfinite(res[-1]) \
        else None
    if kappa is not None:
        message += f" (kappa >= {kappa:.3g} from {len(record)} records)"
    return SolveHealth(
        classification=classification,
        converged=bool(converged),
        iterations=(int(iterations) if iterations is not None
                    else (int(record.iterations[-1]) if len(record)
                          else 0)),
        decay_rate=record.decay_rate(),
        tail_decay_rate=tail_rate,
        kappa_estimate=kappa,
        ritz_min=lmin,
        ritz_max=lmax,
        plateau_iteration=plateau_it,
        residual_min=r_min,
        residual_last=r_last,
        message=message,
    )


def assess_lanes(records, *, converged, statuses, iterations):
    """Per-lane verdicts of a batched (many-RHS) solve.

    ``records`` are the per-lane :class:`~.flight.FlightRecord` views
    (``flight.lanes_from_buffer``); ``converged``/``statuses``/
    ``iterations`` are the per-lane arrays of a
    ``solver.many.CGBatchResult``.  Each lane is classified exactly
    like a single-RHS solve - a lane that flatlined above ITS tolerance
    reads STAGNATED even while its neighbors converged.
    """
    out = []
    for j, rec in enumerate(records):
        out.append(assess_solve_health(
            rec, converged=bool(np.asarray(converged)[j]),
            status=int(np.asarray(statuses)[j]),
            iterations=int(np.asarray(iterations)[j])))
    return out


def emit_solve_health(health: SolveHealth,
                      engine: str = "general") -> dict:
    """Route one verdict through the PR-2 observability stack: the
    ``solve_health`` event (when a sink is active) plus the
    residual-decay-rate and kappa-estimate gauges.  Returns the event
    payload (also the CLI/bench JSON embed)."""
    from . import events
    from .registry import REGISTRY

    payload = health.to_json()
    if health.decay_rate is not None:
        REGISTRY.gauge(
            "solve_residual_decay_rate",
            "log10 ||r|| decay per iteration of the most recent "
            "flight-recorded solve (negative = converging)",
            labelnames=("engine",)).set(health.decay_rate, engine=engine)
    if health.kappa_estimate is not None:
        REGISTRY.gauge(
            "solve_condition_estimate",
            "Ritz-value condition estimate (lower bound) of the most "
            "recent flight-recorded solve",
            labelnames=("engine",)).set(health.kappa_estimate,
                                        engine=engine)
    events.emit("solve_health", engine=engine, **payload)
    return payload
