"""phasetrace: measured per-shard per-phase timing of distributed solves.

Until now every timing signal was ONE wall time per solve:
``calibrate.fit_machine_model`` fit two bandwidths from whole-solve
observations (a single solve could only reach the degraded ``fixed-net``
tier), and the Perfetto timeline in :mod:`.report` rendered a *model* of
the iteration from static shard accounting, honestly labeled "not a
device profile".  This module replaces both with measurement.

Given a live partitioned operator (any of the ``DistCSR`` /
``DistCSRGather`` / ``DistCSRRing`` lanes of ``parallel.dist_cg``), the
profiler compiles **phase-isolated step functions from the operator's
own building blocks** - the methods the real matvec composes, so the
profiled phase IS the solve's code path, never a reimplementation:

* **halo** - the exchange alone: ``DistCSR.gather_x`` (one
  ``all_gather``), every ``DistCSRGather.exchange_round`` (and each
  round *individually*, yielding per-neighbor-round wire seconds and a
  fitted per-link bytes/s where round payloads differ), or the ring's
  ``rotate`` chain;
* **spmv** - the local CSR multiply alone, timed PER SHARD on that
  shard's own arrays (the straggler is measured, not modeled);
* **reduction** - one dot + ``psum``, the iteration's barrier.

Each phase runs ``repeats`` chained repetitions inside one compiled
``fori_loop`` (a data dependency threads every trip, so XLA can neither
hoist nor CSE the collective out of the loop), under the real mesh for
the communication phases.  A composed **step** function - matvec plus
two dot+psum reductions plus the CG axpys, the iteration core - is
timed the same way and anchors the residual check: the profile reports
what fraction of the measured iteration wall the phase sum explains
(:meth:`PhaseProfile.explained_fraction`), so an unexplained phase is a
loud number, not a silent gap.

Consumers:

* ``calibrate.observations_from_profile`` turns one profile into >= 2
  independent observations (orthogonal byte ratios by construction), so
  the ``lstsq2`` confident calibration tier is routine from a single
  profiled solve;
* ``report.perfetto_trace(phase_profile=...)`` draws MEASURED per-shard
  spans (``span_source: "measured"``);
* :func:`note_profile` emits the ``phase_profile`` event plus per-phase
  / per-shard / per-link gauges;
* the CLI's ``--phase-profile [R]``, ``serve`` registration
  (``phase_profile=R``), and ``bench.py``'s ``_phase_entry`` ride all
  of the above.

Profiling runs its own dispatches AFTER a solve - it never touches the
solve's compiled body (the zero-perturbation proof lives in
tests/test_phasetrace.py).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

__all__ = [
    "DEFAULT_REPEATS",
    "PhaseProfile",
    "note_profile",
    "profile_distributed",
    "profile_partition",
]

#: chained repetitions per compiled phase loop: enough to amortize
#: dispatch into the per-rep number, and (at the default) comfortably
#: past calibrate.MIN_CALIBRATION_ITERATIONS so a single profile can
#: back a confident fit
DEFAULT_REPEATS = 16

#: phase sum below this fraction of the measured step wall marks the
#: profile unexplained (a phase the profiler does not isolate is
#: dominating the iteration).  The lint gate enforces
#: ``FLOOR <= explained <= 2 - FLOOR`` - over-explanation past the
#: mirrored bound means the phases double-count work the composed
#: step overlaps.
EXPLAINED_FRACTION_FLOOR = 0.7


@dataclasses.dataclass(frozen=True)
class PhaseProfile:
    """Measured per-shard per-phase seconds of one partitioned operator.

    All times are seconds per repetition (= per matvec / per phase
    application).  ``spmv_s`` is per shard; the communication phases
    are whole-mesh walls (a collective synchronizes every shard, so a
    per-shard split of its wall would be fiction - the per-shard story
    of the wire lives in ``links``, one timed entry per exchange
    round).  ``step_s`` is the measured iteration core (matvec +
    ``reductions_per_iteration`` dot+psum barriers + the CG axpys) -
    the wall the phase sum is checked against.
    """

    kind: str                     # csr | csr-gather | csr-ring
    exchange: str                 # allgather | gather | ring
    n_shards: int
    n_local: int
    itemsize: int
    repeats: int
    spmv_s: np.ndarray            # (P,) per-shard local SpMV seconds
    #: the SpMV phase's whole-MESH wall (every shard multiplying, no
    #: collective) - what the iteration actually pays for the phase
    #: under this executor.  On real parallel hardware this approaches
    #: ``max(spmv_s)``; on CPU hosts with virtual devices the runtime
    #: serializes shard programs and it approaches ``sum(spmv_s)`` -
    #: measuring it keeps the explained-fraction check honest on both.
    spmv_mesh_s: float
    halo_s: float                 # whole exchange, seconds per matvec
    reduction_s: float            # one dot + psum
    step_s: float                 # measured iteration core
    #: per exchange round: shift, per-device padded bytes, measured
    #: seconds, bytes/s (calibrate.fit_link_bandwidths output)
    links: Tuple[dict, ...] = ()
    #: planner slot-term coordinate: ``slots_max * (itemsize + 4)``
    gather_bytes: int = 0
    #: per-device wire bytes per matvec of the lane that ran
    wire_bytes: int = 0
    reductions_per_iteration: int = 2
    solve_iterations: Optional[int] = None
    solve_elapsed_s: Optional[float] = None
    plan: str = "even"

    # ---- derived -----------------------------------------------------
    def phase_seconds(self, shard: int) -> Tuple[float, float, float]:
        """(halo, spmv, reduction) seconds of one iteration on
        ``shard`` - reduction counted ``reductions_per_iteration``
        times, the way the iteration pays it."""
        return (float(self.halo_s), float(self.spmv_s[shard]),
                float(self.reduction_s * self.reductions_per_iteration))

    def critical_path_s(self) -> float:
        """Phase sum of one iteration: halo + the mesh-measured SpMV
        wall + the iteration's reduction barriers.  Every term is a
        whole-mesh wall measured under the same executor, so the sum
        is commensurable with ``step_s`` (and with a real solve's
        per-iteration wall)."""
        return (float(self.halo_s) + float(self.spmv_mesh_s)
                + float(self.reduction_s * self.reductions_per_iteration))

    def stall_factors(self) -> dict:
        """Measured max/mean per phase.  The communication phases are
        1.0 by construction (padded-uniform payloads, one wall); the
        SpMV factor is the real measured straggler penalty."""
        from .shardscope import max_over_mean

        return {
            "halo": 1.0,
            "spmv": max_over_mean(self.spmv_s),
            "reduction": 1.0,
        }

    def explained_fraction(self) -> float:
        """Fraction of the measured iteration core (``step_s``) the
        phase critical path explains - the residual check.  Values
        near 1.0 mean the three phases ARE the iteration; a low value
        means an unprofiled cost dominates."""
        return self.critical_path_s() / max(float(self.step_s), 1e-300)

    @property
    def solve_s_per_iteration(self) -> Optional[float]:
        if not self.solve_iterations or self.solve_elapsed_s is None:
            return None
        return float(self.solve_elapsed_s) / max(
            int(self.solve_iterations), 1)

    def explained_fraction_vs_solve(self) -> Optional[float]:
        """The same residual check against the ACTUAL solve's measured
        per-iteration wall (when the caller provided it) - the solve
        additionally pays while-loop plumbing and convergence checks,
        so this is <= the step-based fraction in practice."""
        spi = self.solve_s_per_iteration
        if spi is None:
            return None
        return self.critical_path_s() / max(spi, 1e-300)

    def to_json(self) -> dict:
        out = {
            "kind": self.kind,
            "exchange": self.exchange,
            "plan": self.plan,
            "n_shards": int(self.n_shards),
            "n_local": int(self.n_local),
            "itemsize": int(self.itemsize),
            "repeats": int(self.repeats),
            "phases": {
                "halo_s": float(self.halo_s),
                "spmv_s": float(self.spmv_mesh_s),
                "spmv_shard_max_s": float(np.max(self.spmv_s)),
                "spmv_shard_mean_s": float(np.mean(self.spmv_s)),
                "reduction_s": float(self.reduction_s),
            },
            "spmv_s": [float(v) for v in self.spmv_s],
            "step_s": float(self.step_s),
            "links": [dict(e) for e in self.links],
            "gather_bytes": int(self.gather_bytes),
            "wire_bytes": int(self.wire_bytes),
            "reductions_per_iteration": int(
                self.reductions_per_iteration),
            "stall_factors": self.stall_factors(),
            "explained_fraction": round(self.explained_fraction(), 6),
        }
        if self.solve_s_per_iteration is not None:
            out["solve_s_per_iteration"] = self.solve_s_per_iteration
            out["explained_fraction_vs_solve"] = round(
                self.explained_fraction_vs_solve(), 6)
        return out

    def describe_lines(self) -> List[str]:
        """Human lines for the report's "-- phase profile --" section
        (also rendered by ``report.phase_lines`` from the JSON form)."""
        from .report import phase_lines

        return phase_lines(self.to_json())


# ---------------------------------------------------------------------------
# measurement machinery

def _chain(s, probe, tiny):
    """Thread a data dependency from ``probe`` (this trip's phase
    output) into the next trip's input without changing ``s``
    meaningfully: adds ``probe's first element * tiny`` (tiny is the
    dtype's smallest normal - a nonzero constant XLA cannot fold away,
    so the chained loop really runs every collective every trip)."""
    return s + probe.reshape(-1)[0] * tiny


def _time_loop(fn, *args, repeats: int, outer: int = 2):
    """Best-of-``outer`` wall seconds of one compiled ``repeats``-trip
    loop, divided by ``repeats`` (compile excluded via warmup)."""
    import jax

    from ..utils.timing import time_fn

    jitted = jax.jit(fn)

    def run():
        return jax.block_until_ready(jitted(*args))

    elapsed, _ = time_fn(run, warmup=1, repeats=outer, reduce="best")
    return elapsed / max(int(repeats), 1)


def _mesh_phase(mesh, axis, arrays, body_of_op, make_op, repeats: int,
                extra_state=None):
    """Time a mesh phase: ``body_of_op(op)`` returns the fori body
    ``(i, state) -> state`` given the per-shard operator built from the
    stripped ``arrays`` (the same construct-inside-shard_map pattern as
    ``dist_cg._solve_csr``)."""
    from functools import partial

    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    x0, shards = arrays[0], arrays[1:]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis),) * len(arrays), out_specs=P(axis))
    def run(x_local, *shard_args):
        strip = partial(jax.tree.map, lambda v: v[0])
        op = make_op(tuple(strip(sa) for sa in shard_args))
        body = body_of_op(op)
        state = x_local if extra_state is None else extra_state(x_local)
        out = lax.fori_loop(0, repeats, body, state)
        return out[0] if isinstance(out, tuple) else out

    return _time_loop(run, x0, *shards, repeats=repeats)


def profile_partition(parts, mesh, *, repeats: int = DEFAULT_REPEATS,
                      solve_iterations: Optional[int] = None,
                      solve_elapsed_s: Optional[float] = None,
                      plan: str = "even") -> PhaseProfile:
    """Measure the phase profile of an already-built partition.

    ``parts`` is ``partition.partition_csr`` output (allgather or
    gather lane - ``parts.halo`` decides) or
    ``partition.ring_partition_csr`` output (the ring lane, detected by
    its per-step tuple slabs); ``mesh`` the 1-D device mesh the solve
    runs on.  Host-side setup is numpy; the timed bodies are the
    operator building blocks under ``shard_map``, plus per-shard
    single-device SpMV timings.
    """
    import jax.numpy as jnp
    from jax import lax

    from . import events
    from .calibrate import fit_link_bandwidths
    from ..parallel.dist_cg import _shard_tree
    from ..parallel.exchange import allgather_wire_bytes
    from ..parallel.mesh import shard_vector
    from ..parallel.operators import DistCSR, DistCSRGather, DistCSRRing

    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    if len(mesh.axis_names) != 1:
        raise ValueError("phase profiling runs on a 1-D mesh (pencil "
                         "meshes are stencil-only)")
    axis = mesh.axis_names[0]
    n_shards = int(mesh.devices.size)
    if n_shards != int(parts.n_shards):
        raise ValueError(f"partition targets {parts.n_shards} shards "
                         f"but the mesh has {n_shards}")
    if n_shards < 2:
        raise ValueError("phase profiling needs a mesh with >= 2 "
                         "devices (a 1-shard 'exchange' has no wire)")
    ring = isinstance(parts.data, tuple)
    n_local = int(parts.n_local)
    dtype = np.asarray(parts.data[0] if ring else parts.data).dtype
    itemsize = int(dtype.itemsize)
    tiny = jnp.asarray(np.finfo(dtype).tiny, dtype)
    reductions = 2   # the CG iteration's two dot+psum barriers

    x_pad = np.ones(parts.n_global_padded, dtype=dtype)
    x_dev = shard_vector(jnp.asarray(x_pad), mesh, axis)

    with events.scoped(phase="phase-profile"):
        if ring:
            profile = _profile_ring(
                parts, mesh, axis, x_dev, tiny, repeats, n_shards,
                n_local, itemsize, _shard_tree, DistCSRRing,
                allgather_wire_bytes, jnp, lax)
        else:
            profile = _profile_allgather_family(
                parts, mesh, axis, x_dev, tiny, repeats, n_shards,
                n_local, itemsize, _shard_tree, DistCSR, DistCSRGather,
                allgather_wire_bytes, jnp, lax)
    kind, exchange, spmv_s, spmv_mesh_s, halo_s, reduction_s, step_s, \
        rounds, gather_bytes, wire_bytes = profile
    return PhaseProfile(
        kind=kind, exchange=exchange, n_shards=n_shards,
        n_local=n_local, itemsize=itemsize, repeats=int(repeats),
        spmv_s=np.asarray(spmv_s, dtype=np.float64),
        spmv_mesh_s=float(spmv_mesh_s),
        halo_s=float(halo_s), reduction_s=float(reduction_s),
        step_s=float(step_s),
        links=tuple(fit_link_bandwidths(rounds)),
        gather_bytes=int(gather_bytes), wire_bytes=int(wire_bytes),
        reductions_per_iteration=reductions,
        solve_iterations=solve_iterations,
        solve_elapsed_s=solve_elapsed_s, plan=str(plan))


def _step_body(op, axis, tiny, jnp, lax):
    """The iteration-core body: one matvec, two dot+psum barriers, the
    CG axpys - bounded synthetic coefficients so ``repeats`` trips stay
    finite whatever the operator's spectrum."""
    def body(i, s):
        p, r = s
        q = op.matvec(p)
        denom = lax.psum(jnp.vdot(p, q), axis)
        alpha = 1.0 / (jnp.abs(denom) + 1.0)
        r2 = r - alpha * q
        rr = lax.psum(jnp.vdot(r2, r2), axis)
        beta = rr / (rr + 1.0)
        return (r2 + beta * p, r2)
    return body


def _reduction_body(axis, tiny, jnp, lax):
    def body(i, s):
        rr = lax.psum(jnp.vdot(s, s), axis)
        return _chain(s, rr.reshape(1), tiny)
    return body


def _spmv_seconds(per_shard_args, x_ext_size, n_local, dtype, repeats,
                  jnp, lax, tiny):
    """Per-shard local-SpMV seconds, each shard's arrays timed alone on
    one device (the measured straggler; the psum barrier in the real
    loop makes the max of these everyone's wait)."""
    from ..ops import spmv as spmv_ops

    out = []
    for data_k, cols_k, rows_k in per_shard_args:
        d = jnp.asarray(data_k)
        c = jnp.asarray(cols_k)
        r = jnp.asarray(rows_k)
        x0 = jnp.ones((x_ext_size,), dtype=dtype)

        def run(xe, d=d, c=c, r=r):
            def body(i, s):
                y = spmv_ops.csr_matvec(d, c, r, s, n_local)
                return _chain(s, y, tiny)
            return lax.fori_loop(0, repeats, body, xe)

        out.append(_time_loop(run, x0, repeats=repeats))
    return np.asarray(out, dtype=np.float64)


def _profile_allgather_family(parts, mesh, axis, x_dev, tiny, repeats,
                              n_shards, n_local, itemsize, _shard_tree,
                              DistCSR, DistCSRGather,
                              allgather_wire_bytes, jnp, lax):
    sched = parts.halo
    gather = sched is not None
    data = _shard_tree(parts.data, mesh, axis)
    cols = _shard_tree(parts.cols, mesh, axis)
    rows = _shard_tree(parts.local_rows, mesh, axis)
    send = tuple(_shard_tree(r.send_idx, mesh, axis)
                 for r in sched.rounds) if gather else ()
    shifts = tuple(r.shift for r in sched.rounds) if gather else ()

    if gather:
        def make_op(stripped):
            d, c, r, *s = stripped
            return DistCSRGather(
                data=d, cols=c, local_rows=r, send_idx=tuple(s),
                shifts=shifts, n_local=n_local, axis_name=axis,
                n_shards=n_shards)
        arrays = (x_dev, data, cols, rows) + send
    else:
        def make_op(stripped):
            d, c, r = stripped
            return DistCSR(data=d, cols=c, local_rows=r,
                           n_local=n_local, axis_name=axis,
                           n_shards=n_shards)
        arrays = (x_dev, data, cols, rows)

    def halo_body(op):
        if gather:
            def body(i, s):
                ext = op.extend_x(s)
                # chain through the RECEIVED slab (ext[n_local:]), not
                # the local block: slice-of-concat at offset 0 would
                # simplify back to s and let XLA drop the ppermutes
                return _chain(s, ext[n_local:], tiny)
        else:
            def body(i, s):
                return _chain(s, op.gather_x(s), tiny)
        return body

    dtype = np.asarray(parts.data).dtype
    x_ext_size = (n_local + sched.halo_width) if gather \
        else parts.n_global_padded

    def spmv_mesh_body(op):
        # no collective: every shard multiplies against a constant
        # extended x (nudged by the chained state so XLA cannot hoist
        # the multiply out of the loop)
        def body(i, s):
            xc = jnp.ones((x_ext_size,), dtype) + s[0] * tiny
            return _chain(s, op.local_matvec(xc), tiny)
        return body

    halo_s = _mesh_phase(mesh, axis, arrays, halo_body, make_op,
                         repeats)
    spmv_mesh_s = _mesh_phase(mesh, axis, arrays, spmv_mesh_body,
                              make_op, repeats)
    reduction_s = _mesh_phase(
        mesh, axis, arrays, lambda op: _reduction_body(axis, tiny, jnp,
                                                       lax),
        make_op, repeats)
    step_s = _mesh_phase(
        mesh, axis, arrays,
        lambda op: _step_body(op, axis, tiny, jnp, lax), make_op,
        repeats, extra_state=lambda x: (x, x))

    rounds = []
    if gather:
        round_bytes = sched.round_wire_bytes(itemsize)
        for i in range(len(shifts)):
            def round_body(op, i=i):
                def body(j, s):
                    return _chain(s, op.exchange_round(s, i), tiny)
                return body
            secs = _mesh_phase(mesh, axis, arrays, round_body, make_op,
                               repeats)
            rounds.append((shifts[i], round_bytes[i], secs))
        wire_bytes = sched.wire_bytes_per_matvec(itemsize)
    else:
        wire_bytes = allgather_wire_bytes(n_shards, n_local, itemsize)

    per_shard = [(parts.data[k], parts.cols[k], parts.local_rows[k])
                 for k in range(n_shards)]
    spmv_s = _spmv_seconds(per_shard, x_ext_size, n_local, dtype,
                           repeats, jnp, lax, tiny)
    slots_max = int(parts.data.shape[1])
    gather_bytes = slots_max * (itemsize + 4)
    kind = "csr-gather" if gather else "csr"
    exchange = "gather" if gather else "allgather"
    return (kind, exchange, spmv_s, spmv_mesh_s, halo_s, reduction_s,
            step_s, rounds, gather_bytes, wire_bytes)


def _profile_ring(parts, mesh, axis, x_dev, tiny, repeats, n_shards,
                  n_local, itemsize, _shard_tree, DistCSRRing,
                  allgather_wire_bytes, jnp, lax):
    data = _shard_tree(parts.data, mesh, axis)
    cols = _shard_tree(parts.cols, mesh, axis)
    rows = _shard_tree(parts.local_rows, mesh, axis)

    def make_op(stripped):
        n = len(parts.data)
        return DistCSRRing(
            data=tuple(stripped[:n]), cols=tuple(stripped[n:2 * n]),
            local_rows=tuple(stripped[2 * n:]), n_local=n_local,
            axis_name=axis, n_shards=n_shards)

    arrays = (x_dev,) + data + cols + rows

    def halo_body(op):
        def body(i, s):
            for _ in range(n_shards - 1):
                s = op.rotate(s)
            return s
        return body

    def one_rotation_body(op):
        def body(i, s):
            return op.rotate(s)
        return body

    dtype = np.asarray(parts.data[0]).dtype

    def spmv_mesh_body(op):
        # every step slab multiplied against a constant resident block
        # (no rotation - the SpMV phase alone)
        def body(i, s):
            xc = jnp.ones((n_local,), dtype) + s[0] * tiny
            y = None
            for t in range(n_shards):
                yt = op.step_matvec(t, xc)
                y = yt if y is None else y + yt
            return _chain(s, y, tiny)
        return body

    halo_s = _mesh_phase(mesh, axis, arrays, halo_body, make_op,
                         repeats)
    spmv_mesh_s = _mesh_phase(mesh, axis, arrays, spmv_mesh_body,
                              make_op, repeats)
    rotation_s = _mesh_phase(mesh, axis, arrays, one_rotation_body,
                             make_op, repeats)
    reduction_s = _mesh_phase(
        mesh, axis, arrays, lambda op: _reduction_body(axis, tiny, jnp,
                                                       lax),
        make_op, repeats)
    step_s = _mesh_phase(
        mesh, axis, arrays,
        lambda op: _step_body(op, axis, tiny, jnp, lax), make_op,
        repeats, extra_state=lambda x: (x, x))
    # one shard's ring spmv = its slab multiplies across all steps
    from ..ops import spmv as spmv_ops

    spmv = []
    for k in range(n_shards):
        slabs = [(jnp.asarray(parts.data[t][k]),
                  jnp.asarray(parts.cols[t][k]),
                  jnp.asarray(parts.local_rows[t][k]))
                 for t in range(len(parts.data))]
        x0 = jnp.ones((n_local,), dtype=dtype)

        def run(xb, slabs=slabs):
            def body(i, s):
                y = None
                for d, c, r in slabs:
                    yt = spmv_ops.csr_matvec(d, c, r, s, n_local)
                    y = yt if y is None else y + yt
                return _chain(s, y, tiny)
            return lax.fori_loop(0, repeats, body, xb)

        spmv.append(_time_loop(run, x0, repeats=repeats))
    spmv_s = np.asarray(spmv, dtype=np.float64)

    # every rotation ships the same fixed n_local block - links cannot
    # separate, but the one measured rotation is still an honest wire
    rounds = [(1, n_local * itemsize, rotation_s)]
    wire_bytes = allgather_wire_bytes(n_shards, n_local, itemsize)
    # the ring's per-shard multiply walks every step slab: the slot
    # coordinate is the summed per-step slot widths
    gather_bytes = (sum(int(parts.data[t].shape[1])
                        for t in range(len(parts.data)))
                    * (itemsize + 4))
    return ("csr-ring", "ring", spmv_s, spmv_mesh_s, halo_s,
            reduction_s, step_s, rounds, gather_bytes, wire_bytes)


def profile_distributed(a, *, mesh=None, n_devices: Optional[int] = None,
                        plan=None, csr_comm: str = "allgather",
                        exchange=None,
                        repeats: int = DEFAULT_REPEATS,
                        solve_iterations: Optional[int] = None,
                        solve_elapsed_s: Optional[float] = None
                        ) -> PhaseProfile:
    """Profile the partition a ``solve_distributed(a, ...)`` call with
    the same arguments would run: resolve the plan, apply its
    permutation, build the identical partition (same helpers as
    ``dist_cg._solve_csr``), and measure (:func:`profile_partition`).
    This re-pays the O(nnz) host partition work a just-finished solve
    already did - acceptable for a post-solve profiling pass (the
    phase compiles dominate it); a caller holding the live partition
    (the solver service's dispatcher) should call
    :func:`profile_partition` directly instead.

    ``solve_iterations``/``solve_elapsed_s`` optionally anchor the
    profile to an actual measured solve of this system, enabling
    :meth:`PhaseProfile.explained_fraction_vs_solve`.
    """
    from ..models.operators import CSRMatrix
    from ..parallel import partition as part
    from ..parallel.dist_cg import (
        _apply_plan_permutation,
        _plan_exchange_hint,
        _resolve_exchange_mode,
        resolve_plan,
    )
    from ..parallel.mesh import make_mesh

    if not isinstance(a, CSRMatrix):
        raise ValueError(
            f"phase profiling supports assembled CSRMatrix problems "
            f"(the partitioned-operator lanes); got "
            f"{type(a).__name__}")
    if csr_comm == "ring-shiftell":
        raise ValueError(
            "phase profiling does not support csr_comm='ring-shiftell' "
            "(the pallas slab kernel fuses its phases; use the csr "
            "ring lane)")
    if mesh is None:
        mesh = make_mesh(n_devices)
    n_shards = int(mesh.devices.size)
    plan = resolve_plan(plan, a, n_shards,
                        exchange=_plan_exchange_hint(csr_comm, exchange))
    ap, _ = _apply_plan_permutation(a, np.zeros(a.shape[0]), plan)
    ranges = plan.row_ranges if plan is not None else None
    if csr_comm == "ring" or exchange == "ring":
        parts = part.ring_partition_csr(ap, n_shards, ranges)
    else:
        parts = part.partition_csr(
            ap, n_shards, ranges,
            exchange=_resolve_exchange_mode(exchange, plan))
    return profile_partition(
        parts, mesh, repeats=repeats,
        solve_iterations=solve_iterations,
        solve_elapsed_s=solve_elapsed_s,
        plan=plan.label if plan is not None else "even")


def note_profile(profile: PhaseProfile) -> PhaseProfile:
    """Publish a profile: the ``phase_profile`` event (when a sink is
    active) plus per-phase / per-shard / per-link registry gauges -
    the measured siblings of the static ``shard_profile`` emission."""
    from . import events
    from .registry import REGISTRY

    payload = profile.to_json()
    events.emit("phase_profile", **payload)
    for phase, secs in (("halo", profile.halo_s),
                        ("spmv", profile.spmv_mesh_s),
                        ("reduction", profile.reduction_s),
                        ("step", profile.step_s)):
        REGISTRY.gauge(
            "phase_seconds",
            "measured whole-mesh seconds per application of one solve "
            "phase (step = the composed iteration core)",
            labelnames=("phase",)).set(float(secs), phase=phase)
    for phase, factor in profile.stall_factors().items():
        REGISTRY.gauge(
            "phase_stall_factor",
            "measured max/mean across shards per phase (the psum-"
            "barrier straggler penalty)",
            labelnames=("phase",)).set(float(factor), phase=phase)
    for k, secs in enumerate(profile.spmv_s):
        REGISTRY.gauge(
            "phase_spmv_seconds",
            "measured per-shard local-SpMV seconds per matvec",
            labelnames=("shard",)).set(float(secs), shard=str(k))
    for link in profile.links:
        REGISTRY.gauge(
            "phase_link_bytes_per_s",
            "measured per-link halo-wire bandwidth (one exchange "
            "round, timed alone)",
            labelnames=("shift",)).set(
                float(link["bytes_per_s"]), shift=str(link["shift"]))
    REGISTRY.gauge(
        "phase_explained_fraction",
        "fraction of the measured iteration core explained by the "
        "phase critical path (halo + slowest spmv + reductions)").set(
            profile.explained_fraction())
    return profile
