"""calibra: the runtime-measured machine model and drift tracker.

The partition planner (``balance.plan``) and the roofline price every
decision with a *fixed reference* machine model - the gather slowdown
is a conservative table guess, net bandwidth is a table entry, and
nothing ever records how wrong those guesses were.  Production
workloads (time-stepping, the ROADMAP solver-service) solve the same
operator hundreds of times, and SpMV throughput is ultimately
sustained-stream bandwidth (arxiv 2204.00900) - so the model fitted
from the *first* solve's measured wall time should steer every later
solve.  This module closes ROADMAP open item 4 in three layers:

* **Measurement** - :func:`observation_for` turns one observed solve
  (its measured ``(iterations, elapsed_s)`` plus the static per-shard
  accounting the partition already produced) into a
  :class:`PhaseObservation`; :func:`fit_machine_model` least-squares
  fits the free parameters of the planner's own cost model - an
  effective gather bandwidth (reported as a measured
  ``gather_slowdown`` replacing the hardcoded table 8.0) and net
  bytes/s - with explicit fit residuals and a ``confident`` flag that
  stays False when iterations are too few or the fit had to fall back.
* **Drift as a first-class metric** - :func:`drift_report` compares
  the model-predicted per-iteration stall seconds
  (``balance.plan.score_report``, the SAME terms that chose the plan)
  against the measured per-iteration time; :func:`note_drift` exports
  the error % as registry gauges and an extended ``partition_plan``
  event, so model error is itself tracked across runs.
* **Persistence** - calibrated models live in the measured-artifact
  disk cache next to the autotuner (``utils.tune.JsonCache``), keyed
  by backend + host fingerprint with a staleness bound;
  :func:`preferred_model` is the one-line lookup the replan loop
  (``parallel.dist_cg.resolve_plan`` / ``solve_sequence``) uses to
  prefer a calibrated model when a fresh, confident one exists.

The fit deliberately does NOT try to separate gather slowdown from
streaming bandwidth inside one total - they are not identifiable from
a single wall time.  Streaming bandwidth comes from the base machine
model (the roofline table, or the CPU triad self-benchmark); the solve
fits the *effective gather bandwidth* and the measured slowdown is
their ratio.  Everything here is host arithmetic on already-synced
scalars: calibration can never touch a compiled solve (the
zero-perturbation proof in tests/test_calibrate.py).
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

from .roofline import MachineModel, machine_model

__all__ = [
    "CALIBRATION_MAX_AGE_S",
    "CalibrationFit",
    "DriftReport",
    "MIN_CALIBRATION_ITERATIONS",
    "PhaseObservation",
    "cache_key",
    "drift_report",
    "fit_link_bandwidths",
    "fit_machine_model",
    "load_calibration",
    "note_calibration",
    "note_drift",
    "observation_for",
    "observations_from_profile",
    "preferred_model",
    "store_calibration",
]

#: below this many total observed iterations the fit is never marked
#: confident: a 3-iteration oracle solve is all dispatch overhead, not
#: bandwidth
MIN_CALIBRATION_ITERATIONS = 8

#: a fit whose max relative residual exceeds this is not confident -
#: the model family does not explain the observations (noise, or a
#: phase the cost model does not price)
CONFIDENT_RESIDUAL = 0.25

#: disk-cached calibrations older than this are ignored by
#: :func:`preferred_model` (same week bound as the roofline CPU model)
CALIBRATION_MAX_AGE_S = 7 * 24 * 3600.0


@dataclasses.dataclass(frozen=True)
class PhaseObservation:
    """One observed solve, reduced to the cost model's coordinates.

    ``gather_bytes_per_iteration`` is the padded slot work the model's
    memory term prices (``slots_max * (itemsize + 4)``);
    ``net_bytes_per_iteration`` the wire-priced bytes of the exchange
    lane that actually ran (fixed x-rotation payload, or the packed
    coupled-entry rounds - ``balance.plan.wire_bytes_for``) - both
    computed by :func:`observation_for` from a ``ShardReport`` so
    predicted and measured always price the same terms.
    """

    iterations: int
    elapsed_s: float
    gather_bytes_per_iteration: float
    net_bytes_per_iteration: float
    label: str = ""

    def __post_init__(self):
        if self.iterations < 1:
            raise ValueError(
                f"observation needs >= 1 iteration, got {self.iterations}")
        if self.elapsed_s <= 0.0:
            raise ValueError(
                f"observation needs elapsed_s > 0, got {self.elapsed_s}")

    @property
    def s_per_iteration(self) -> float:
        return self.elapsed_s / self.iterations

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def observation_for(report, iterations: int, elapsed_s: float, *,
                    itemsize: int,
                    comm_bytes_per_iteration: Optional[float] = None,
                    exchange: str = "allgather",
                    label: str = "") -> PhaseObservation:
    """Build the observation for one solve from its static accounting.

    ``report`` is the coupling-semantics ``ShardReport`` of the layout
    that ran (``shardscope.report_for_ranges`` / the plan's predicted
    report) - the same report ``balance.plan.score_report`` prices, so
    the fit corrects exactly the model that planned.  ``exchange``
    names the halo wire the solve ran; its per-iteration bytes come
    from ``balance.plan.wire_bytes_for`` (the planner's own term -
    fixed payload for allgather/ring, the full-weight packed coupled
    rounds for gather; the historical 0.25 coupling fudge is gone on
    both sides at once, so predicted and measured stay one model).
    When the jaxpr-derived per-iteration wire is known
    (``dist_cg.last_comm_cost``), pass it as
    ``comm_bytes_per_iteration`` to replace the analytic term.
    """
    from ..balance.plan import wire_bytes_for

    gather = float(report.slots.max()) * (itemsize + 4)
    if comm_bytes_per_iteration is not None:
        net = float(comm_bytes_per_iteration)
    else:
        net = wire_bytes_for(report, exchange, itemsize)
    return PhaseObservation(
        iterations=int(iterations), elapsed_s=float(elapsed_s),
        gather_bytes_per_iteration=gather,
        net_bytes_per_iteration=net, label=label)


def observations_from_profile(profile, *, label: str = "phase"
                              ) -> List[PhaseObservation]:
    """Phase-resolved observations from ONE measured phase profile
    (``telemetry.phasetrace.PhaseProfile``) - the constructor that
    makes the ``lstsq2`` confident tier routine without ``--repeat``.

    A whole-solve wall time collapses the gather and wire terms into
    one number, so a single solve could historically only reach the
    degraded ``fixed-net`` tier.  A phase profile measured the two
    terms SEPARATELY, so it decomposes into two observations whose
    byte ratios are orthogonal by construction:

    * the SpMV phase (the mesh-measured phase wall, priced at the
      planner's ``slots.max()`` slot coordinate - the same
      wall-per-coordinate convention the whole-solve fit uses) with
      zero wire bytes - determines the effective gather bandwidth;
    * the halo phase (every exchange round, the lane's real padded
      wire bytes) with zero gather bytes - determines net bytes/s.

    Each observation carries ``iterations=profile.repeats`` (the
    measured repetitions), so the fit's iteration floor
    (:data:`MIN_CALIBRATION_ITERATIONS`) is met by any profile with
    ``repeats >= 4``.  The reduction phase is deliberately dropped:
    the planner's cost model has no latency term, and folding barrier
    time into a bandwidth would corrupt both estimates.
    """
    reps = int(profile.repeats)
    spmv_s = float(profile.spmv_mesh_s)
    obs = [PhaseObservation(
        iterations=reps, elapsed_s=spmv_s * reps,
        gather_bytes_per_iteration=float(profile.gather_bytes),
        net_bytes_per_iteration=0.0, label=f"{label}:spmv")]
    if profile.halo_s > 0.0 and profile.wire_bytes > 0:
        obs.append(PhaseObservation(
            iterations=reps, elapsed_s=float(profile.halo_s) * reps,
            gather_bytes_per_iteration=0.0,
            net_bytes_per_iteration=float(profile.wire_bytes),
            label=f"{label}:halo"))
    return obs


def fit_link_bandwidths(rounds) -> List[dict]:
    """Per-link bandwidth estimates from individually timed exchange
    rounds: ``rounds`` is an iterable of ``(shift, bytes, seconds)``
    (or dicts with those keys) - one entry per ``ppermute`` round, as
    ``telemetry.phasetrace`` measures them.  Each round is one ring
    rotation (shard ``j`` -> ``(j + shift) % P``), timed alone, so its
    bandwidth is directly ``bytes / seconds`` - exact recovery, no
    least squares (tests feed synthetic timings and get the chosen
    bandwidths back bit-exactly).  Links only separate when round
    payloads differ; uniform payloads still yield honest (equal)
    estimates."""
    out = []
    for r in rounds:
        if isinstance(r, dict):
            shift, nbytes, secs = r["shift"], r["bytes"], r["seconds"]
        else:
            shift, nbytes, secs = r
        out.append({
            "shift": int(shift),
            "bytes": int(nbytes),
            "seconds": float(secs),
            "bytes_per_s": float(nbytes) / max(float(secs), 1e-300),
        })
    return out


@dataclasses.dataclass(frozen=True)
class CalibrationFit:
    """Outcome of fitting the machine model to observed solves."""

    model: MachineModel
    method: str            # "lstsq2" | "fixed-net" | "proportional"
    residual_rel: float    # max relative per-observation fit error
    n_observations: int
    total_iterations: int
    confident: bool
    backend: str
    host: str

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["model"] = self.model.to_json()
        return out

    @classmethod
    def from_json(cls, data: dict) -> "CalibrationFit":
        if not isinstance(data, dict):
            raise TypeError(
                f"calibration JSON must be an object, got "
                f"{type(data).__name__}")
        return cls(
            model=MachineModel.from_json(data["model"]),
            method=str(data.get("method", "?")),
            residual_rel=float(data.get("residual_rel", float("nan"))),
            n_observations=int(data.get("n_observations", 0)),
            total_iterations=int(data.get("total_iterations", 0)),
            confident=bool(data.get("confident", False)),
            backend=str(data.get("backend", "?")),
            host=str(data.get("host", "?")),
        )

    def describe(self) -> str:
        m = self.model
        net = m.net_bytes_per_s or 0.0
        return (f"{m.name}: gather x{m.gather_slowdown:.2f} slowdown "
                f"(eff {m.mem_bytes_per_s / m.gather_slowdown / 1e9:.2f} "
                f"GB/s of {m.mem_bytes_per_s / 1e9:.2f} stream), net "
                f"{net / 1e9:.2f} GB/s; fit {self.method}, residual "
                f"{self.residual_rel * 100:.1f}%, "
                f"{'confident' if self.confident else 'LOW CONFIDENCE'} "
                f"({self.n_observations} obs, "
                f"{self.total_iterations} iters)")


def _solve_2x2(a, b, y):
    """Least-squares ``y ~ a*u + b*v`` via the normal equations;
    returns ``(u, v)`` or ``None`` when the design is (near) rank
    deficient - one observation, or observations whose gather/net byte
    ratios are indistinguishable."""
    g = np.array([[float(a @ a), float(a @ b)],
                  [float(a @ b), float(b @ b)]])
    rhs = np.array([float(a @ y), float(b @ y)])
    det = g[0, 0] * g[1, 1] - g[0, 1] * g[1, 0]
    if det <= 1e-12 * max(g[0, 0] * g[1, 1], 1e-300):
        return None
    u, v = np.linalg.solve(g, rhs)
    return float(u), float(v)


def fit_machine_model(observations: Sequence[PhaseObservation], *,
                      base: Optional[MachineModel] = None,
                      backend: Optional[str] = None,
                      per_link=None) -> CalibrationFit:
    """Fit the planner's cost model to observed per-iteration times.

    Model: ``t_iter = gather_bytes / gather_bw + net_bytes / net_bw``
    with unknown effective bandwidths.  Strategy, most to least
    determined:

    1. **lstsq2** - >= 2 observations with distinct byte ratios: both
       bandwidths from the 2x2 normal equations;
    2. **fixed-net** - the net term is pinned at the base model's
       bandwidth and only the gather bandwidth is fitted (the only
       honest option for a single observation);
    3. **proportional** - if a fitted bandwidth came out non-positive
       (the model family cannot explain the data), both reference
       bandwidths are scaled by measured/modeled total time; never
       marked confident.

    The returned model keeps the base model's streaming
    ``mem_bytes_per_s`` and ``flops_per_s`` (a CG solve cannot measure
    a matmul) and reports ``gather_slowdown = stream_bw / gather_bw``.

    ``per_link`` optionally attaches per-link wire bandwidths (the
    :func:`fit_link_bandwidths` output, or raw ``(shift, bytes/s)``
    pairs) to the fitted model's ``per_link`` field - they ride the
    calibration cache and every ``drift_report``/``solve_sequence``
    replan that adopts the model, without changing the aggregate
    ``net_bytes_per_s`` the planner prices today (two-tier wire
    pricing is ROADMAP item 4's consumer).
    """
    obs = list(observations)
    if not obs:
        raise ValueError("fit_machine_model needs >= 1 observation")
    if backend is None:
        import jax

        backend = jax.default_backend()
    if base is None:
        base = machine_model(backend)
    base_net = float(base.net_bytes_per_s or base.mem_bytes_per_s)

    a = np.array([o.gather_bytes_per_iteration for o in obs],
                 dtype=np.float64)
    b = np.array([o.net_bytes_per_iteration for o in obs],
                 dtype=np.float64)
    y = np.array([o.s_per_iteration for o in obs], dtype=np.float64)
    total_iters = int(sum(o.iterations for o in obs))

    method = None
    u = v = None                      # u = 1/gather_bw, v = 1/net_bw
    if len(obs) >= 2:
        sol = _solve_2x2(a, b, y)
        if sol is not None and sol[0] > 0.0 and sol[1] > 0.0:
            u, v = sol
            method = "lstsq2"
    if method is None:
        # pin the net term at the base model and fit the gather term
        v_fixed = 1.0 / base_net
        resid = y - b * v_fixed
        denom = float(a @ a)
        u_fit = float(a @ resid) / denom if denom > 0.0 else -1.0
        if u_fit > 0.0:
            u, v = u_fit, v_fixed
            method = "fixed-net"
    if method is None:
        # proportional fallback: scale the whole reference model by the
        # measured/modeled time ratio (the model family cannot separate
        # the terms for this data) - never confident
        ref_gather_bw = base.mem_bytes_per_s / max(
            base.gather_slowdown, 1e-9)
        t_model = a / ref_gather_bw + b / base_net
        factor = float(np.mean(y / np.maximum(t_model, 1e-300)))
        factor = max(factor, 1e-9)
        u = factor / ref_gather_bw
        v = factor / base_net
        method = "proportional"

    gather_bw = 1.0 / u
    net_bw = 1.0 / v
    pred = a * u + b * v
    residual = float(np.max(np.abs(pred - y) / np.maximum(y, 1e-300)))

    from ..utils.tune import host_fingerprint

    host = host_fingerprint()
    gather_slowdown = max(base.mem_bytes_per_s / gather_bw, 1e-3)
    links = None
    if per_link:
        links = tuple(
            (int(e["shift"]), float(e["bytes_per_s"]))
            if isinstance(e, dict) else (int(e[0]), float(e[1]))
            for e in per_link)
    model = MachineModel(
        name=f"calibrated-{backend}-{host}",
        mem_bytes_per_s=base.mem_bytes_per_s,
        flops_per_s=base.flops_per_s,
        net_bytes_per_s=net_bw,
        source="calibrated",
        gather_slowdown=gather_slowdown,
        created_at=time.time(),
        per_link=links)
    confident = (method != "proportional"
                 and total_iters >= MIN_CALIBRATION_ITERATIONS
                 and residual <= CONFIDENT_RESIDUAL)
    return CalibrationFit(
        model=model, method=method, residual_rel=residual,
        n_observations=len(obs), total_iterations=total_iters,
        confident=confident, backend=backend, host=host)


# ---------------------------------------------------------------------------
# persistence (the measured-artifact disk cache, utils.tune.JsonCache)

def cache_key(backend: Optional[str] = None,
              host: Optional[str] = None) -> str:
    from ..utils.tune import host_fingerprint

    if backend is None:
        import jax

        backend = jax.default_backend()
    return f"calibration-{backend}-{host or host_fingerprint()}"


def store_calibration(fit: CalibrationFit, cache=None) -> Optional[str]:
    """Persist a fit for :func:`load_calibration`/:func:`preferred_model`
    (best-effort: an unwritable cache directory returns ``None`` rather
    than failing the solve that produced the fit)."""
    from ..utils.tune import JsonCache

    if cache is None:
        cache = JsonCache()
    try:
        return cache.put(cache_key(fit.backend, fit.host), fit.to_json(),
                         created_at=fit.model.created_at)
    except (OSError, ValueError):
        return None


def load_calibration(backend: Optional[str] = None, cache=None,
                     max_age_s: float = CALIBRATION_MAX_AGE_S
                     ) -> Optional[CalibrationFit]:
    """The stored fit for ``backend`` on this host, or ``None`` when
    missing, stale, or unparseable."""
    from ..utils.tune import JsonCache

    if cache is None:
        cache = JsonCache()
    entry = cache.get(cache_key(backend), max_age_s=max_age_s)
    if entry is None:
        return None
    try:
        return CalibrationFit.from_json(entry["payload"])
    except (KeyError, TypeError, ValueError):
        return None


def preferred_model(backend: Optional[str] = None, cache=None
                    ) -> Optional[MachineModel]:
    """The calibrated model a planner should prefer, or ``None``.

    Only a fresh AND confident stored fit qualifies - an unconfident
    fit must never silently steer plans (the reference model is the
    safe default).  ``None`` keeps ``plan_partition`` on the
    deterministic reference table, so with no calibration on disk the
    planning path is bit-identical to pre-calibration behavior.
    """
    fit = load_calibration(backend, cache)
    if fit is None or not fit.confident:
        return None
    return fit.model


# ---------------------------------------------------------------------------
# drift: predicted-vs-measured model error, tracked per solve

@dataclasses.dataclass(frozen=True)
class DriftReport:
    """How wrong the machine model was about one solve."""

    predicted_s_per_iteration: float
    measured_s_per_iteration: float
    drift_pct: float               # 100 * (measured - predicted) / predicted
    model: str                     # name of the model that predicted
    plan: str                      # layout lane ("even", "rcm+nnz", ...)
    fingerprint: Optional[str] = None
    iterations: int = 0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def describe(self) -> str:
        return (f"model error {self.drift_pct:+.1f}% "
                f"(predicted {self.predicted_s_per_iteration * 1e6:.3g} "
                f"us/iter vs measured "
                f"{self.measured_s_per_iteration * 1e6:.3g} on "
                f"{self.model})")


def drift_report(report, iterations: int, elapsed_s: float, *,
                 itemsize: int, model: Optional[MachineModel] = None,
                 plan=None, exchange: Optional[str] = None
                 ) -> DriftReport:
    """Predicted-vs-measured stall-time drift for one solve.

    ``report``/``itemsize`` describe the layout that ran (coupling
    semantics); ``model`` is the machine model that PRICED it (the one
    that chose the plan - reference unless a calibrated model was
    passed), so drift measures that model's error, not the best
    possible model's.  ``exchange`` names the halo wire the solve ran
    (default: the plan's scored lane, or allgather) - the drift
    contract extends to the wire: prediction prices the same exchange
    that moved the bytes."""
    from ..balance.plan import score_report

    if exchange is None:
        exchange = getattr(plan, "exchange", "allgather") \
            if plan is not None else "allgather"
    predicted = score_report(report, itemsize=itemsize, model=model,
                             exchange=exchange)
    measured = float(elapsed_s) / max(int(iterations), 1)
    drift = 100.0 * (measured - predicted) / max(predicted, 1e-300)
    if model is None:
        from ..balance.plan import reference_model

        model = reference_model()
    return DriftReport(
        predicted_s_per_iteration=predicted,
        measured_s_per_iteration=measured,
        drift_pct=drift, model=str(model.name),
        plan=(plan.label if plan is not None else "even"),
        fingerprint=(plan.fingerprint() if plan is not None else None),
        iterations=int(iterations))


def note_drift(drift: DriftReport, *, report=None,
               plan=None, n_shards: Optional[int] = None) -> DriftReport:
    """Publish a drift measurement: registry gauges always, plus (when
    an event sink is active) the EXTENDED ``partition_plan`` event -
    the partition-time event's required fields re-stated with the
    post-solve ``drift_pct``/predicted/measured stall seconds attached
    and ``stage="drift"`` so consumers can tell the two apart."""
    from .. import telemetry
    from .registry import REGISTRY

    REGISTRY.gauge(
        "plan_drift_pct",
        "predicted-vs-measured per-iteration stall-time model error %"
        " of the most recent solve",
        labelnames=("plan",)).set(drift.drift_pct, plan=drift.plan)
    REGISTRY.gauge(
        "plan_predicted_s_per_iteration",
        "modeled per-iteration stall seconds of the layout that ran",
        labelnames=("plan",)).set(drift.predicted_s_per_iteration,
                                  plan=drift.plan)
    REGISTRY.gauge(
        "plan_measured_s_per_iteration",
        "measured per-iteration wall seconds of the layout that ran",
        labelnames=("plan",)).set(drift.measured_s_per_iteration,
                                  plan=drift.plan)
    if telemetry.events.active():
        reorder, split = "none", "even"
        exchange = "allgather"
        if plan is not None:
            reorder, split = plan.reorder, plan.split
            exchange = getattr(plan, "exchange", "allgather")
        shards = n_shards
        if shards is None:
            shards = (plan.n_shards if plan is not None
                      else (report.n_shards if report is not None else 0))
        measured_imb = (report.imbalance() if report is not None
                        else None)
        telemetry.events.emit(
            "partition_plan", stage="drift", reorder=reorder,
            split=split, exchange=exchange, n_shards=int(shards),
            measured=measured_imb,
            drift_pct=drift.drift_pct,
            predicted_s_per_iteration=drift.predicted_s_per_iteration,
            measured_s_per_iteration=drift.measured_s_per_iteration,
            model=drift.model,
            **({"fingerprint": drift.fingerprint}
               if drift.fingerprint else {}))
    return drift


def note_calibration(fit: CalibrationFit) -> CalibrationFit:
    """Export a fit's parameters as registry gauges (labeled by
    backend), so calibration itself is observable across runs."""
    from .registry import REGISTRY

    m = fit.model
    for gname, help_, val in (
            ("calibration_gather_slowdown",
             "measured sparse-gather slowdown vs streaming bandwidth",
             m.gather_slowdown),
            ("calibration_mem_bytes_per_s",
             "streaming memory bandwidth of the calibrated model",
             m.mem_bytes_per_s),
            ("calibration_net_bytes_per_s",
             "network bandwidth of the calibrated model",
             m.net_bytes_per_s or 0.0),
            ("calibration_residual_rel",
             "max relative fit residual of the calibrated model",
             fit.residual_rel),
            ("calibration_confident",
             "1 when the stored calibration is confident enough to "
             "steer plans", 1.0 if fit.confident else 0.0)):
        REGISTRY.gauge(gname, help_, labelnames=("backend",)).set(
            val, backend=fit.backend)
    for shift, bps in (m.per_link or ()):
        REGISTRY.gauge(
            "calibration_link_bytes_per_s",
            "measured per-link halo-wire bandwidth (phase profiler; "
            "ring-rotation shift identifies the link)",
            labelnames=("backend", "shift")).set(
                bps, backend=fit.backend, shift=str(shift))
    return fit
