"""Many-RHS solver tier: masked batched CG and true block-CG.

Production traffic is thousands of concurrent medium systems, not one
giant solve (ROADMAP item 1), and SpMV is memory-bound - its throughput
is sustained stream bandwidth (arXiv 2204.00900) - so every extra RHS
column riding one matrix sweep is nearly free FLOPs.  This module
solves ``A X = B`` for a column stack ``B`` of shape ``(n, k)`` with
ONE matrix sweep (``LinearOperator.matmat`` - an SpMM) and ONE fused
reduction (``blas1.dot_many`` - a k-wide psum on a mesh) per iteration,
in two flavors:

* **masked batched CG** (``method="batched"``): ``k`` textbook CG
  recurrences run in lockstep through one ``lax.while_loop``; alpha/
  beta/rr are per-lane ``(k,)`` vectors and a convergence mask freezes
  finished lanes in the carry (a ``jnp.where`` select per update - no
  early-exit serialization, no NaN leakage from frozen lanes).  The
  loop runs until the LAST live lane meets its tolerance.  Lanes are
  arithmetically independent: at ``check_every=1`` lane ``j``'s
  iterates are bit-identical to a single-RHS ``cg`` solve of column
  ``j`` (tests assert exact equality at ``k = 1`` and per-lane), so
  batching never changes an answer - it only amortizes the matrix
  sweep and the collective latency across lanes.  Under
  ``check_every > 1`` the single-RHS solver runs up to k-1 UNMASKED
  extra steps past convergence inside a block while a batched lane
  freezes exactly at its convergence step - the batched iterate is
  the check_every=1 answer, the single-RHS one drifts below it.
* **true block-CG** (``method="block"``, O'Leary 1980): the search
  directions span a k-dimensional block Krylov space coupled through a
  ``k x k`` Gram solve per iteration (Cholesky on the MXU-friendly
  small dense block).  Every lane taps every lane's subspace, so
  convergence takes measurably fewer iterations than the independent
  recurrences - the s-step/block communication-avoiding win of arXiv
  1612.08060 - at the price of two small Gram factorizations per
  iteration.  Rank collapse (converged/duplicate columns make the Gram
  singular - Cholesky yields NaN) is detected IN the loop: the state
  freezes one step before poisoning, the loop exits, and a masked
  batched continuation (same trace, zero host round-trips) finishes
  the unconverged lanes from the frozen iterate.

Both run under ``jit``/``shard_map`` exactly like ``solver.cg``; the
distributed entry (``parallel.solve_distributed_many``) ships all ``k``
columns through ONE halo exchange per iteration.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..models.operators import IdentityOperator, LinearOperator
from ..ops import blas1
from .cg import (
    CGResult,
    _as_operator,
    _blocked_while,
    _note_engine,
    _safe_div,
)
from .status import CGStatus

__all__ = ["CGBatchResult", "cg_many", "solve_many", "stack_columns"]

#: batched-solver recurrences accepted by :func:`cg_many`
MANY_METHODS = ("batched", "block")


def stack_columns(columns, k: int, dtype=None):
    """Stack 1-D right-hand sides into a zero-padded ``(n, k)`` batch.

    The serving tier's bucket-padding primitive: a microbatch of ``m``
    requests dispatches on the smallest compiled lane bucket ``k >= m``
    and the ``k - m`` pad lanes carry ``b = 0`` - a zero-RHS lane has
    ``||r0|| = 0``, so both recurrences freeze it at iteration 0
    (``_active_lanes``'s ``rr > 0`` clause; tests assert the 0-iter
    freeze) and a padded dispatch costs the same sweeps as a full one,
    never extra iterations.  ``dtype=None`` takes the common numpy
    result type of the columns.
    """
    import numpy as np

    if k < 1:
        raise ValueError(f"bucket size must be >= 1, got {k}")
    cols = [np.asarray(c) for c in columns]
    if not cols:
        raise ValueError("stack_columns needs at least one column")
    if len(cols) > k:
        raise ValueError(
            f"{len(cols)} columns do not fit a k={k} bucket")
    n = cols[0].shape[0]
    for c in cols:
        if c.ndim != 1 or c.shape[0] != n:
            raise ValueError(
                f"columns must be 1-D of one length, got shapes "
                f"{[c.shape for c in cols]}")
    if dtype is None:
        dtype = np.result_type(*cols)
    out = np.zeros((n, k), dtype=dtype)
    for j, c in enumerate(cols):
        out[:, j] = c
    return out


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x", "iterations", "residual_norm", "converged",
                 "status", "indefinite", "flight", "fallback", "basis"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class CGBatchResult:
    """Per-lane outcome of a many-RHS solve.

    Every field after ``x`` is a ``(k,)`` per-lane array - each column
    gets the full ``CGResult`` story (status/iterations/residual), not
    a batch-wide summary; :meth:`lane` slices out a standard
    ``CGResult`` view of one column.
    """

    x: jax.Array               # (n, k) solution stack
    iterations: jax.Array      # (k,) per-lane iterations to freeze
    residual_norm: jax.Array   # (k,) final ||r_j||_2
    converged: jax.Array       # (k,) bool
    status: jax.Array          # (k,) CGStatus int codes
    indefinite: jax.Array      # (k,) bool: lane saw p.Ap <= 0
    #: batched flight buffer (capacity, 1 + 3k) when a FlightConfig was
    #: passed; decode with telemetry.flight.lanes_from_buffer
    flight: Optional[jax.Array] = None
    #: block-CG only: True when the Gram solve broke down PAST the
    #: in-lane rank deflation and the masked-batched continuation
    #: finished the solve (None = batched)
    fallback: Optional[jax.Array] = None
    #: Krylov-recycling basis ring ``(iterations, vectors)`` when a
    #: recycle.BasisConfig was passed (records one lane's normalized
    #: residuals); feed to recycle.harvest_space(n_rhs=..., lane=...)
    basis: Optional[tuple] = None

    @property
    def n_rhs(self) -> int:
        return int(self.x.shape[1])

    def lane(self, j: int) -> CGResult:
        """A single column's result as a standard ``CGResult`` (the
        flight buffer does not slice device-side - use
        ``telemetry.flight.lanes_from_buffer`` on ``self.flight``)."""
        return CGResult(
            x=self.x[:, j], iterations=self.iterations[j],
            residual_norm=self.residual_norm[j],
            converged=self.converged[j], status=self.status[j],
            indefinite=self.indefinite[j], residual_history=None)

    def status_enums(self):
        import numpy as np

        return [CGStatus(int(s)) for s in np.asarray(self.status)]


class _ManyState(NamedTuple):
    k: jax.Array            # global loop iteration (scalar)
    x: jax.Array            # (n, k)
    r: jax.Array            # (n, k)
    p: jax.Array            # (n, k)
    rho: jax.Array          # (k,) r . z per lane
    rr: jax.Array           # (k,) ||r||^2 per lane
    iters: jax.Array        # (k,) per-lane iterations (frozen with lane)
    indefinite: jax.Array   # (k,) bool


class _BlockState(NamedTuple):
    k: jax.Array
    x: jax.Array            # (n, k)
    r: jax.Array            # (n, k)
    p: jax.Array            # (n, k)
    gamma: jax.Array        # (k, k) Gram R^T Z
    rr: jax.Array           # (k,) per-lane ||r||^2
    iters: jax.Array        # (k,)
    indefinite: jax.Array   # (k,)
    broke: jax.Array        # () bool: Gram solve went non-finite


def _threshold_sq_many(tol, rtol, nrm0: jax.Array, dtype) -> jax.Array:
    """Per-lane squared threshold ``max(tol, rtol * ||r0_j||)^2``;
    ``tol``/``rtol`` may be scalars or ``(k,)`` per-lane arrays (mixed
    tolerances - each lane freezes on its own bar)."""
    threshold = jnp.maximum(
        jnp.broadcast_to(jnp.asarray(tol, dtype), nrm0.shape),
        jnp.asarray(rtol, dtype) * nrm0)
    return threshold * threshold


def _active_lanes(rr, rho, thresh_sq):
    """The per-lane liveness mask: unconverged, nontrivial (rr > 0 -
    an exactly-solved lane would divide 0/0) and healthy (finite
    scalars, SPD rho) - the same three clauses as ``cg``'s predicate,
    per lane."""
    unconverged = rr >= thresh_sq
    nontrivial = rr > 0
    healthy = jnp.isfinite(rr) & jnp.isfinite(rho) & (rho > 0)
    return unconverged & nontrivial & healthy


def _select_lanes(mask, new, old):
    """Per-lane select of an ``(n, k)`` stack update: frozen lanes keep
    their column bit-for-bit (a select, so NaN garbage computed for a
    frozen lane never propagates)."""
    return jnp.where(mask[None, :], new, old)


def _init_xr_many(a, b, x0):
    if x0 is None:
        return jnp.zeros_like(b), b   # r0 = B - A@0 = B: copy-only init
    x = jnp.asarray(x0, b.dtype)
    return x, b - a.matmat(x)


def _package_many(final, thresh_sq, flight_buf=None,
                  fallback=None, basis_buf=None) -> CGBatchResult:
    """Per-lane epilogue: the same status derivation as ``cg``'s
    ``_package``, vectorized over lanes."""
    nrm = jnp.sqrt(final.rr)
    converged = (final.rr < thresh_sq) | (final.rr == 0)
    healthy = jnp.isfinite(final.rr) & jnp.isfinite(final.rho) \
        & ((final.rho > 0) | (final.rr == 0))
    status = jnp.where(
        converged,
        jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return CGBatchResult(
        x=final.x, iterations=final.iters, residual_norm=nrm,
        converged=converged, status=status, indefinite=final.indefinite,
        flight=flight_buf, fallback=fallback, basis=basis_buf)


def cg_many(
    a,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol=1e-7,
    rtol=0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    axis_name: Optional[str] = None,
    iter_cap=None,
    check_every: int = 1,
    method: str = "batched",
    compensated: bool = False,
    flight=None,
    fault=None,
    deflate=None,
    basis=None,
) -> CGBatchResult:
    """Solve ``A X = B`` for all columns of ``B`` in one loop.

    Args:
      a: SPD ``LinearOperator`` (or raw 2-D array).  Applied via
        ``matmat`` - one matrix sweep per iteration serves every lane.
      b: right-hand-side column stack, shape ``(n, k)``.
      x0: optional initial stack ``(n, k)``; ``None`` = zeros (the
        copy-only init fast path, per lane).
      tol/rtol: scalars or per-lane ``(k,)`` arrays - mixed tolerances
        freeze each lane on its own bar.
      m: optional preconditioner (applied via ``matmat``).
      axis_name: mesh axis for row-partitioned execution; the per-lane
        reductions ride ONE ``lax.psum`` per evaluation point.
      method: ``"batched"`` (masked independent recurrences - lane
        ``j`` bit-matches a single-RHS solve of column ``j`` at
        ``check_every=1``; see the module docstring for the
        ``check_every > 1`` freeze-at-convergence difference) or
        ``"block"`` (O'Leary block-CG: coupled k-dim Krylov space,
        fewer iterations, Gram-breakdown falls back to the batched
        recurrence inside the same trace).
      compensated: double-float per-lane inner products
        (``blas1.dot_many_compensated``); ``"batched"`` only.
      flight: optional ``telemetry.flight.FlightConfig`` - carry the
        batched flight recorder (per-lane ``||r||^2``/alpha/beta rows,
        ``(capacity, 1 + 3k)``) in the loop state; ``"batched"`` only
        (block-CG's recurrence scalars are ``k x k`` matrices, not
        per-lane pairs).  ``None`` leaves the traced jaxpr untouched.
      fault: optional ``robust.FaultPlan`` (``method="batched"``
        only - block-CG's Gram-collapse fallback would mask an armed
        fault as a rank event).  Array sites (halo/spmv) poison one
        ROW of the stack - every lane breaks down together; the
        ``reduction`` site poisons lane ``fault.lane``'s scalar only,
        so the chaos matrix can prove per-lane failure isolation (the
        poisoned lane exits BREAKDOWN while its batchmates converge).
        ``None`` leaves the traced jaxpr untouched.
      deflate: optional ``recycle.RecycleSpace`` - Krylov-recycling
        deflation of every lane (``solver.recycle``): the entry
        Galerkin correction and the per-iteration direction projection
        apply column-wise, and the ``(k_defl, k_rhs)`` projection
        reduction FUSES into the per-lane residual psum (per-iteration
        collective count unchanged).  ``method="batched"`` only
        (block-CG carries its own in-lane rank deflation).  ``None``
        leaves the traced jaxpr untouched.
      basis: optional ``recycle.BasisConfig`` - carry the recycling
        basis ring recording lane ``basis.lane``'s normalized
        residuals; requires ``flight`` (stride-1) and
        ``method="batched"``.  ``None`` compiles to nothing.
      (maxiter/iter_cap/check_every as in ``solver.cg``.)

    Returns a :class:`CGBatchResult` with per-lane status/iterations/
    residual.  Pure and traceable - call under ``jit`` (or use
    :func:`solve_many`).
    """
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if b.ndim != 2:
        raise ValueError(
            f"cg_many solves a column stack: b must be (n, k), got "
            f"shape {b.shape} (use solver.cg for a single RHS)")
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    if axis_name is None and a.shape[1] != b.shape[0]:
        raise ValueError(f"operator shape {a.shape} does not match rhs "
                         f"stack shape {b.shape}")
    if method not in MANY_METHODS:
        raise ValueError(f"unknown method {method!r}; expected one of "
                         f"{MANY_METHODS}")
    if flight is not None and method != "batched":
        raise ValueError(
            "the batched flight recorder records per-lane (rr, alpha, "
            "beta) scalars; block-CG's recurrence coefficients are "
            "k x k matrices - use method='batched' with flight, or "
            "drop the recorder")
    if compensated and method != "batched":
        raise ValueError("compensated dots ride the per-lane batched "
                         "recurrence only")
    if fault is not None:
        if method != "batched":
            raise ValueError(
                "fault injection (robust.FaultPlan) rides "
                "method='batched' only: block-CG's in-trace "
                "Gram-collapse fallback would mask an armed fault as "
                "a rank event instead of a typed BREAKDOWN")
        fault.validate_for_operator(
            a, n_shards=1 if axis_name is None
            else getattr(a, "n_shards", 1))
    if deflate is not None:
        from .recycle import RecycleSpace

        if not isinstance(deflate, RecycleSpace):
            raise TypeError(
                f"deflate must be a solver.recycle.RecycleSpace, got "
                f"{type(deflate).__name__}")
        if method != "batched":
            raise ValueError(
                "deflate= (Krylov recycling) rides method='batched' "
                "only: block-CG deflates rank collapse in-lane "
                "through its own Gram pseudo-inverse")
        if compensated or fault is not None:
            raise ValueError(
                "deflate= does not compose with compensated dots or "
                "fault injection (the deflated recurrence is its own "
                "lane)")
    if basis is not None:
        from .recycle import BasisConfig

        if not isinstance(basis, BasisConfig):
            raise TypeError(
                f"basis must be a solver.recycle.BasisConfig, got "
                f"{type(basis).__name__}")
        if method != "batched":
            raise ValueError(
                "basis= (the recycling harvest ring) rides "
                "method='batched' only (block-CG's recurrence scalars "
                "are k x k matrices, not a lane's Lanczos process)")
        if flight is None:
            raise ValueError(
                "basis= needs flight= (a stride-1 FlightConfig): the "
                "harvest combines the basis ring with the recorder's "
                "alpha/beta tridiagonal")
        if basis.lane >= b.shape[1]:
            raise ValueError(
                f"basis.lane={basis.lane} out of range for a "
                f"{b.shape[1]}-column stack")
    preconditioned = m is not None
    if m is None:
        m = IdentityOperator(dim=b.shape[0],
                             _dtype_name=jnp.dtype(b.dtype).name)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                      jnp.int32)

    dot_many = partial(
        blas1.dot_many_compensated if compensated else blas1.dot_many,
        axis_name=axis_name)

    x, r = _init_xr_many(a, b, x0)
    if deflate is not None:
        # Galerkin entry correction, column-wise: every lane's r0
        # starts orthogonal to the recycled space (one (k_defl x
        # k_rhs)-wide psum at entry on a mesh)
        from .recycle import entry_project

        x, r = entry_project(deflate, x, r, axis_name)
    rr0 = dot_many(r, r)
    if preconditioned:
        z = m.matmat(r)
        rho0 = dot_many(r, z)
    else:
        z, rho0 = r, rr0
    nrm0 = jnp.sqrt(rr0)
    thresh_sq = _threshold_sq_many(tol, rtol, nrm0, b.dtype)
    k0 = jnp.zeros((), jnp.int32)
    iters0 = jnp.zeros(b.shape[1], jnp.int32)
    indef0 = jnp.zeros(b.shape[1], jnp.bool_)

    if method == "block":
        gamma0 = blas1.gram(r, z, axis_name=axis_name)
        bstate = _BlockState(
            k=k0, x=x, r=r, p=z, gamma=gamma0, rr=rr0,
            iters=iters0, indefinite=indef0,
            broke=jnp.zeros((), jnp.bool_))
        final, fell_back = _run_block(
            a, b, m, preconditioned, bstate, thresh_sq, maxiter, cap,
            check_every, dot_many, axis_name)
        return _package_many(final, thresh_sq, fallback=fell_back)

    if deflate is None:
        p0 = z
    else:
        from .recycle import project_direction

        p0 = project_direction(deflate, z, axis_name)
    state = _ManyState(
        k=k0, x=x, r=r, p=p0, rho=rho0, rr=rr0,
        iters=iters0, indefinite=indef0)
    final, fbuf, bbuf = _run_batched(a, m, preconditioned, state,
                                     thresh_sq, maxiter, cap,
                                     check_every, dot_many, flight,
                                     b.dtype, fault=fault,
                                     axis_name=axis_name,
                                     deflate=deflate, basis=basis)
    return _package_many(final, thresh_sq, flight_buf=fbuf,
                         basis_buf=bbuf)


def _batched_step_fn(a, m, preconditioned, thresh_sq, dot_many,
                     fault=None, axis_name=None, deflate=None):
    """One masked batched CG step.  Returns ``(new_state, k, rr,
    alpha, beta)`` - the step plus its per-lane recording scalars (the
    flight recorder's row; traced away when the recorder is off).
    ``fault`` arms the chaos-injection sites exactly as in ``cg``'s
    step (``fault=None`` is the untouched path); ``deflate`` routes
    the direction update through the recycling projector with its
    ``(k_defl, k_rhs)`` reduction fused into the residual psum
    (``deflate=None`` is the untouched path)."""
    def step_ab(s: _ManyState):
        act = _active_lanes(s.rr, s.rho, thresh_sq)
        if fault is None:
            ap = a.matmat(s.p)                   # ONE sweep, all lanes
        else:
            ap = fault.apply_matvec(a, s.p, s.k, axis_name)
        p_ap = dot_many(s.p, ap)
        if fault is not None:
            p_ap = fault.poison_reduction(p_ap, s.k)
        alpha = _safe_div(s.rho, p_ap)           # (k,) elementwise
        x = _select_lanes(act, blas1.axpy_many(alpha, s.p, s.x), s.x)
        r = _select_lanes(act, blas1.axpy_many(-alpha, ap, s.r), s.r)
        if deflate is None:
            rr_new = dot_many(r, r)
            rr = jnp.where(act, rr_new, s.rr)
            if preconditioned:
                z = m.matmat(r)
                rho_new = dot_many(r, z)
            else:
                z, rho_new = r, rr_new
            beta = _safe_div(rho_new, s.rho)
            rho = jnp.where(act, rho_new, s.rho)
            p = _select_lanes(act, blas1.xpby_many(z, beta, s.p), s.p)
        else:
            # deflated lane: per-lane rr/rho and the (k_defl, k_rhs)
            # projection matrix ride ONE fused psum - the
            # per-iteration collective count matches the undeflated
            # batched solve
            from .recycle import chol_solve

            n_rhs = s.rr.shape[0]
            z = m.matmat(r) if preconditioned else r
            parts = [jnp.einsum("nk,nk->k", r, r)]
            if preconditioned:
                parts.append(jnp.einsum("nk,nk->k", r, z))
            wz_l = deflate.aw.T @ z              # (k_defl, k_rhs)
            fused = jnp.concatenate(parts + [wz_l.reshape(-1)])
            if axis_name is not None:
                from jax import lax

                fused = lax.psum(fused, axis_name)
            rr_new = fused[:n_rhs]
            rho_new = fused[n_rhs:2 * n_rhs] if preconditioned \
                else rr_new
            wz = fused[-deflate.k * n_rhs:].reshape(deflate.k, n_rhs)
            rr = jnp.where(act, rr_new, s.rr)
            beta = _safe_div(rho_new, s.rho)
            rho = jnp.where(act, rho_new, s.rho)
            p_new = blas1.xpby_many(z, beta, s.p) \
                - deflate.w @ chol_solve(deflate.chol, wz)
            p = _select_lanes(act, p_new, s.p)
        k = s.k + 1
        return _ManyState(
            k=k, x=x, r=r, p=p, rho=rho, rr=rr,
            iters=s.iters + act.astype(jnp.int32),
            # s.rr > 0 excludes frozen lanes (p = 0 gives p.Ap = 0,
            # not evidence of indefiniteness) - same rule as cg
            indefinite=s.indefinite | ((p_ap <= 0) & (s.rr > 0) & act),
        ), k, rr, jnp.where(act, alpha, jnp.nan), \
            jnp.where(act, beta, jnp.nan)
    return step_ab


def _run_batched(a, m, preconditioned, state, thresh_sq, maxiter, cap,
                 check_every, dot_many, flight, dtype, fault=None,
                 axis_name=None, deflate=None, basis=None):
    """The masked batched while loop (+ optional flight recorder and
    recycling basis ring).  Returns ``(final, flight_buf,
    basis_buf)``."""
    step_ab = _batched_step_fn(a, m, preconditioned, thresh_sq,
                               dot_many, fault=fault,
                               axis_name=axis_name, deflate=deflate)

    def cond(s: _ManyState) -> jax.Array:
        act = _active_lanes(s.rr, s.rho, thresh_sq)
        return (s.k < maxiter) & (s.k < cap) & jnp.any(act)

    def step(s: _ManyState) -> _ManyState:
        return step_ab(s)[0]

    def fits(s):
        return (s.k + check_every <= maxiter) \
            & (s.k + check_every <= cap)

    if flight is None:
        return _blocked_while(cond, step, state, check_every, fits), \
            None, None

    from ..telemetry.flight import flight_init_many, flight_record_many

    buf0 = flight_init_many(flight, dtype, state.k, state.rr)

    if basis is None:
        def fcond(fs):
            return cond(fs[0])

        def fstep(fs):
            s, buf = fs
            s2, k, rr, alpha, beta = step_ab(s)
            buf = flight_record_many(buf, flight, k, rr, alpha, beta)
            return s2, buf

        final, buf = _blocked_while(fcond, fstep, (state, buf0),
                                    check_every,
                                    lambda fs: fits(fs[0]))
        return final, buf, None

    from .recycle import basis_init_many, basis_record_many

    bbuf0 = basis_init_many(basis, dtype, state.k, state.r, state.rr)

    def bcond(fs):
        return cond(fs[0])

    def bstep(fs):
        s, buf, bbuf = fs
        s2, k, rr, alpha, beta = step_ab(s)
        buf = flight_record_many(buf, flight, k, rr, alpha, beta)
        # the recorded lane writes only while it is LIVE (step_ab
        # masks frozen lanes' alpha to NaN): a lane that converged
        # early must not wrap the ring with its frozen residual while
        # slower batchmates finish - that would evict exactly the
        # rows the harvest needs (serve batches converge unevenly)
        bbuf = basis_record_many(bbuf, basis, k, s2.r, rr,
                                 active=jnp.isfinite(alpha[basis.lane]))
        return s2, buf, bbuf

    final, buf, bbuf = _blocked_while(bcond, bstep,
                                      (state, buf0, bbuf0),
                                      check_every,
                                      lambda fs: fits(fs[0]))
    return final, buf, bbuf


def _gram_rank_deflated_solve(gram_mat, rhs):
    """Eigenvalue pseudo-inverse Gram solve: the block lane's IN-LANE
    rank-collapse deflation (ROADMAP item 2 / the PR-8-named
    follow-up).  Eigendecompose the (symmetrized) Gram, invert only
    the directions above ``GRAM_DEFLATE_RTOL * lambda_max``, and zero
    the collapsed ones - the converged/duplicate direction simply
    drops out of the block step instead of poisoning the factor, and
    the remaining lanes keep their coupled Krylov space.  O(k^3) on a
    k x k block, but it runs ONLY inside the rank-collapse branch of
    ``lax.cond`` - the healthy path stays on Cholesky."""
    sym = 0.5 * (gram_mat + gram_mat.T)
    lam, q = jnp.linalg.eigh(sym)
    lmax = jnp.max(jnp.abs(lam))
    good = lam > GRAM_DEFLATE_RTOL * lmax
    inv = jnp.where(good, 1.0 / jnp.where(good, lam, 1.0), 0.0)
    return q @ (inv[:, None] * (q.T @ rhs))


#: relative eigenvalue floor below which a Gram direction reads as
#: collapsed (converged/duplicate column) and is deflated in-lane
GRAM_DEFLATE_RTOL = 1e-10


def _gram_solve(gram_mat, rhs):
    """``gram_mat^{-1} rhs`` with in-lane rank deflation: the Cholesky
    fast path when the factor is finite (the common, full-rank case -
    bit-identical to the pre-deflation block step), else the
    eigenvalue pseudo-inverse that deflates the collapsed direction
    (``lax.cond`` - one branch executes).  Returns ``(solution,
    collapsed)``; a non-finite SOLUTION even after deflation is the
    terminal tier's signal (the masked-batched continuation)."""
    lw = jnp.linalg.cholesky(gram_mat)
    chol = jax.scipy.linalg.cho_solve((lw, True), rhs)
    ok = jnp.all(jnp.isfinite(chol))
    sol = lax.cond(ok, lambda: chol,
                   lambda: _gram_rank_deflated_solve(gram_mat, rhs))
    return sol, ~ok


def _run_block(a, b, m, preconditioned, bstate, thresh_sq, maxiter,
               cap, check_every, dot_many, axis_name):
    """The block-CG loop plus its in-trace masked-batched continuation.

    Gram rank collapse (converged or linearly dependent columns) is
    first deflated IN-LANE: the collapsed direction is dropped from
    the Gram solves by the eigenvalue pseudo-inverse
    (:func:`_gram_solve`) and the block iteration continues - no
    restart, no lost Krylov space.  Only when even the deflated solve
    goes non-finite (a genuinely poisoned state) does the TERMINAL
    tier fire: the loop freezes (``broke``) one step before the NaN
    would poison the iterate, and the continuation below re-seeds the
    independent recurrences from the frozen ``(x, r)`` (a steepest-
    descent restart: p = z = M r) and runs the SAME masked batched
    loop as ``method="batched"`` under the remaining iteration budget.
    When nothing broke - the common case - every lane is converged (or
    the budget is gone) and the continuation's predicate is false on
    entry: zero extra iterations, zero extra exchanges.
    """
    gram = partial(blas1.gram, axis_name=axis_name)

    def cond(s: _BlockState) -> jax.Array:
        live = (s.rr >= thresh_sq) & (s.rr > 0) & jnp.isfinite(s.rr)
        return (s.k < maxiter) & (s.k < cap) & ~s.broke & jnp.any(live)

    def step(s: _BlockState) -> _BlockState:
        live = (s.rr >= thresh_sq) & (s.rr > 0)
        q = a.matmat(s.p)                     # ONE sweep, all lanes
        w = gram(s.p, q)                      # P^T A P  (k, k)
        alpha, _ = _gram_solve(w, s.gamma)
        x = s.x + s.p @ alpha
        r = s.r - q @ alpha
        z = m.matmat(r) if preconditioned else r
        gamma_new = gram(r, z)
        beta, _ = _gram_solve(s.gamma, gamma_new)
        p = z + s.p @ beta
        rr = dot_many(r, r)
        ok = jnp.all(jnp.isfinite(alpha)) & jnp.all(jnp.isfinite(beta)) \
            & jnp.all(jnp.isfinite(rr))
        # non-finite PAST the in-lane deflation must freeze the
        # PRE-step state: the NaN factors above already contaminated
        # every candidate array (the terminal fallback tier)
        sel = lambda new, old: jnp.where(ok, new, old)
        return _BlockState(
            k=jnp.where(ok, s.k + 1, s.k),
            x=sel(x, s.x), r=sel(r, s.r), p=sel(p, s.p),
            gamma=sel(gamma_new, s.gamma), rr=sel(rr, s.rr),
            iters=s.iters + (ok & live).astype(jnp.int32),
            # diag(P^T A P) <= 0 on a live lane is the block analogue
            # of cg's p.Ap <= 0 indefiniteness probe
            indefinite=s.indefinite
            | (ok & live & (jnp.diagonal(w) <= 0)),
            broke=s.broke | ~ok)

    def fits(s):
        return (s.k + check_every <= maxiter) \
            & (s.k + check_every <= cap)

    final = _blocked_while(cond, step, bstate, check_every, fits)

    # masked-batched continuation from the frozen state (runs 0
    # iterations unless the Gram broke down with live lanes left)
    z = m.matmat(final.r) if preconditioned else final.r
    rho = dot_many(final.r, z) if preconditioned \
        else dot_many(final.r, final.r)
    mstate = _ManyState(
        k=final.k, x=final.x, r=final.r, p=z, rho=rho, rr=final.rr,
        iters=final.iters, indefinite=final.indefinite)
    mfinal, _, _ = _run_batched(a, m, preconditioned, mstate,
                                thresh_sq, maxiter, cap, check_every,
                                dot_many, None, b.dtype)
    fell_back = final.broke & (mfinal.iters > final.iters).any()
    return mfinal, fell_back


@partial(jax.jit, static_argnames=("maxiter", "check_every", "method",
                                   "compensated", "flight", "fault",
                                   "basis"))
def _solve_many_jit(a, b, x0, tol, rtol, maxiter, m, iter_cap,
                    check_every, method, compensated, flight,
                    fault=None, deflate=None, basis=None):
    return cg_many(a, b, x0, tol=tol, rtol=rtol, maxiter=maxiter, m=m,
                   iter_cap=iter_cap, check_every=check_every,
                   method=method, compensated=compensated,
                   flight=flight, fault=fault, deflate=deflate,
                   basis=basis)


def solve_many(
    a,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol=1e-7,
    rtol=0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    iter_cap: Optional[int] = None,
    check_every: int = 1,
    method: str = "batched",
    compensated: bool = False,
    flight=None,
    fault=None,
    deflate=None,
    basis=None,
) -> CGBatchResult:
    """Jitted single-call many-RHS entry point (the ``solve()`` of the
    batched tier): compile once per (operator structure, shapes,
    maxiter, method) and reuse.  ``tol``/``rtol``/``iter_cap`` are
    device values (scalars or per-lane arrays) so sweeping them never
    recompiles.  Single-device; the distributed entry is
    ``parallel.solve_distributed_many``.
    """
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if b.ndim != 2:
        raise ValueError(
            f"solve_many solves a column stack: b must be (n, k), got "
            f"shape {b.shape} (use solve() for a single RHS)")
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    tol_a = jnp.asarray(tol, b.dtype)
    rtol_a = jnp.asarray(rtol, b.dtype)
    cap_a = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                        jnp.int32)
    if deflate is not None:
        from .recycle import check_space

        check_space(deflate, a)         # typed RecycleMismatch
    _note_engine("many", method, check_every, n_rhs=int(b.shape[1]),
                 **({"flight_stride": flight.stride}
                    if flight is not None else {}),
                 **({"fault": fault.fingerprint()}
                    if fault is not None else {}),
                 **({"deflate_k": deflate.k}
                    if deflate is not None else {}))
    return _solve_many_jit(a, b, x0, tol_a, rtol_a, maxiter, m, cap_a,
                           check_every, method, compensated, flight,
                           fault=fault, deflate=deflate, basis=basis)
