"""MINRES: the symmetric-INDEFINITE solver the reference actually needed.

The reference's hardcoded system is symmetric indefinite (eigenvalues
{-0.236, 2, 4.236} - SURVEY quirk Q1, ``CUDACG.cu:76-78``), yet it runs
plain CG, which is only guaranteed for SPD matrices and converges on
that system by luck (p.Ap goes negative at iteration 2).  MINRES
(Paige & Saunders 1975) is the principled algorithm for symmetric
indefinite systems: a Lanczos three-term recurrence with the
tridiagonal least-squares problem solved by a running QR of Givens
rotations - monotonically nonincreasing residual, no positivity
assumption anywhere.

Implemented from the textbook recurrence in the framework's house
style: one jitted ``lax.while_loop``, scalars never leave the device,
inner products through ``blas1.dot`` so ``axis_name`` turns them into
``psum`` over a mesh (``solve_distributed(..., method="minres")``
works), ``check_every``-blocked convergence checks with identical
iterates, and the ``CGResult`` contract (residual history, typed
status, indefiniteness observation).

Scope: ``m=None`` (unpreconditioned; preconditioned MINRES requires an
SPD preconditioner and a different inner product - route SPD problems
to CG variants instead), any ``LinearOperator``.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops import blas1
from .status import CGStatus


def minres(
    a,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    record_history: bool = False,
    axis_name=None,
    iter_cap=None,
    check_every: int = 1,
):
    """Solve the symmetric (possibly indefinite) system ``A x = b``.

    Arguments mirror ``solver.cg.cg`` (absolute-``tol`` reference
    semantics, quirk Q3; ``rtol`` relative option; traced ``iter_cap``;
    ``check_every``-blocked predicate with identical iterates).  The
    residual norm tracked is MINRES's recurrence residual ``phibar``
    (exact in exact arithmetic, standard in practice).

    Returns a ``CGResult``; ``indefinite`` reports whether a negative
    ``v . A v`` Rayleigh quotient was observed (the certificate that CG
    would not have been guaranteed here).
    """
    from .cg import CGResult, _as_operator, _threshold_sq
    from ..models.operators import LinearOperator

    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    if axis_name is None and a.shape[1] != b.shape[0]:
        raise ValueError(f"operator shape {a.shape} does not match rhs "
                         f"shape {b.shape}")
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    dot = partial(blas1.dot, axis_name=axis_name)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    dtype = b.dtype
    eps = jnp.asarray(jnp.finfo(dtype).tiny, dtype)

    if x0 is None:
        x = jnp.zeros_like(b)
        r0 = b                       # x0 = 0 fast path (CUDACG.cu:247-259)
    else:
        x = jnp.asarray(x0, dtype)
        r0 = b - a @ x
    beta1 = jnp.sqrt(dot(r0, r0))
    thresh_sq = _threshold_sq(tol, rtol, beta1, dtype)
    thresh = jnp.sqrt(thresh_sq)

    history = None
    if record_history:
        history = jnp.full((maxiter + 1,), jnp.nan, dtype).at[0].set(beta1)

    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    # Paige-Saunders state: two scaled Lanczos residuals (r1, r2), the
    # rotation pair (cs, sn), the bar quantities (dbar, phibar), the
    # last two update directions (w1, w2), and epsln one step delayed.
    state = dict(
        k=jnp.zeros((), jnp.int32), x=x,
        r1=r0, r2=r0, oldb=zero, beta=beta1,
        dbar=zero, epsln=zero, phibar=beta1,
        cs=-one, sn=zero,
        w=jnp.zeros_like(b), w2=jnp.zeros_like(b),
        indefinite=jnp.zeros((), jnp.bool_),
        history=history if record_history else jnp.zeros((0,), dtype),
    )

    def cond(s):
        # reference-semantics continue condition (solver.cg's cond):
        # unconverged (>= keeps the exact tie iterating), nontrivial,
        # healthy, within the caps.  beta == 0 means the Krylov space
        # is exhausted - the solution is exact in it; stop.
        return ((s["k"] < maxiter) & (s["k"] < cap)
                & (s["phibar"] >= thresh) & (s["phibar"] > 0)
                & jnp.isfinite(s["phibar"]) & (s["beta"] > 0))

    def step(s):
        k = s["k"]
        beta, oldb = s["beta"], s["oldb"]
        beta_safe = jnp.where(beta == 0, one, beta)
        v = s["r2"] / beta_safe
        y = a @ v
        # y -= (beta/oldb) * r1  == beta_k * v_{k-1}; absent at k = 0
        factor = jnp.where(k > 0, beta / jnp.where(oldb == 0, one, oldb),
                           zero)
        y = y - factor * s["r1"]
        alfa = dot(v, y)
        indefinite = s["indefinite"] | (alfa < 0)
        y = y - (alfa / beta_safe) * s["r2"]
        r1, r2 = s["r2"], y
        oldb_n = beta
        beta_n = jnp.sqrt(dot(y, y))
        # previous rotations applied to the new tridiagonal column,
        # then the new rotation annihilating beta_{k+1}
        oldeps = s["epsln"]
        delta = s["cs"] * s["dbar"] + s["sn"] * alfa
        gbar = s["sn"] * s["dbar"] - s["cs"] * alfa
        epsln = s["sn"] * beta_n
        dbar = -s["cs"] * beta_n
        gamma = jnp.maximum(jnp.sqrt(gbar * gbar + beta_n * beta_n), eps)
        cs = gbar / gamma
        sn = beta_n / gamma
        phi = cs * s["phibar"]
        phibar = sn * s["phibar"]
        # direction update and solution step
        w1, w2 = s["w2"], s["w"]
        w = (v - oldeps * w1 - delta * w2) / gamma
        x = s["x"] + phi * w
        k = k + 1
        history = s["history"]
        if record_history:
            history = history.at[k].set(phibar)
        return dict(k=k, x=x, r1=r1, r2=r2, oldb=oldb_n, beta=beta_n,
                    dbar=dbar, epsln=epsln, phibar=phibar, cs=cs, sn=sn,
                    w=w, w2=w2, indefinite=indefinite, history=history)

    from .cg import _blocked_while

    def fits(s):
        return (s["k"] + check_every <= maxiter) \
            & (s["k"] + check_every <= cap)

    final = _blocked_while(cond, step, state, check_every, fits)

    phibar = final["phibar"]
    healthy = jnp.isfinite(phibar)
    converged = (phibar < thresh) | (phibar == 0)
    # Krylov exhaustion (beta == 0) always collapses phibar to 0
    # through the final rotation (sn = beta/gamma = 0), so it reports
    # CONVERGED with the subspace's least-squares solution - exact for
    # consistent systems.  For SINGULAR-inconsistent systems (b with a
    # null-space component) this is the textbook-MINRES limitation:
    # phibar tracks the recurrence residual, not ||b - A x||; callers
    # solving possibly-inconsistent systems should check the true
    # residual of the returned x (scipy's minres carries the same
    # caveat behind extra stopping tests).
    status = jnp.where(
        converged, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return CGResult(
        x=final["x"], iterations=final["k"], residual_norm=phibar,
        converged=converged, status=status,
        indefinite=final["indefinite"],
        residual_history=final["history"] if record_history else None)


# -- df64 (double-float) MINRES ------------------------------------------------


def minres_df64(
    a,
    b,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    record_history: bool = False,
    axis_name=None,
    iter_cap=None,
    check_every: int = 1,
):
    """f64-class MINRES on (hi, lo) double-float pairs.

    The reference's defining precision (``CUDA_R_64F``,
    ``CUDACG.cu:216``) x the principled algorithm for its indefinite
    matrix class (quirk Q1): the same Paige-Saunders recurrence as
    :func:`minres` with every vector, inner product and Givens scalar
    in df64 arithmetic (``ops.df64``; f64-class significand on hardware
    with no f64 units).  Operator/rhs coercion, distribution and result
    contract mirror ``solver.df64.cg_df64`` (``DF64CGResult``; history
    is the hi-word diagnostic trace).
    """
    from ..ops import df64 as df
    from .cg import _blocked_while
    from .df64 import DF64CGResult, _coerce_rhs_df, _prepare_operator

    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    # An operator already exposing matvec_df (ShiftELLDF64Matrix, or a
    # mesh-local DistStencilDF64 inside shard_map) is used directly -
    # _prepare_operator handles the host types that need lifting.
    op = a if hasattr(a, "matvec_df") else _prepare_operator(a)
    mv = op.matvec_df if hasattr(op, "matvec_df") else op.matvec
    b_df = _coerce_rhs_df(b)

    def ddot(x, y):
        return df.dot(x, y, axis_name=axis_name)

    zero = df.const(0.0)
    one = df.const(1.0)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                      jnp.int32)
    # the df64 analogue of the f32 kernel's gamma floor
    eps = df.const(float(jnp.finfo(jnp.float32).tiny))

    def dmax(p, q):
        keep_q = df.less(p, q)
        return (jnp.where(keep_q, q[0], p[0]),
                jnp.where(keep_q, q[1], p[1]))

    def dwhere(c, p, q):
        return (jnp.where(c, p[0], q[0]), jnp.where(c, p[1], q[1]))

    x0 = (jnp.zeros_like(b_df[0]), jnp.zeros_like(b_df[1]))
    r0 = b_df                       # x0 = 0 fast path (CUDACG.cu:247-259)
    beta1 = df.sqrt(ddot(r0, r0))
    thresh = dmax(df.const(float(tol)), df.mul(df.const(float(rtol)),
                                               beta1))

    history = jnp.zeros((0,), jnp.float32)
    if record_history:
        history = jnp.full((maxiter + 1,), jnp.nan,
                           jnp.float32).at[0].set(beta1[0])

    state = dict(
        k=jnp.zeros((), jnp.int32), x=x0,
        r1=r0, r2=r0, oldb=zero, beta=beta1,
        dbar=zero, epsln=zero, phibar=beta1,
        cs=df.neg(one), sn=zero,
        w=x0, w2=x0,
        indefinite=jnp.zeros((), jnp.bool_),
        history=history,
    )

    def cond(s):
        unconverged = jnp.logical_not(df.less(s["phibar"], thresh))
        nontrivial = s["phibar"][0] > 0
        return ((s["k"] < maxiter) & (s["k"] < cap) & unconverged
                & nontrivial & jnp.isfinite(s["phibar"][0])
                & (s["beta"][0] > 0))

    def smul(c, v):
        """df64 scalar * df64 vector (broadcast)."""
        return df.mul((jnp.broadcast_to(c[0], v[0].shape),
                       jnp.broadcast_to(c[1], v[0].shape)), v)

    def step(s):
        k = s["k"]
        beta, oldb = s["beta"], s["oldb"]
        beta_safe = dwhere(beta[0] == 0, one, beta)
        v = smul(df.div(one, beta_safe), s["r2"])   # v = r2 / beta
        y = mv(v)
        oldb_safe = dwhere(oldb[0] == 0, one, oldb)
        factor = dwhere(k > 0, df.div(beta, oldb_safe), zero)
        y = df.sub(y, smul(factor, s["r1"]))
        alfa = ddot(v, y)
        indefinite = s["indefinite"] | (alfa[0] < 0)
        y = df.sub(y, smul(df.div(alfa, beta_safe), s["r2"]))
        r1, r2 = s["r2"], y
        oldb_n = beta
        beta_n = df.sqrt(ddot(y, y))
        oldeps = s["epsln"]
        delta = df.add(df.mul(s["cs"], s["dbar"]), df.mul(s["sn"], alfa))
        gbar = df.sub(df.mul(s["sn"], s["dbar"]), df.mul(s["cs"], alfa))
        epsln = df.mul(s["sn"], beta_n)
        dbar = df.neg(df.mul(s["cs"], beta_n))
        gamma = df.sqrt(df.add(df.mul(gbar, gbar),
                               df.mul(beta_n, beta_n)))
        gamma = dmax(gamma, eps)
        cs = df.div(gbar, gamma)
        sn = df.div(beta_n, gamma)
        phi = df.mul(cs, s["phibar"])
        phibar = df.mul(sn, s["phibar"])
        w1, w2 = s["w2"], s["w"]
        num = df.sub(df.sub(v, smul(oldeps, w1)), smul(delta, w2))
        w = smul(df.div(one, gamma), num)
        x = df.add(s["x"], smul(phi, w))
        k = k + 1
        history = s["history"]
        if record_history:
            history = history.at[k].set(phibar[0])
        return dict(k=k, x=x, r1=r1, r2=r2, oldb=oldb_n, beta=beta_n,
                    dbar=dbar, epsln=epsln, phibar=phibar, cs=cs, sn=sn,
                    w=w, w2=w2, indefinite=indefinite, history=history)

    def fits(s):
        return (s["k"] + check_every <= maxiter) \
            & (s["k"] + check_every <= cap)

    final = _blocked_while(cond, step, state, check_every, fits)

    phibar = final["phibar"]
    healthy = jnp.isfinite(phibar[0])
    converged = df.less(phibar, thresh) | (phibar[0] == 0)
    status = jnp.where(
        converged, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    rr = df.mul(phibar, phibar)
    return DF64CGResult(
        x_hi=final["x"][0], x_lo=final["x"][1], iterations=final["k"],
        residual_norm_sq_hi=rr[0], residual_norm_sq_lo=rr[1],
        converged=converged, status=status,
        indefinite=final["indefinite"],
        residual_history=final["history"] if record_history else None)
