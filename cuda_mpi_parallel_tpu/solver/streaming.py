"""Solver-level entry for the fused-iteration HBM-streaming CG engine.

``cg_streaming`` runs each CG iteration as TWO pallas slab-streaming
launches (``ops/pallas/fused_cg.py``) inside one jitted
``lax.while_loop`` - the VMEM-resident engine's fuse-everything idea
carried past the VMEM boundary to the 256^3 north star (BASELINE
config #4), where the general solver's XLA fusion boundaries cost ~16
HBM plane-passes per iteration and the fused passes need 8.

Semantics mirror ``solver.cg`` (x0 = 0 fast path or general
``r0 = b - A x0``, absolute-``tol`` quirk-Q3 convergence plus ``rtol``,
``check_every`` blocked predicate via the SAME ``_blocked_while``,
``_safe_div`` breakdown freezing, CGStatus reporting, optional
per-iteration residual history); iterates agree with the general solver
to f32 reduction-order rounding (the two inner products accumulate
slab-by-slab in grid order), with iteration counts matching at equal
tolerances - asserted in ``tests/test_streaming.py``.

Scope: matrix-free 5/7-point f32 stencils of any slab-supported size,
``m=None``, ``method="cg"``.  Everything else stays on ``solver.cg``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..models.operators import Stencil2D, Stencil3D
from ..ops.pallas.fused_cg import (
    fused_cg_pass_a,
    fused_cg_pass_b,
    pick_block_streaming,
    supports_streaming,
)
from .cg import (
    CGResult,
    _blocked_while,
    _history_init,
    _safe_div,
    _threshold_sq,
)
from .status import CGStatus


def supports_streaming_op(a) -> bool:
    """True if ``cg_streaming`` can run this operator: an f32
    ``Stencil2D``/``Stencil3D`` whose grid satisfies the fused-CG
    kernels' DMA tiling (``fused_cg.supports_streaming``)."""
    if not isinstance(a, (Stencil2D, Stencil3D)):
        return False
    if a.dtype != jnp.float32:
        return False
    return supports_streaming(a.grid)


def streaming_eligible(a, b=None, m=None, *, method: str = "cg",
                       x0=None, resume_from=None,
                       return_checkpoint: bool = False,
                       compensated: bool = False,
                       record_history: bool = False) -> bool:
    """Eligibility for ``solve(engine="streaming")`` / the CLI - one
    predicate, same contract as ``resident_eligible``.  History IS
    supported (per-iteration, same granularity as the general solver).
    """
    del record_history  # supported at full granularity
    if m is not None or method != "cg":
        return False
    if resume_from is not None or return_checkpoint or compensated:
        return False
    if not supports_streaming_op(a):
        return False
    if x0 is not None and jnp.asarray(x0).dtype != jnp.float32:
        return False
    if b is not None and jnp.asarray(b).dtype != jnp.float32:
        return False
    return True


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "bm", "record_history",
    "interpret"))
def _cg_streaming_call(scale, b_grid, x0_grid, tol, rtol, cap, *, shape,
                       maxiter, check_every, bm, record_history,
                       interpret):
    ndim = len(shape)

    def stencil(u):
        # init-only matvec (r0 = b - A x0); the hot loop's stencils live
        # inside the fused passes
        from ..ops.pallas.stencil import stencil2d_apply, stencil3d_apply

        fn = stencil2d_apply if ndim == 2 else stencil3d_apply
        return fn(u, scale, bm=bm, interpret=interpret)

    if x0_grid is None:
        x = jnp.zeros(shape, jnp.float32)     # explicit x0 = 0 (quirk Q6)
        r = b_grid                            # r0 = b (CUDACG.cu:248)
    else:
        x = x0_grid
        r = b_grid - stencil(x0_grid)
    rr0 = jnp.vdot(r, r)
    nrm0 = jnp.sqrt(rr0)
    thresh_sq = _threshold_sq(tol, rtol, nrm0, jnp.float32)
    history = _history_init(record_history, maxiter, jnp.float32,
                            jnp.zeros((), jnp.int32), nrm0)

    # state: (k, x, r, p_prev, beta_prev, rho, indefinite, history)
    # The p-update is deferred into pass A of the NEXT iteration
    # (p_k = r_k + beta_{k-1} p_{k-1}), so the carry holds the previous
    # direction and its beta; iteration 0 seeds p_0 = r_0 via
    # beta_prev = 0 against a zero p_prev.
    state = (jnp.zeros((), jnp.int32), x, r, jnp.zeros(shape, jnp.float32),
             jnp.zeros((), jnp.float32), rr0, jnp.zeros((), jnp.bool_),
             history)

    def cond(s):
        k, _, _, _, _, rho, _, _ = s
        unconverged = rho >= thresh_sq
        nontrivial = rho > 0
        healthy = jnp.isfinite(rho)
        return (k < maxiter) & (k < cap) & unconverged & nontrivial \
            & healthy

    def step(s):
        k, x, r, p_prev, beta_prev, rho, indef, hist = s
        p, pap = fused_cg_pass_a(scale, beta_prev, r, p_prev, bm=bm,
                                 interpret=interpret)
        indef = indef | ((pap <= 0) & (rho > 0))     # quirk Q1 tracking
        alpha = _safe_div(rho, pap)                  # CUDACG.cu:311
        x, r, rr = fused_cg_pass_b(scale, alpha, p, x, r, bm=bm,
                                   interpret=interpret)
        beta = _safe_div(rr, rho)                    # CUDACG.cu:336-339
        k = k + 1
        if record_history:
            hist = hist.at[k].set(jnp.sqrt(rr))
        return (k, x, r, p, beta, rr, indef, hist)

    state = _blocked_while(
        cond, step, state, check_every,
        lambda s: (s[0] + check_every <= maxiter)
        & (s[0] + check_every <= cap))
    k, x, r, _, _, rho, indef, hist = state
    healthy = jnp.isfinite(rho)
    converged = (rho < thresh_sq) | (rho == 0)
    status = jnp.where(
        converged, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return (x, k, jnp.sqrt(rho), converged, status, indef,
            hist if record_history else None)


def cg_streaming(
    a,
    b: jax.Array,
    x0=None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 1,
    iter_cap=None,
    record_history: bool = False,
    interpret: bool = False,
) -> CGResult:
    """Solve ``A x = b`` with the fused-iteration HBM-streaming engine.

    Arguments mirror ``solver.cg`` (absolute-``tol`` reference
    semantics, ``rtol``, traced ``iter_cap``, ``check_every`` blocked
    convergence checks with IDENTICAL iterates, per-iteration
    ``record_history``).  ``a`` must be an f32 ``Stencil2D``/``Stencil3D``
    satisfying ``supports_streaming_op``; unlike the resident engine
    there is no VMEM capacity ceiling - this is the engine for grids
    too large to pin (256^3 and beyond).

    Returns a ``CGResult``.  The default ``check_every=1`` matches
    ``solve()`` (round-4 advice: the old default of 32 made direct
    calls overshoot to block boundaries while the docstring promised
    count parity): iteration counts match the general solver's exactly
    at equal tolerances AND equal ``check_every``.  Unlike the resident
    engine the per-iteration check costs no extra HBM traffic (the
    scalars live in the while_loop carry), but ``check_every=32`` still
    drops the per-trip predicate serialization - use it for throughput
    runs, as ``bench.py`` does.
    """
    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_streaming needs a Stencil2D or Stencil3D operator, got "
            f"{type(a).__name__} - use solver.cg for general operators")
    if a.dtype != jnp.float32:
        raise ValueError(
            f"cg_streaming is float32-only (got {a.dtype}); other dtypes "
            "route through solver.cg / solver.df64")
    grid = a.grid
    if not supports_streaming(grid):
        raise ValueError(
            f"grid {grid} does not satisfy the fused-CG slab tiling "
            f"(2D: nx % 8 == 0, ny % 128 == 0; 3D: nx % 2 == 0, "
            f"ny % 8 == 0, nz % 128 == 0)")
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    n_cells = math.prod(grid)
    b = jnp.asarray(b)
    flat_in = b.ndim == 1
    if flat_in:
        if b.shape[0] != n_cells:
            raise ValueError(f"rhs length {b.shape[0]} != grid {grid}")
        b_grid = b.reshape(grid)
    else:
        if b.shape != grid:
            raise ValueError(f"rhs shape {b.shape} != grid {grid}")
        b_grid = b
    if b_grid.dtype != jnp.float32:
        raise ValueError(
            f"cg_streaming is float32-only, got rhs {b_grid.dtype}")
    if x0 is not None:
        x0 = jnp.asarray(x0)
        if x0.dtype != jnp.float32:
            raise ValueError(f"x0 must be float32, got {x0.dtype}")
        x0 = x0.reshape(grid) if x0.ndim == 1 else x0
        if x0.shape != grid:
            raise ValueError(f"x0 shape {x0.shape} != grid {grid}")
    bm = pick_block_streaming(grid)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    x, k, nrm, converged, status, indef, hist = _cg_streaming_call(
        a.scale, b_grid, x0, jnp.asarray(tol, jnp.float32),
        jnp.asarray(rtol, jnp.float32), cap, shape=grid, maxiter=maxiter,
        check_every=min(check_every, max(maxiter, 1)), bm=bm,
        record_history=record_history, interpret=interpret)
    return CGResult(
        x=x.reshape(-1) if flat_in else x,
        iterations=k, residual_norm=nrm,
        converged=converged.astype(bool), status=status,
        indefinite=indef.astype(bool),
        residual_history=hist)


# -- df64 (double-float) streaming solver --------------------------------------


def supports_streaming_df64(a) -> bool:
    """True if ``cg_streaming_df64`` can run this operator: an
    ``Stencil2D``/``Stencil3D`` (any stored dtype - the solve re-splits
    the scale from host f64) whose grid satisfies the fused-CG slab
    tiling."""
    if not isinstance(a, (Stencil2D, Stencil3D)):
        return False
    return supports_streaming(a.grid)


def cg_streaming_df64(
    a,
    b,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 1,
    iter_cap=None,
    interpret: bool = False,
):
    """f64-class fused-iteration streaming CG (df64 storage).

    The reference's defining precision (``CUDA_R_64F``,
    ``CUDACG.cu:216``) at the north-star scale: the same two-pass fused
    iteration as :func:`cg_streaming` with every plane an (hi, lo) pair
    and every product/accumulation in error-free transforms
    (``ops/pallas/fused_cg.fused_cg_pass_{a,b}_df64``) - 16 HBM
    plane-passes per iteration vs the general df64 solver's ~32.
    Arguments and the rhs coercion mirror ``solver.df64.cg_df64``
    (threshold ``max(tol^2, rtol^2 ||r0||^2)`` evaluated in df64);
    returns a ``DF64CGResult``.
    """
    import numpy as np

    from ..ops import df64 as df
    from ..ops.pallas.fused_cg import (
        fused_cg_pass_a_df64,
        fused_cg_pass_b_df64,
    )
    from ..ops.pallas.resident import _safe_div_df
    from .df64 import DF64CGResult, _coerce_rhs_df

    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_streaming_df64 needs a Stencil2D or Stencil3D operator, "
            f"got {type(a).__name__} - use solver.df64.cg_df64 for "
            f"general operators")
    grid = a.grid
    if not supports_streaming(grid):
        raise ValueError(
            f"grid {grid} does not satisfy the fused-CG slab tiling "
            f"(2D: nx % 8 == 0, ny % 128 == 0; 3D: nx % 2 == 0, "
            f"ny % 8 == 0, nz % 128 == 0)")
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    n_cells = math.prod(grid)
    b_df = _coerce_rhs_df(b)
    if b_df[0].ndim == 1:
        if b_df[0].shape[0] != n_cells:
            raise ValueError(
                f"rhs length {b_df[0].shape[0]} != grid {grid}")
        b_df = (b_df[0].reshape(grid), b_df[1].reshape(grid))
    elif b_df[0].shape != grid:
        raise ValueError(f"rhs shape {b_df[0].shape} != grid {grid}")
    # re-split the scale from host f64 (solver.df64._prepare_operator)
    scale64 = np.float64(np.asarray(a.scale, dtype=np.float64))
    sh, sl = df.split_f64(scale64)
    scale = (jnp.asarray(sh), jnp.asarray(sl))
    bm = pick_block_streaming(grid)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                      jnp.int32)
    tol2 = df.const(float(tol) ** 2)
    rtol2 = df.const(float(rtol) ** 2)

    xh, xl, iters, rr_pair, indef, conv, health = _cg_streaming_df64_call(
        scale, b_df, tol2, rtol2, cap, shape=grid, maxiter=maxiter,
        check_every=min(check_every, max(maxiter, 1)), bm=bm,
        interpret=interpret, safe_div=_safe_div_df,
        pass_a=fused_cg_pass_a_df64, pass_b=fused_cg_pass_b_df64)
    status = jnp.where(
        conv, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~health, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return DF64CGResult(
        x_hi=xh.reshape(-1), x_lo=xl.reshape(-1), iterations=iters,
        residual_norm_sq_hi=rr_pair[0], residual_norm_sq_lo=rr_pair[1],
        converged=conv, status=status, indefinite=indef,
        residual_history=None)


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "bm", "interpret", "safe_div",
    "pass_a", "pass_b"))
def _cg_streaming_df64_call(scale, b_df, tol2, rtol2, cap, *, shape,
                            maxiter, check_every, bm, interpret,
                            safe_div, pass_a, pass_b):
    from ..ops import df64 as df
    from .df64 import _threshold

    x = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    r = b_df                              # x0 = 0 (CUDACG.cu:248)
    # df.dot folds flat vectors to a scalar pair (grid shapes would
    # leave a lane axis); init-only, so the reshape is free
    rr0 = df.dot((r[0].reshape(-1), r[1].reshape(-1)),
                 (r[0].reshape(-1), r[1].reshape(-1)))
    thr = _threshold(tol2, rtol2, rr0)
    zerop = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    zeros = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))

    state = (jnp.zeros((), jnp.int32), x, r, zerop, zeros, rr0,
             jnp.zeros((), jnp.bool_))

    def cond(s):
        k, _, _, _, _, rho, _ = s
        unconverged = jnp.logical_not(df.less(rho, thr))
        return (k < maxiter) & (k < cap) & unconverged & (rho[0] > 0) \
            & jnp.isfinite(rho[0])

    def step(s):
        k, x, r, p_prev, beta_prev, rho, indef = s
        p, pap = pass_a(scale, beta_prev, r, p_prev, bm=bm,
                        interpret=interpret)
        indef = indef | ((pap[0] <= 0) & (rho[0] > 0))
        alpha = safe_div(rho, pap)
        x, r, rr = pass_b(scale, alpha, p, x, r, bm=bm,
                          interpret=interpret)
        beta = safe_div(rr, rho)
        return (k + 1, x, r, p, beta, rr, indef)

    state = _blocked_while(
        cond, step, state, check_every,
        lambda s: (s[0] + check_every <= maxiter)
        & (s[0] + check_every <= cap))
    k, x, r, _, _, rho, indef = state
    healthy = jnp.isfinite(rho[0])
    converged = df.less(rho, thr) | (rho[0] == 0)
    return (x[0], x[1], k, rho, indef, converged, healthy)
