"""Solver-level entry for the fused-iteration HBM-streaming CG engine.

``cg_streaming`` runs each CG iteration as TWO pallas slab-streaming
launches (``ops/pallas/fused_cg.py``) inside one jitted
``lax.while_loop`` - the VMEM-resident engine's fuse-everything idea
carried past the VMEM boundary to the 256^3 north star (BASELINE
config #4), where the general solver's XLA fusion boundaries cost ~16
HBM plane-passes per iteration and the fused passes need 8.

Semantics mirror ``solver.cg`` (x0 = 0 fast path or general
``r0 = b - A x0``, absolute-``tol`` quirk-Q3 convergence plus ``rtol``,
``check_every`` blocked predicate via the SAME ``_blocked_while``,
``_safe_div`` breakdown freezing, CGStatus reporting, optional
per-iteration residual history); iterates agree with the general solver
to f32 reduction-order rounding (the two inner products accumulate
slab-by-slab in grid order), with iteration counts matching at equal
tolerances - asserted in ``tests/test_streaming.py``.

Scope: matrix-free 5/7-point f32 stencils of any slab-supported size,
``m=None``, ``method="cg"``.  Everything else stays on ``solver.cg``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..models.operators import Stencil2D, Stencil3D
from ..ops.pallas.fused_cg import (
    fused_cg_pass_a,
    fused_cg_pass_b,
    fused_cheb_step,
    pick_block_streaming,
    supports_streaming,
)
from .cg import (
    CGResult,
    _blocked_while,
    _history_init,
    _safe_div,
    _threshold_sq,
)
from .status import CGStatus


def supports_streaming_op(a) -> bool:
    """True if ``cg_streaming`` can run this operator: an f32
    ``Stencil2D``/``Stencil3D`` whose grid satisfies the fused-CG
    kernels' DMA tiling (``fused_cg.supports_streaming``)."""
    if not isinstance(a, (Stencil2D, Stencil3D)):
        return False
    if a.dtype != jnp.float32:
        return False
    return supports_streaming(a.grid)


def streaming_eligible(a, b=None, m=None, *, method: str = "cg",
                       x0=None, resume_from=None,
                       return_checkpoint: bool = False,
                       compensated: bool = False,
                       record_history: bool = False) -> bool:
    """Eligibility for ``solve(engine="streaming")`` / the CLI - one
    predicate, same contract as ``resident_eligible``.  History IS
    supported (per-iteration, same granularity as the general solver).
    ``m`` may be ``None`` or a ``ChebyshevPreconditioner`` verifiably
    built over ``a`` (same contract as the resident engine: the fused
    cheb steps apply THIS operator's stencil, so a foreign interval
    would silently precondition with the wrong polynomial).
    """
    del record_history  # supported at full granularity
    if m is not None:
        from ..models.precond import ChebyshevPreconditioner
        from .resident import _chebyshev_match_status

        if not isinstance(m, ChebyshevPreconditioner):
            return False
        if not isinstance(a, (Stencil2D, Stencil3D)):
            return False
        if _chebyshev_match_status(a, m) != "match":
            return False
    if method != "cg":
        return False
    if resume_from is not None or return_checkpoint or compensated:
        return False
    if not supports_streaming_op(a):
        return False
    if x0 is not None and jnp.asarray(x0).dtype != jnp.float32:
        return False
    if b is not None and jnp.asarray(b).dtype != jnp.float32:
        return False
    return True


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "bm", "record_history",
    "interpret", "degree", "flight"))
def _cg_streaming_call(scale, b_grid, x0_grid, tol, rtol, cap, lmin, lmax,
                       *, shape, maxiter, check_every, bm, record_history,
                       interpret, degree, flight=None):
    ndim = len(shape)
    preconditioned = degree > 0

    def stencil(u):
        # init-only matvec (r0 = b - A x0); the hot loop's stencils live
        # inside the fused passes
        from ..ops.pallas.stencil import stencil2d_apply, stencil3d_apply

        fn = stencil2d_apply if ndim == 2 else stencil3d_apply
        return fn(u, scale, bm=bm, interpret=interpret)

    # Chebyshev interval scalars (models.precond.ChebyshevPreconditioner
    # .matvec): traced, so lmin/lmax sweeps reuse the executable.
    theta = (lmax + lmin) / 2 if preconditioned else None
    if degree >= 2:
        delta = (lmax - lmin) / 2
        sigma = theta / delta

        def cheb_apply(r_grid):
            """z = P(A) r via (degree - 1) fused slab-streamed steps;
            the last step also accumulates rho = r . z (slab order)."""
            rho_c = 1.0 / sigma
            z = d = None
            rz = None
            for j in range(degree - 1):
                rho_new = 1.0 / (2.0 * sigma - rho_c)
                c1 = rho_new * rho_c
                c2 = 2.0 * rho_new / delta
                first = j == 0
                out = fused_cheb_step(
                    scale, theta, c1, c2, r_grid if first else z,
                    None if first else r_grid, None if first else d,
                    bm=bm, first=first, last=j == degree - 2,
                    interpret=interpret)
                z, d = out[0], out[1]
                if j == degree - 2:
                    rz = out[2]
                rho_c = rho_new
            return z, rz

    if x0_grid is None:
        x = jnp.zeros(shape, jnp.float32)     # explicit x0 = 0 (quirk Q6)
        r = b_grid                            # r0 = b (CUDACG.cu:248)
    else:
        x = x0_grid
        r = b_grid - stencil(x0_grid)
    rr0 = jnp.vdot(r, r)
    nrm0 = jnp.sqrt(rr0)
    thresh_sq = _threshold_sq(tol, rtol, nrm0, jnp.float32)
    history = _history_init(record_history, maxiter, jnp.float32,
                            jnp.zeros((), jnp.int32), nrm0)

    if degree >= 2:
        z0, rho0 = cheb_apply(r)
    elif degree == 1:
        # z = r/theta: the polynomial folds into the passes (pass A
        # divides by theta, pass B accumulates rho); init in plain XLA
        z0, rho0 = None, jnp.vdot(r, r / theta)
    else:
        z0, rho0 = None, rr0

    # state: (k, x, r, [z,] p_prev, beta_prev, rho, rr, indefinite,
    # history).  The p-update is deferred into pass A of the NEXT
    # iteration (p_k = z_k + beta_{k-1} p_{k-1}), so the carry holds the
    # previous direction and its beta; iteration 0 seeds p_0 = z_0 via
    # beta_prev = 0 against a zero p_prev.  z rides the carry only for
    # degree >= 2 (separate cheb launches); degree 1 derives it in-pass.
    zs = (z0,) if degree >= 2 else ()
    state = (jnp.zeros((), jnp.int32), x, r, *zs,
             jnp.zeros(shape, jnp.float32),
             jnp.zeros((), jnp.float32), rho0, rr0,
             jnp.zeros((), jnp.bool_), history)
    nz = len(zs)

    def cond(s):
        # layout: k(0) x(1) r(2) [z(3)] p_prev beta_prev rho rr indef hist
        k, rho, rr = s[0], s[5 + nz], s[6 + nz]
        unconverged = rr >= thresh_sq
        nontrivial = rr > 0
        # rho = r . M^-1 r <= 0 with r != 0 is a preconditioner
        # breakdown (solver.cg's health predicate); unpreconditioned
        # rho == rr so the extra terms are free
        healthy = jnp.isfinite(rr) & jnp.isfinite(rho) & (rho > 0)
        return (k < maxiter) & (k < cap) & unconverged & nontrivial \
            & healthy

    def step_ab(s):
        if degree >= 2:
            k, x, r, z, p_prev, beta_prev, rho, rr, indef, hist = s
            v = z
        else:
            k, x, r, p_prev, beta_prev, rho, rr, indef, hist = s
            v = r
        p, pap = fused_cg_pass_a(scale, beta_prev, v, p_prev, bm=bm,
                                 interpret=interpret,
                                 theta=theta if degree == 1 else None)
        indef = indef | ((pap <= 0) & (rr > 0))      # quirk Q1 tracking
        alpha = _safe_div(rho, pap)                  # CUDACG.cu:311
        if degree == 1:
            x, r, rr, rho_new = fused_cg_pass_b(
                scale, alpha, p, x, r, bm=bm, interpret=interpret,
                theta=theta, with_rz=True)
        else:
            x, r, rr = fused_cg_pass_b(scale, alpha, p, x, r, bm=bm,
                                       interpret=interpret)
            if degree >= 2:
                z, rho_new = cheb_apply(r)
            else:
                rho_new = rr
        beta = _safe_div(rho_new, rho)               # CUDACG.cu:336-339
        k = k + 1
        if record_history:
            hist = hist.at[k].set(jnp.sqrt(rr))
        if degree >= 2:
            out = (k, x, r, z, p, beta, rho_new, rr, indef, hist)
        else:
            out = (k, x, r, p, beta, rho_new, rr, indef, hist)
        return out, k, rr, alpha, beta

    def step(s):
        return step_ab(s)[0]

    def fits(s):
        return (s[0] + check_every <= maxiter) \
            & (s[0] + check_every <= cap)

    if flight is None:
        state = _blocked_while(cond, step, state, check_every, fits)
        fbuf = None
    else:
        from .cg import _flight_while

        state, fbuf, _ = _flight_while(
            cond, step_ab, state, check_every, fits, flight,
            dtype=jnp.float32, k0=jnp.zeros((), jnp.int32), rr0=rr0)
    k, x = state[0], state[1]
    rho, rr, indef, hist = (state[5 + nz], state[6 + nz], state[7 + nz],
                            state[8 + nz])
    healthy = jnp.isfinite(rr) & jnp.isfinite(rho) \
        & ((rho > 0) | (rr == 0))
    converged = (rr < thresh_sq) | (rr == 0)
    status = jnp.where(
        converged, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return (x, k, jnp.sqrt(rr), converged, status, indef,
            hist if record_history else None, fbuf)


def cg_streaming(
    a,
    b: jax.Array,
    x0=None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 1,
    iter_cap=None,
    m=None,
    record_history: bool = False,
    flight=None,
    interpret: bool = False,
) -> CGResult:
    """Solve ``A x = b`` with the fused-iteration HBM-streaming engine.

    Arguments mirror ``solver.cg`` (absolute-``tol`` reference
    semantics, ``rtol``, traced ``iter_cap``, ``check_every`` blocked
    convergence checks with IDENTICAL iterates, per-iteration
    ``record_history``).  ``a`` must be an f32 ``Stencil2D``/``Stencil3D``
    satisfying ``supports_streaming_op``; unlike the resident engine
    there is no VMEM capacity ceiling - this is the engine for grids
    too large to pin (256^3 and beyond).

    ``m`` accepts ``None`` or a ``ChebyshevPreconditioner`` built over
    THIS operator (the resident engine's contract): the polynomial is
    applied by fused slab-streamed cheb steps following ``solver.cg``'s
    preconditioned recurrence.  Plane-pass cost per iteration on top of
    the unpreconditioned 8: degree 1 adds ZERO (z = r/theta folds into
    pass A's theta divisor and pass B's fused rho accumulation);
    degree k >= 2 adds 3 (first step: r halo-read + z/d writes) plus
    5 per additional step (z halo-read, r/d reads, z/d writes), with
    the PCG reduction rho = r . z fused into the last step - e.g.
    degree 4 runs 8 + 3 + 5 + 5 = 21 passes vs the general cheb-CG's
    ~16 + 3 * (k - 1) fusion-boundary passes plus its separate dot.

    Returns a ``CGResult``.  The default ``check_every=1`` matches
    ``solve()`` (round-4 advice: the old default of 32 made direct
    calls overshoot to block boundaries while the docstring promised
    count parity): iteration counts match the general solver's exactly
    at equal tolerances AND equal ``check_every``.  Unlike the resident
    engine the per-iteration check costs no extra HBM traffic (the
    scalars live in the while_loop carry), but ``check_every=32`` still
    drops the per-trip predicate serialization - use it for throughput
    runs, as ``bench.py`` does.

    ``flight``: optional ``telemetry.flight.FlightConfig`` - carry the
    per-iteration convergence flight recorder in the while_loop state
    (``solver.cg`` semantics: ``None`` leaves the traced solve
    bit-identical; the scalars recorded are the slab-accumulated
    global values the loop already holds).
    """
    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_streaming needs a Stencil2D or Stencil3D operator, got "
            f"{type(a).__name__} - use solver.cg for general operators")
    if a.dtype != jnp.float32:
        raise ValueError(
            f"cg_streaming is float32-only (got {a.dtype}); other dtypes "
            "route through solver.cg / solver.df64")
    grid = a.grid
    if not supports_streaming(grid):
        raise ValueError(
            f"grid {grid} does not satisfy the fused-CG slab tiling "
            f"(2D: nx % 8 == 0, ny % 128 == 0; 3D: nx % 2 == 0, "
            f"ny % 8 == 0, nz % 128 == 0)")
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    n_cells = math.prod(grid)
    b = jnp.asarray(b)
    flat_in = b.ndim == 1
    if flat_in:
        if b.shape[0] != n_cells:
            raise ValueError(f"rhs length {b.shape[0]} != grid {grid}")
        b_grid = b.reshape(grid)
    else:
        if b.shape != grid:
            raise ValueError(f"rhs shape {b.shape} != grid {grid}")
        b_grid = b
    if b_grid.dtype != jnp.float32:
        raise ValueError(
            f"cg_streaming is float32-only, got rhs {b_grid.dtype}")
    if x0 is not None:
        x0 = jnp.asarray(x0)
        if x0.dtype != jnp.float32:
            raise ValueError(f"x0 must be float32, got {x0.dtype}")
        x0 = x0.reshape(grid) if x0.ndim == 1 else x0
        if x0.shape != grid:
            raise ValueError(f"x0 shape {x0.shape} != grid {grid}")
    degree, lmin, lmax = 0, None, None
    if m is not None:
        from ..models.precond import ChebyshevPreconditioner
        from .resident import _chebyshev_match_status

        if not isinstance(m, ChebyshevPreconditioner):
            raise TypeError(
                f"cg_streaming supports m=None or a "
                f"ChebyshevPreconditioner (applied by fused streamed "
                f"steps), got {type(m).__name__} - use solver.cg for "
                f"other preconditioners")
        status = _chebyshev_match_status(a, m)
        if status == "unverifiable":
            raise ValueError(
                "under jit, build the ChebyshevPreconditioner over the "
                "SAME operator instance passed to cg_streaming (scale "
                "equality cannot be checked on traced values)")
        if status == "mismatch":
            raise ValueError(
                "the ChebyshevPreconditioner must be built over the "
                "same stencil operator being solved (same grid and "
                "same scale)")
        degree = int(m.degree)
        lmin = jnp.asarray(m.lmin, jnp.float32)
        lmax = jnp.asarray(m.lmax, jnp.float32)
    from .cg import _note_engine

    _note_engine("streaming", "cg", check_every,
                 **({"flight_stride": flight.stride}
                    if flight is not None else {}))
    bm = pick_block_streaming(grid)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    x, k, nrm, converged, status, indef, hist, fbuf = _cg_streaming_call(
        a.scale, b_grid, x0, jnp.asarray(tol, jnp.float32),
        jnp.asarray(rtol, jnp.float32), cap, lmin, lmax, shape=grid,
        maxiter=maxiter,
        check_every=min(check_every, max(maxiter, 1)), bm=bm,
        record_history=record_history, interpret=interpret,
        degree=degree, flight=flight)
    return CGResult(
        x=x.reshape(-1) if flat_in else x,
        iterations=k, residual_norm=nrm,
        converged=converged.astype(bool), status=status,
        indefinite=indef.astype(bool),
        residual_history=hist,
        flight=fbuf)


# -- df64 (double-float) streaming solver --------------------------------------


def supports_streaming_df64(a) -> bool:
    """True if ``cg_streaming_df64`` can run this operator: an
    ``Stencil2D``/``Stencil3D`` (any stored dtype - the solve re-splits
    the scale from host f64) whose grid satisfies the fused-CG slab
    tiling."""
    if not isinstance(a, (Stencil2D, Stencil3D)):
        return False
    return supports_streaming(a.grid, itemsize=8)


def cg_streaming_df64(
    a,
    b,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 1,
    iter_cap=None,
    interpret: bool = False,
):
    """f64-class fused-iteration streaming CG (df64 storage).

    The reference's defining precision (``CUDA_R_64F``,
    ``CUDACG.cu:216``) at the north-star scale: the same two-pass fused
    iteration as :func:`cg_streaming` with every plane an (hi, lo) pair
    and every product/accumulation in error-free transforms
    (``ops/pallas/fused_cg.fused_cg_pass_{a,b}_df64``) - 16 HBM
    plane-passes per iteration vs the general df64 solver's ~32.
    Arguments and the rhs coercion mirror ``solver.df64.cg_df64``
    (threshold ``max(tol^2, rtol^2 ||r0||^2)`` evaluated in df64);
    returns a ``DF64CGResult``.
    """
    import numpy as np

    from ..ops import df64 as df
    from ..ops.pallas.fused_cg import (
        fused_cg_pass_a_df64,
        fused_cg_pass_b_df64,
    )
    from ..ops.pallas.resident import _safe_div_df
    from .df64 import DF64CGResult, _coerce_rhs_df

    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_streaming_df64 needs a Stencil2D or Stencil3D operator, "
            f"got {type(a).__name__} - use solver.df64.cg_df64 for "
            f"general operators")
    grid = a.grid
    if not supports_streaming(grid, itemsize=8):
        raise ValueError(
            f"grid {grid} does not satisfy the fused-CG slab tiling "
            f"(2D: nx % 8 == 0, ny % 128 == 0; 3D: nx % 2 == 0, "
            f"ny % 8 == 0, nz % 128 == 0)")
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    n_cells = math.prod(grid)
    b_df = _coerce_rhs_df(b)
    if b_df[0].ndim == 1:
        if b_df[0].shape[0] != n_cells:
            raise ValueError(
                f"rhs length {b_df[0].shape[0]} != grid {grid}")
        b_df = (b_df[0].reshape(grid), b_df[1].reshape(grid))
    elif b_df[0].shape != grid:
        raise ValueError(f"rhs shape {b_df[0].shape} != grid {grid}")
    # re-split the scale from host f64 (solver.df64._prepare_operator)
    scale64 = np.float64(np.asarray(a.scale, dtype=np.float64))
    sh, sl = df.split_f64(scale64)
    scale = (jnp.asarray(sh), jnp.asarray(sl))
    # itemsize=8: every df64 plane is an (hi, lo) f32 pair, so the
    # kernels hold twice the slabs per block-height - round 5's bm=16
    # 3D picker OOM'd Mosaic's scoped VMEM when modeled at 4 bytes
    from .cg import _note_engine

    _note_engine("streaming-df64", "cg", check_every)
    bm = pick_block_streaming(grid, itemsize=8)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                      jnp.int32)
    tol2 = df.const(float(tol) ** 2)
    rtol2 = df.const(float(rtol) ** 2)

    xh, xl, iters, rr_pair, indef, conv, health = _cg_streaming_df64_call(
        scale, b_df, tol2, rtol2, cap, shape=grid, maxiter=maxiter,
        check_every=min(check_every, max(maxiter, 1)), bm=bm,
        interpret=interpret, safe_div=_safe_div_df,
        pass_a=fused_cg_pass_a_df64, pass_b=fused_cg_pass_b_df64)
    status = jnp.where(
        conv, jnp.int32(CGStatus.CONVERGED),
        jnp.where(~health, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)))
    return DF64CGResult(
        x_hi=xh.reshape(-1), x_lo=xl.reshape(-1), iterations=iters,
        residual_norm_sq_hi=rr_pair[0], residual_norm_sq_lo=rr_pair[1],
        converged=conv, status=status, indefinite=indef,
        residual_history=None)


@functools.partial(jax.jit, static_argnames=(
    "shape", "maxiter", "check_every", "bm", "interpret", "safe_div",
    "pass_a", "pass_b"))
def _cg_streaming_df64_call(scale, b_df, tol2, rtol2, cap, *, shape,
                            maxiter, check_every, bm, interpret,
                            safe_div, pass_a, pass_b):
    from ..ops import df64 as df
    from .df64 import _threshold

    x = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    r = b_df                              # x0 = 0 (CUDACG.cu:248)
    # df.dot folds flat vectors to a scalar pair (grid shapes would
    # leave a lane axis); init-only, so the reshape is free
    rr0 = df.dot((r[0].reshape(-1), r[1].reshape(-1)),
                 (r[0].reshape(-1), r[1].reshape(-1)))
    thr = _threshold(tol2, rtol2, rr0)
    zerop = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    zeros = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))

    state = (jnp.zeros((), jnp.int32), x, r, zerop, zeros, rr0,
             jnp.zeros((), jnp.bool_))

    def cond(s):
        k, _, _, _, _, rho, _ = s
        unconverged = jnp.logical_not(df.less(rho, thr))
        return (k < maxiter) & (k < cap) & unconverged & (rho[0] > 0) \
            & jnp.isfinite(rho[0])

    def step(s):
        k, x, r, p_prev, beta_prev, rho, indef = s
        p, pap = pass_a(scale, beta_prev, r, p_prev, bm=bm,
                        interpret=interpret)
        indef = indef | ((pap[0] <= 0) & (rho[0] > 0))
        alpha = safe_div(rho, pap)
        x, r, rr = pass_b(scale, alpha, p, x, r, bm=bm,
                          interpret=interpret)
        beta = safe_div(rr, rho)
        return (k + 1, x, r, p, beta, rr, indef)

    state = _blocked_while(
        cond, step, state, check_every,
        lambda s: (s[0] + check_every <= maxiter)
        & (s[0] + check_every <= cap))
    k, x, r, _, _, rho, indef = state
    healthy = jnp.isfinite(rho[0])
    converged = df.less(rho, thr) | (rho[0] == 0)
    return (x[0], x[1], k, rho, indef, converged, healthy)
