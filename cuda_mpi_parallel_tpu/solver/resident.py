"""Solver-level entry for the VMEM-resident single-kernel CG.

``cg_resident`` runs the entire solve as ONE pallas kernel with the CG
working set pinned in VMEM (``ops/pallas/resident.py``) and adapts the
kernel's raw outputs to the framework's ``CGResult`` contract.  Measured
on TPU v5e at 1024x1024 f32 (BASELINE config #2): 6.65 us/iteration -
2.9x the general ``lax.while_loop`` solver (whose fusion boundaries
cost ~4 HBM passes per iteration) and ~35x the derived estimate for the
reference's host-synchronous loop (``CUDACG.cu:269-352``).

Scope: matrix-free 2D 5-point stencils (``Stencil2D``), float32, x0 = 0,
unpreconditioned ``method="cg"``, no residual history.  Everything else
routes through ``solver.cg`` - the general path exists precisely so the
fast path can stay narrow.  Trajectory parity with the general solver is
exact in iteration counts (2688 == 2688 at 1M unknowns, tol 1e-4) with
iterates agreeing to f32 reduction-order rounding (~3e-6 relative).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..models.operators import Stencil2D
from ..ops.pallas.resident import cg_resident_2d, supports_resident_2d
from .cg import CGResult
from .status import CGStatus


def supports_resident(a, b=None, dtype=None) -> bool:
    """True if ``cg_resident`` can run this operator (see module scope)."""
    if not isinstance(a, Stencil2D):
        return False
    if a.dtype != jnp.float32:
        return False
    nx, ny = a.grid
    return supports_resident_2d(nx, ny, itemsize=4)


def cg_resident(
    a: Stencil2D,
    b: jax.Array,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 32,
    iter_cap=None,
    interpret: bool = False,
) -> CGResult:
    """Solve ``A x = b`` entirely inside one VMEM-resident pallas kernel.

    Arguments mirror ``solver.cg`` (absolute-``tol`` reference semantics,
    quirk Q3; ``rtol`` relative option; traced ``iter_cap``); ``x0`` is
    fixed at zero (the reference's init fast path, ``CUDACG.cu:247-259``)
    and preconditioners / residual history are unsupported - use
    ``solver.cg`` for those.  The reported iteration count is
    ``check_every``-block aligned, exactly like ``cg(check_every=k)``.

    Returns a ``CGResult`` (history ``None``).
    """
    if not isinstance(a, Stencil2D):
        raise TypeError(
            f"cg_resident needs a Stencil2D operator, got {type(a).__name__}"
            " - use solver.cg for general operators")
    nx, ny = a.grid
    b = jnp.asarray(b)
    flat_in = b.ndim == 1
    if flat_in:
        if b.shape[0] != nx * ny:
            raise ValueError(f"rhs length {b.shape[0]} != grid {nx}x{ny}")
        b2d = b.reshape(nx, ny)
    else:
        if b.shape != (nx, ny):
            raise ValueError(f"rhs shape {b.shape} != grid ({nx}, {ny})")
        b2d = b
    if b2d.dtype != jnp.float32:
        raise ValueError(
            f"cg_resident is float32-only (got {b2d.dtype}); df64/x64 "
            "precision routes through solver.cg / solver.df64")

    x2d, iters, rr, indef = cg_resident_2d(
        a.scale, b2d, tol=tol, rtol=rtol, maxiter=maxiter,
        check_every=check_every, iter_cap=iter_cap, interpret=interpret)

    res_norm = jnp.sqrt(rr)
    thresh = jnp.maximum(jnp.asarray(tol, jnp.float32),
                         jnp.asarray(rtol, jnp.float32)
                         * jnp.linalg.norm(b2d.reshape(-1)))
    converged = res_norm <= thresh
    healthy = jnp.isfinite(res_norm)
    status = jnp.where(
        ~healthy, jnp.int32(CGStatus.BREAKDOWN),
        jnp.where(converged, jnp.int32(CGStatus.CONVERGED),
                  jnp.int32(CGStatus.MAXITER)))
    x = x2d.reshape(-1) if flat_in else x2d
    return CGResult(
        x=x, iterations=iters, residual_norm=res_norm,
        converged=converged, status=status,
        indefinite=indef.astype(bool), residual_history=None)
