"""Solver-level entry for the VMEM-resident single-kernel CG.

``cg_resident`` runs the entire solve as ONE pallas kernel with the CG
working set pinned in VMEM (``ops/pallas/resident.py``) and adapts the
kernel's raw outputs to the framework's ``CGResult`` contract.  Measured
on TPU v5e at 1024x1024 f32 (BASELINE config #2): 6.65 us/iteration -
2.9x the general ``lax.while_loop`` solver (whose fusion boundaries
cost ~4 HBM passes per iteration) and ~35x the derived estimate for the
reference's host-synchronous loop (``CUDACG.cu:269-352``).

Scope: matrix-free 5/7-point stencils (``Stencil2D``/``Stencil3D``,
grids fitting VMEM), float32 (or df64 via ``cg_resident_df64``), x0 = 0,
``method="cg"``, ``m`` ``None`` or in-kernel Chebyshev, no residual
history.  Everything else routes through ``solver.cg`` - the general
path exists precisely so the fast path can stay narrow.  Trajectory
parity with the general solver is exact in iteration counts (2688 ==
2688 at 1M unknowns, tol 1e-4) with iterates agreeing to f32
reduction-order rounding (~3e-6 relative).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..models.operators import Stencil2D, Stencil3D
from ..ops import df64 as df
from ..ops.pallas.resident import (
    cg_resident_2d,
    cg_resident_3d,
    cg_resident_df64_2d,
    cg_resident_df64_3d,
    supports_resident_2d,
    supports_resident_3d,
    supports_resident_df64_2d,
    supports_resident_df64_3d,
)
from .cg import CGResult
from .df64 import DF64CGResult
from .status import CGStatus


def supports_resident(a, preconditioned: bool = False,
                      warm_start: bool = False,
                      cg1: bool = False) -> bool:
    """True if ``cg_resident`` can run this operator (see module scope).

    ``preconditioned`` budgets the in-kernel Chebyshev recurrence's
    extra planes (a MEASURED 6-plane surcharge - see ``_extra_planes``;
    13 planes total with the base bound); ``warm_start`` budgets the
    pinned x0 plane; ``cg1`` the single-reduction recurrence's s/w
    planes.
    """
    if isinstance(a, Stencil2D):
        if a.dtype != jnp.float32:
            return False
        nx, ny = a.grid
        return supports_resident_2d(nx, ny, itemsize=4,
                                    preconditioned=preconditioned,
                                    warm_start=warm_start, cg1=cg1)
    if isinstance(a, Stencil3D):
        if a.dtype != jnp.float32:
            return False
        nx, ny, nz = a.grid
        return supports_resident_3d(nx, ny, nz, itemsize=4,
                                    preconditioned=preconditioned,
                                    warm_start=warm_start, cg1=cg1)
    return False


def _chebyshev_match_status(a, m) -> str:
    """How ``m``'s operator relates to ``a`` (a 2D/3D stencil).

    The kernel pairs ``a``'s stencil with ``m``'s spectral interval, so
    they must describe the same matrix: same grid AND same scale.
    Returns ``"match"``, ``"mismatch"``, or ``"unverifiable"`` (traced
    scale that cannot be compared - eligibility decisions treat it as
    non-matching and fall back to the general solver; explicit
    ``cg_resident`` calls raise a specific error).  Call only after
    ``supports_resident(a)`` - the grid/scale attributes exist on
    stencil operators only.
    """
    if m.a is a:
        return "match"
    if not (isinstance(m.a, type(a)) and m.a.grid == a.grid):
        return "mismatch"
    try:
        return "match" if bool(jnp.all(m.a.scale == a.scale)) \
            else "mismatch"
    except jax.errors.TracerBoolConversionError:
        return "unverifiable"


def resident_eligible(a, b=None, m=None, *, method: str = "cg",
                      record_history: bool = False, x0=None,
                      resume_from=None, return_checkpoint: bool = False,
                      compensated: bool = False) -> bool:
    """Single source of truth for "can this solve run on the resident
    engine?" - shared by ``solve(engine=...)`` and the CLI so the two
    cannot drift.

    Checks the operator (f32 2D/3D stencil fitting VMEM, preconditioned
    budget included), the rhs dtype (f32 - the general path casts other
    dtypes, the kernel does not), the preconditioner (``None`` or a
    ``ChebyshevPreconditioner`` verifiably built over ``a``), and the
    feature set the one-kernel solve supports (``method="cg"``, f32
    ``x0`` or none, no checkpointing / compensated dots).

    ``record_history=True`` is NOT eligible here on purpose: the
    resident trace is check-block-granular while the general solver's
    is per-iteration, and ``engine="auto"`` must never silently change
    what a returned field means.  Callers who want the block-granular
    trace ask for it explicitly (``cg_resident(record_history=True)``
    or ``solve(engine="resident", record_history=True)``).
    """
    from ..models.precond import ChebyshevPreconditioner

    chebyshev = isinstance(m, ChebyshevPreconditioner)
    if m is not None and not chebyshev:
        return False
    if method not in ("cg", "cg1"):
        return False
    if method == "cg1" and m is not None:
        return False  # the in-kernel cg1 form is unpreconditioned
    # operator gate FIRST: _chebyshev_match_status reads grid/scale,
    # which only stencil operators have
    if not supports_resident(a, preconditioned=chebyshev,
                             warm_start=x0 is not None,
                             cg1=method == "cg1"):
        return False
    if chebyshev and _chebyshev_match_status(a, m) != "match":
        return False
    if (record_history
            or resume_from is not None or return_checkpoint
            or compensated):
        return False
    if x0 is not None and jnp.asarray(x0).dtype != jnp.float32:
        return False
    if b is not None and jnp.asarray(b).dtype != jnp.float32:
        return False
    return True


def cg_resident(
    a: Stencil2D,
    b: jax.Array,
    x0=None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 32,
    iter_cap=None,
    m=None,
    record_history: bool = False,
    method: str = "cg",
    interpret: bool = False,
) -> CGResult:
    """Solve ``A x = b`` entirely inside one VMEM-resident pallas kernel.

    Arguments mirror ``solver.cg`` (absolute-``tol`` reference semantics,
    quirk Q3; ``rtol`` relative option; traced ``iter_cap``).  ``x0``
    ``None`` takes the reference's copy-only init fast path
    (``CUDACG.cu:247-259``); a nonzero ``x0`` warm-starts with the
    general ``r0 = b - A x0`` init (one extra in-kernel stencil apply).

    ``record_history=True`` returns the kernel's residual trace at
    CHECK-BLOCK granularity (quirk Q7 closed on this engine): a
    ``(maxiter + 1,)`` array holding ``||r||`` at index 0 and at every
    block boundary the solve actually reached (``check_every``,
    ``2 * check_every``, ..., truncated at the cap), NaN elsewhere.
    At those boundaries the values agree with the general solver's
    per-iteration trace (up to f32 reduction-order rounding); for a
    full per-iteration trace use ``solver.cg`` - ``engine="auto"``
    keeps routing history requests there for exactly that reason.
    ``m`` accepts ``None`` or a ``ChebyshevPreconditioner`` built over
    THIS operator: its polynomial is applied in-kernel (pure VPU work on
    the resident planes - ``degree - 1`` extra stencil applies per
    iteration, no extra HBM traffic), following ``solver.cg``'s
    preconditioned recurrence.  The reported iteration count is
    ``check_every``-block aligned, exactly like ``cg(check_every=k)``.

    Returns a ``CGResult`` (history ``None``).
    """
    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_resident needs a Stencil2D or Stencil3D operator, got "
            f"{type(a).__name__} - use solver.cg for general operators")
    degree, lmin, lmax = 0, 0.0, 1.0
    if m is not None:
        from ..models.precond import ChebyshevPreconditioner

        if not isinstance(m, ChebyshevPreconditioner):
            raise TypeError(
                f"cg_resident supports m=None or a ChebyshevPreconditioner "
                f"(applied in-kernel), got {type(m).__name__} - use "
                f"solver.cg for other preconditioners")
        # The kernel applies the polynomial with THIS operator's
        # stencil, so m must describe the same matrix - same grid AND
        # same scale (a same-grid, different-scale operator would
        # silently pair a's stencil with m's foreign spectral
        # interval).  Shared logic with resident_eligible.
        status = _chebyshev_match_status(a, m)
        if status == "unverifiable":
            raise ValueError(
                "under jit, build the ChebyshevPreconditioner over the "
                "SAME operator instance passed to cg_resident (scale "
                "equality cannot be checked on traced values)")
        if status == "mismatch":
            raise ValueError(
                "the ChebyshevPreconditioner must be built over the "
                "same stencil operator being solved (same grid and "
                "same scale)")
        degree, lmin, lmax = m.degree, m.lmin, m.lmax
    grid = a.grid
    n_cells = math.prod(grid)
    b = jnp.asarray(b)
    flat_in = b.ndim == 1
    if flat_in:
        if b.shape[0] != n_cells:
            raise ValueError(f"rhs length {b.shape[0]} != grid {grid}")
        b_grid = b.reshape(grid)
    else:
        if b.shape != grid:
            raise ValueError(f"rhs shape {b.shape} != grid {grid}")
        b_grid = b

    if b_grid.dtype != jnp.float32:
        raise ValueError(
            f"cg_resident is float32-only (got {b_grid.dtype}); df64/x64 "
            "precision routes through solver.cg / solver.df64")

    if method == "cg1" and m is not None:
        raise ValueError(
            "cg_resident method='cg1' is unpreconditioned (the "
            "preconditioned Chronopoulos-Gear form needs a third "
            "reduction)")
    from .cg import _note_engine

    _note_engine("resident", method, check_every)
    kernel_fn = cg_resident_2d if len(grid) == 2 else cg_resident_3d
    x2d, iters, rr, indef, conv, health, hist = kernel_fn(
        a.scale, b_grid, x0=x0, tol=tol, rtol=rtol, maxiter=maxiter,
        check_every=check_every, iter_cap=iter_cap, interpret=interpret,
        precond_degree=degree, lmin=lmin, lmax=lmax, method=method)

    history = None
    if record_history:
        history = _expand_block_history(hist, maxiter, check_every,
                                        iter_cap)

    res_norm = jnp.sqrt(rr)
    # converged/healthy come from INSIDE the kernel: recomputing the
    # threshold here (different ||b|| reduction order) could contradict
    # the kernel's actual stop decision, and a rho <= 0 preconditioner
    # breakdown must surface as BREAKDOWN, not MAXITER (solver/cg.py
    # health semantics).
    converged = conv.astype(bool)
    healthy = health.astype(bool)
    status = jnp.where(
        ~healthy, jnp.int32(CGStatus.BREAKDOWN),
        jnp.where(converged, jnp.int32(CGStatus.CONVERGED),
                  jnp.int32(CGStatus.MAXITER)))
    x = x2d.reshape(-1) if flat_in else x2d
    return CGResult(
        x=x, iterations=iters, residual_norm=res_norm,
        converged=converged, status=status,
        indefinite=indef.astype(bool), residual_history=history)


def _expand_block_history(hist, maxiter: int, check_every: int, iter_cap):
    """Kernel block trace -> the general solver's ``(maxiter + 1,)``
    ``residual_history`` layout: ``||r||`` at index 0 and at each block
    boundary the solve reached, NaN elsewhere.  Boundary j lands at
    ``min((j + 1) * check_every, cap)`` (the final partial block
    truncates at the cap); never-run blocks carry NaN in the kernel
    trace and their (duplicate, capped) indices are dropped rather than
    allowed to overwrite a real final value."""
    check_every = max(1, min(check_every, maxiter))
    nblocks = -(-maxiter // check_every) if maxiter else 0
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    full = jnp.full((maxiter + 1,), jnp.nan, jnp.float32)
    full = full.at[0].set(jnp.sqrt(hist[0]))
    if nblocks == 0:
        return full
    vals = hist[1:]
    idx = jnp.minimum((jnp.arange(nblocks, dtype=jnp.int32) + 1)
                      * jnp.int32(check_every), cap)
    # The kernel marks never-run blocks with a -1.0 sentinel (||r||^2 is
    # nonnegative; NaN in the always-emitted output would trip
    # jax_debug_nans on every default solve).  Route sentinel slots out
    # of bounds so mode="drop" discards them (several trailing blocks
    # can share the capped index, and a sentinel must not clobber the
    # real entry there); survivors become the NaN fill of `full`.
    idx = jnp.where(vals < 0.0, jnp.int32(maxiter + 1), idx)
    return full.at[idx].set(jnp.sqrt(jnp.abs(vals)), mode="drop")


def supports_resident_df64(a, preconditioned: bool = False) -> bool:
    """True if ``cg_resident_df64`` can run this operator: a 2D/3D
    stencil whose df64 working set (8 pinned hi/lo planes +
    temporaries; +4 transient planes for in-kernel Chebyshev when
    ``preconditioned``) fits the device VMEM budget."""
    if isinstance(a, Stencil2D):
        nx, ny = a.grid
        return supports_resident_df64_2d(nx, ny,
                                         preconditioned=preconditioned)
    if isinstance(a, Stencil3D):
        nx, ny, nz = a.grid
        return supports_resident_df64_3d(nx, ny, nz,
                                         preconditioned=preconditioned)
    return False


def cg_resident_df64(
    a: Stencil2D,
    b,
    x0=None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    check_every: int = 32,
    iter_cap=None,
    preconditioner=None,
    precond_degree: int = 4,
    record_history: bool = False,
    interpret: bool = False,
) -> DF64CGResult:
    """f64-class CG (df64 storage) entirely inside one VMEM-resident kernel.

    The reference's defining precision x this framework's defining
    execution shape: ``CUDA_R_64F`` CG (``CUDACG.cu:216``) as a single
    pallas kernel with eight hi/lo planes pinned in VMEM and all
    arithmetic in compiler-proof error-free transforms.  Arguments and
    trajectory semantics mirror ``solver.df64.cg_df64`` (x0 = 0, no
    preconditioner, ``method="cg"``, no history; interpret-mode parity
    1.1e-14 relative at fixed iteration count).

    ``b`` may be float64 numpy (full precision via host split), an f32
    array (lifted with zero lo words), or an explicit ``(hi, lo)`` pair;
    flat ``(n,)`` or grid ``(nx, ny)`` shapes are accepted, and the
    solution comes back flat (``DF64CGResult.x()`` recombines to f64).
    ``x0`` takes the same forms and warm-starts the solve with the
    general ``r0 = b - A x0`` init in full df64 (``None`` = the
    reference's x0 = 0 fast path; the pair aliases the x output in
    VMEM, so a warm start costs no extra planes).

    ``preconditioner``: ``None`` or ``"chebyshev"`` - the
    ``precond_degree``-term polynomial applied IN-KERNEL in df64
    arithmetic (``cg_df64``'s chebyshev semantics; spectral interval
    from the host-side ``solver.df64.chebyshev_interval``).

    ``record_history=True`` returns the check-block-granular ``||r||``
    trace (hi word - ``DF64CGResult.residual_history``'s documented
    diagnostic semantics), laid out like :func:`cg_resident`'s.
    """
    if not isinstance(a, (Stencil2D, Stencil3D)):
        raise TypeError(
            f"cg_resident_df64 needs a Stencil2D or Stencil3D operator, "
            f"got {type(a).__name__} - use solver.df64.cg_df64 for "
            f"general operators")
    if preconditioner not in (None, "chebyshev"):
        raise ValueError(
            f"cg_resident_df64 supports preconditioner=None or "
            f"'chebyshev', got {preconditioner!r} - use "
            f"solver.df64.cg_df64 for jacobi/mg")
    degree = precond_degree if preconditioner == "chebyshev" else 0
    theta = delta = (1.0, 0.0)
    if degree:
        from .df64 import chebyshev_interval

        th, dl = chebyshev_interval(a)
        theta = (float(th[0]), float(th[1]))
        delta = (float(dl[0]), float(dl[1]))
    grid = a.grid
    n_cells = math.prod(grid)

    def to_pair(v, what):
        """host f64 (split), f32 (lifted), or explicit (hi, lo) -> a
        grid-shaped df64 pair.  Delegates the dtype/pair rules to
        ``solver.df64._coerce_rhs_df`` (ONE definition of "explicit
        device pair passes through without a host round-trip"; a second
        copy here had already drifted to weaker validation) and adds
        only the grid-shape handling the resident kernel needs."""
        from .df64 import _coerce_rhs_df

        vh, vl = _coerce_rhs_df(
            tuple(v) if isinstance(v, (tuple, list)) else v)
        if vh.ndim == 1:
            if vh.shape[0] != n_cells:
                raise ValueError(
                    f"{what} length {vh.shape[0]} != grid {grid}")
            vh, vl = vh.reshape(grid), vl.reshape(grid)
        elif vh.shape != grid:
            raise ValueError(f"{what} shape {vh.shape} != grid {grid}")
        return vh, vl

    bh, bl = to_pair(b, "rhs")
    x0_pair = None if x0 is None else to_pair(x0, "x0")

    # re-split the scale from host f64 so non-exact scales keep their
    # low word (same as solver.df64._prepare_operator)
    scale64 = np.float64(np.asarray(a.scale, dtype=np.float64))
    sh, sl = df.split_f64(scale64)

    from .cg import _note_engine

    _note_engine("resident-df64", "cg", check_every)
    kernel_fn = (cg_resident_df64_2d if len(grid) == 2
                 else cg_resident_df64_3d)
    xh, xl, iters, rr, indef, conv, health, hist = kernel_fn(
        (sh, sl), (bh, bl), x0=x0_pair, tol=tol, rtol=rtol,
        maxiter=maxiter, check_every=check_every, iter_cap=iter_cap,
        interpret=interpret, precond_degree=degree, theta=theta,
        delta=delta)

    history = None
    if record_history:
        history = _expand_block_history(hist, maxiter, check_every,
                                        iter_cap)
    converged = conv.astype(bool)
    healthy = health.astype(bool)
    status = jnp.where(
        ~healthy, jnp.int32(CGStatus.BREAKDOWN),
        jnp.where(converged, jnp.int32(CGStatus.CONVERGED),
                  jnp.int32(CGStatus.MAXITER)))
    return DF64CGResult(
        x_hi=xh.reshape(-1), x_lo=xl.reshape(-1), iterations=iters,
        residual_norm_sq_hi=rr[0], residual_norm_sq_lo=rr[1],
        converged=converged, status=status,
        indefinite=indef.astype(bool), residual_history=history)
