"""f64-parity CG on TPU hardware via double-float storage.

The reference solves entirely in float64 (``CUDA_R_64F`` descriptors,
``cublasD*`` calls - ``CUDACG.cu:216,248-347``); a TPU has no native f64.
``solver.cg`` offers two partial answers (f32 + compensated *reductions*,
or x64 emulation on CPU); this module is the full one: every vector,
matrix value, and recurrence scalar is a df64 ``(hi, lo)`` f32 pair
(``ops.df64``), ~48-bit significands end to end.  Measured
(``tests/test_df64.py``, README "f64 story"): on diag-scaled Poisson at
cond ~1e7/1e9 to rtol 1e-10, plain f32 pays +84%/+180% iterations over
the x64 solver while df64 lands at +7%/+15% - and unlike f32, df64
reaches rtol 1e-12 with ~1e-9 solution error.  On the 3x3 oracle it
reproduces the f64 trajectory exactly (3 iterations, ||r|| ~ 5e-14 on
real TPU hardware).  Cost: ~85 us/iter on a 1M-unknown 2D Poisson
stencil on v5e (~4x plain f32; ~12k CG iters/s at f64-class precision -
above the reference loop's estimated f64 throughput, on a chip with no
f64 units).  Measured with 6000-iteration deltas; the tunnel's
per-dispatch jitter swamps anything shorter.

Same reference-parity semantics as ``solver.cg``: absolute ``tol=1e-7``
on ||r|| (quirk Q3), ``maxiter=2000``, x0 = 0 fast path (r0 = p0 = b,
no initial SpMV, ``CUDACG.cu:247-259``), indefinite-direction recording
(quirk Q1), breakdown detection on non-finite scalars (quirk Q4).
Textbook recurrence; plain CG (the reference's configuration) or
Jacobi-PCG with the diagonal applied in df64 (BASELINE config #3).

Operators: ``CSRMatrix``/``ELLMatrix`` (values re-split from host f64 -
numpy always has f64, even on TPU hosts with x64 off), ``Stencil2D``/
``Stencil3D`` (matrix-free df64 shifted adds).  Under ``shard_map``, pass
``axis_name`` exactly as with ``cg`` (dots psum hi/lo).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.operators import (
    CSRMatrix,
    ELLMatrix,
    ShiftELLDF64Matrix,
    ShiftELLMatrix,
    Stencil2D,
    Stencil3D,
)
from ..ops import df64 as df
from .cg import _blocked_while
from .status import CGStatus


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x_hi", "x_lo", "r_hi", "r_lo", "p_hi", "p_lo",
                 "rho_hi", "rho_lo", "rr_hi", "rr_lo", "rr0_hi", "rr0_lo",
                 "k", "indefinite"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class DF64Checkpoint:
    """Complete df64 CG recurrence state: resuming continues the exact
    trajectory (mirror of ``cg.CGCheckpoint`` for the double-float
    solver; the rr0 pair preserves the original rtol threshold)."""

    x_hi: jax.Array
    x_lo: jax.Array
    r_hi: jax.Array
    r_lo: jax.Array
    p_hi: jax.Array
    p_lo: jax.Array
    rho_hi: jax.Array
    rho_lo: jax.Array
    rr_hi: jax.Array
    rr_lo: jax.Array
    rr0_hi: jax.Array
    rr0_lo: jax.Array
    k: jax.Array
    indefinite: jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x_hi", "x_lo", "iterations", "residual_norm_sq_hi",
                 "residual_norm_sq_lo", "converged", "status", "indefinite",
                 "residual_history", "checkpoint", "flight"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class DF64CGResult:
    """CG outcome with the solution as a df64 pair.

    ``x()`` recombines to host float64 (independent of jax x64 mode);
    ``residual_norm()`` likewise.
    """

    x_hi: jax.Array
    x_lo: jax.Array
    iterations: jax.Array
    residual_norm_sq_hi: jax.Array
    residual_norm_sq_lo: jax.Array
    converged: jax.Array
    status: jax.Array
    indefinite: jax.Array
    residual_history: Optional[jax.Array]  # (maxiter+1,) ||r||, NaN-filled
    # past the final iterate - same semantics as CGResult (hi word only;
    # the trace is diagnostic, full df64 depth lives in the scalars)
    checkpoint: Optional[DF64Checkpoint] = None  # set when return_checkpoint
    #: flight-recorder ring buffer (capacity, 4) f32 when a FlightConfig
    #: was passed (hi words of rr/alpha/beta - diagnostic precision,
    #: like residual_history); decode with FlightRecord.from_buffer
    flight: Optional[jax.Array] = None

    def x(self) -> np.ndarray:
        return df.to_f64(self.x_hi, self.x_lo)

    def residual_norm(self) -> float:
        rr = float(np.float64(np.asarray(self.residual_norm_sq_hi))
                   + np.float64(np.asarray(self.residual_norm_sq_lo)))
        return float(np.sqrt(max(rr, 0.0)))

    def status_enum(self) -> CGStatus:
        return CGStatus(int(self.status))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("vals_hi", "vals_lo", "cols", "scale_hi", "scale_lo",
                 "diag_hi", "diag_lo"),
    meta_fields=("kind", "grid"),
)
@dataclasses.dataclass(frozen=True)
class _DF64Operator:
    """Pre-split df64 operator: ELL (vals pair + cols) or stencil.
    ``diag_hi/lo`` carry diag(A) for the Jacobi preconditioner."""

    vals_hi: jax.Array
    vals_lo: jax.Array
    cols: jax.Array
    scale_hi: jax.Array
    scale_lo: jax.Array
    diag_hi: jax.Array
    diag_lo: jax.Array
    kind: str
    grid: Tuple[int, ...]

    def matvec(self, x: df.DF) -> df.DF:
        if self.kind == "ell":
            return df.ell_matvec((self.vals_hi, self.vals_lo), self.cols, x)
        scale = (self.scale_hi, self.scale_lo)
        if self.kind == "stencil2d":
            return df.stencil2d_matvec(x, self.grid, scale)
        return df.stencil3d_matvec(x, self.grid, scale)


def _prepare_operator(a, jacobi: bool = False):
    """Host-side split; the Jacobi diagonal (full-length for ELL, a
    broadcastable scalar pair for constant-diagonal stencils) is built
    only when requested - it is dead weight for plain CG."""
    zero = jnp.zeros((), jnp.float32)
    if isinstance(a, ShiftELLDF64Matrix):
        return a  # already a df64 operator (pallas lane-gather kernel)
    if isinstance(a, ShiftELLMatrix):
        # lift the f32 packing: values stay exact, accumulation is df64
        return ShiftELLDF64Matrix.from_shiftell(a)
    if isinstance(a, (Stencil2D, Stencil3D)):
        # re-split the scale from host f64 so non-exact scales keep
        # their low word
        scale64 = np.float64(np.asarray(a.scale, dtype=np.float64))
        sh, sl = df.split_f64(scale64)
        kind = "stencil2d" if isinstance(a, Stencil2D) else "stencil3d"
        dh = dl = zero
        if jacobi:
            # the operator owns its diagonal definition; recover the
            # (constant) center weight from it rather than restating it
            center = np.float64(np.asarray(a.diagonal()[0],
                                           dtype=np.float64))
            dh, dl = (jnp.asarray(v) for v in df.split_f64(center))
        return _DF64Operator(
            vals_hi=zero, vals_lo=zero, cols=jnp.zeros((), jnp.int32),
            scale_hi=jnp.asarray(sh), scale_lo=jnp.asarray(sl),
            diag_hi=dh, diag_lo=dl, kind=kind, grid=a.grid)
    if isinstance(a, CSRMatrix):
        a = a.to_ell()
    if isinstance(a, ELLMatrix) and a.shape[0] >= 200_000:
        import warnings

        warnings.warn(
            f"df64 on an assembled csr/ell matrix routes through the XLA "
            f"gather (~43 ms/CG-iteration at 1M rows - roughly 400x the "
            f"pallas rate); at n={a.shape[0]} use "
            f"CSRMatrix.to_shiftell_df64() (CLI: --format shiftell) for "
            f"the df64 lane-gather kernel, or shard over a mesh",
            UserWarning, stacklevel=3)
    if not isinstance(a, ELLMatrix):
        raise TypeError(
            f"cg_df64 supports CSRMatrix/ELLMatrix/Stencil2D/Stencil3D, "
            f"got {type(a).__name__} (dense df64 would need error-free "
            f"MXU accumulation, which the hardware cannot provide)")
    vh, vl = df.split_f64(np.asarray(a.vals, dtype=np.float64))
    dh = dl = zero
    if jacobi:
        dh, dl = (jnp.asarray(v) for v in df.split_f64(
            np.asarray(a.diagonal(), dtype=np.float64)))
    return _DF64Operator(
        vals_hi=jnp.asarray(vh), vals_lo=jnp.asarray(vl), cols=a.cols,
        scale_hi=zero, scale_lo=zero, diag_hi=dh, diag_lo=dl,
        kind="ell", grid=())



def _coerce_rhs_df(b) -> df.DF:
    """Right-hand side -> df64 pair: an already-split (hi, lo) pair of
    equal-shape f32 vectors passes through (the distributed tier
    pre-splits on host and calls solver entries inside shard_map), host
    float64 splits at full precision, x64-mode device arrays split via
    the host, anything else lifts from f32 with zero low words.  Shared
    by every df64 solver entry (cg_df64, minres_df64) so the precision
    rules cannot drift.  The pair rule is deliberately strict - f32
    dtype, matching non-scalar shapes - so a plain 2-element numeric
    tuple like ``(1.0, 2.0)`` still coerces as a length-2 VECTOR, not a
    scalar hi/lo pair."""
    if (isinstance(b, tuple) and len(b) == 2
            and all(isinstance(v, (np.ndarray, jnp.ndarray)) for v in b)):
        hi, lo = (jnp.asarray(v) for v in b)
        if (hi.dtype == jnp.float32 and lo.dtype == jnp.float32
                and hi.shape == lo.shape and hi.ndim >= 1):
            return (hi, lo)
    if isinstance(b, np.ndarray) and b.dtype == np.float64:
        bh, bl = df.split_f64(b)
        return (jnp.asarray(bh), jnp.asarray(bl))
    b_arr = jnp.asarray(b)
    if b_arr.dtype == jnp.float64:  # x64 mode (CPU tests)
        bh, bl = df.split_f64(np.asarray(b_arr))
        return (jnp.asarray(bh), jnp.asarray(bl))
    return df.from_f32(b_arr.astype(jnp.float32))

class _State(NamedTuple):
    k: jax.Array
    x: df.DF
    r: df.DF
    p: df.DF
    rho: df.DF            # r . z as a df64 scalar pair (== rr w/o precond)
    rr: df.DF             # ||r||^2 (convergence is checked on r, not z)
    indefinite: jax.Array
    finite: jax.Array
    history: jax.Array


def cg_df64(
    a,
    b,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    record_history: bool = False,
    preconditioner: Optional[str] = None,
    axis_name: Optional[str] = None,
    resume_from: Optional[DF64Checkpoint] = None,
    return_checkpoint: bool = False,
    check_every: int = 1,
    method: str = "cg",
    iter_cap: Optional[int] = None,
    precond_degree: int = 4,
    flight=None,
) -> DF64CGResult:
    """CG with df64 storage (see module docstring).

    ``b`` may be a float64 numpy array (full precision via host split),
    or any f32/f64 array-like.  ``a`` additionally accepts
    ``ShiftELLDF64Matrix`` (or a plain f32 ``ShiftELLMatrix``, lifted
    with zero lo planes): the pallas double-float lane-gather kernel -
    the fast path for ASSEMBLED matrices at f64-class precision (the
    reference's ``CUDA_R_64F`` CSR SpMV, ``CUDACG.cu:216,288``).
    ``preconditioner``: ``None`` (plain CG, the reference's
    configuration), ``"jacobi"`` (diag(A)^-1 applied in df64 - BASELINE
    config #3 at f64-class precision) or ``"chebyshev"``
    (``precond_degree``-term Chebyshev polynomial applied in df64, its
    spectral interval from a HOST-SIDE hi-word power iteration before
    dispatch - an in-jit estimate exploded virtual-mesh compile times,
    see ``chebyshev_interval``; ``method="cg"`` only) or ``"mg"`` (one symmetric f32 geometric
    V-cycle on the residual's hi word - mixed-precision PCG, stencil
    operators only, ``method="cg"`` only; grid-independent iteration
    counts at f64-class precision).
    ``resume_from``/``return_checkpoint`` mirror ``solve``'s
    checkpointing: ``maxiter`` remains the TOTAL iteration cap, and the
    resumed run continues the exact df64 trajectory.
    ``check_every``: evaluate the convergence predicate once per k
    iterations (same contract as ``solver.cg``: iterates are IDENTICAL,
    up to k-1 extra iterations may run past convergence; measured ~30%
    faster per iteration on v5e in the f32 solver, and df64 - 4x
    costlier per iteration - benefits at least as much).
    ``method``: ``"cg"`` (textbook, the reference's recurrence),
    ``"cg1"`` (Chronopoulos-Gear - every inner product fused into ONE
    collective) or ``"pipecg"`` (Ghysels-Vanroose - that collective
    overlaps the matvec; periodic residual replacement bounds drift).
    Checkpoint/resume requires ``method="cg"``.
    ``iter_cap``: TRACED early-stop bound (<= ``maxiter``); segment
    sweeps (``solve_resumable_df64``) vary it without recompiling -
    ``maxiter`` alone is static and would retrace per segment.
    ``flight``: optional ``telemetry.flight.FlightConfig`` - carry the
    convergence flight recorder in the loop state (``solver.cg``
    semantics; rows hold the HI words of ``||r||^2``/alpha/beta, f32
    diagnostic precision like ``residual_history``).  ``method="cg"``
    only - the fused-reduction variants keep their recorder on the
    ``solver.cg`` side of the trade for now.
    """
    if preconditioner not in (None, "jacobi", "chebyshev", "mg"):
        raise ValueError(
            f"cg_df64 supports preconditioner=None, 'jacobi', 'chebyshev' "
            f"or 'mg', got {preconditioner!r}")
    if method not in ("cg", "cg1", "pipecg", "minres"):
        raise ValueError(f"unknown method {method!r}; expected 'cg', "
                         f"'cg1', 'pipecg' or 'minres'")
    if flight is not None and method != "cg":
        raise ValueError(
            f"cg_df64 carries the flight recorder on method='cg' only "
            f"(got method={method!r}); use record_history for the "
            f"variants' dense trace")
    if method == "minres":
        # the symmetric-indefinite solver at f64-class precision
        # (solver.minres.minres_df64; quirk Q1 x CUDA_R_64F)
        if preconditioner is not None:
            raise ValueError(
                "method='minres' is unpreconditioned (preconditioned "
                "MINRES needs an SPD preconditioner and a different "
                "inner product)")
        if resume_from is not None or return_checkpoint:
            raise ValueError(
                "method='minres' does not support checkpoint/resume")
        from .minres import minres_df64

        return minres_df64(a, b, tol=tol, rtol=rtol, maxiter=maxiter,
                           record_history=record_history,
                           axis_name=axis_name, iter_cap=iter_cap,
                           check_every=check_every)
    if preconditioner in ("chebyshev", "mg") and method != "cg":
        raise ValueError(
            f"preconditioner={preconditioner!r} requires method='cg' in "
            f"df64 (the variants fuse their reductions around the plain "
            f"or Jacobi recurrence)")
    if preconditioner == "mg" and not isinstance(a, (Stencil2D, Stencil3D)):
        raise ValueError(
            f"preconditioner='mg' needs a matrix-free stencil operator "
            f"(Stencil2D/Stencil3D - the geometric hierarchy rediscretizes "
            f"the grid), got {type(a).__name__}")
    if precond_degree < 1:
        raise ValueError(f"precond_degree must be >= 1, got "
                         f"{precond_degree}")
    if method != "cg" and (resume_from is not None or return_checkpoint
                           or iter_cap is not None):
        raise ValueError(
            "checkpoint/resume (and its iter_cap segmenting) requires "
            "method='cg': DF64Checkpoint carries the standard recurrence "
            "state, not the variants' extra vectors")
    op = _prepare_operator(a, jacobi=preconditioner == "jacobi")
    b_df = _coerce_rhs_df(b)

    tol2 = df.const(float(tol) ** 2)
    rtol2 = df.const(float(rtol) ** 2)
    jacobi = preconditioner == "jacobi"
    if method != "cg":
        impl = (_variant_jits if axis_name is None else _VARIANTS)[method]
        return impl(op, b_df, tol2, rtol2, maxiter=maxiter,
                    record_history=record_history, jacobi=jacobi,
                    axis_name=axis_name, check_every=check_every)
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap,
                      jnp.int32)
    cheb = precond_degree if preconditioner == "chebyshev" else None
    interval = chebyshev_interval(a) if cheb is not None else None
    mg = None
    if preconditioner == "mg":
        # the V-cycle applies in f32 to the HI word only - the standard
        # mixed-precision PCG arrangement (a preconditioner is just a
        # fixed SPD operator; the attainable accuracy is set by the df64
        # recurrence arithmetic, not by M's application precision)
        from ..models.multigrid import MultigridPreconditioner

        a32 = a
        if a._dtype_name != "float32":
            a32 = dataclasses.replace(
                a, scale=a.scale.astype(jnp.float32),
                _dtype_name="float32")
        mg = MultigridPreconditioner.from_operator(a32)
    if axis_name is None:
        return _solve_jit(op, b_df, tol2, rtol2, resume_from, cap,
                          interval, mg,
                          maxiter=maxiter, record_history=record_history,
                          jacobi=jacobi, axis_name=None,
                          return_checkpoint=return_checkpoint,
                          check_every=check_every, chebyshev_degree=cheb,
                          flight=flight)
    return _solve(op, b_df, tol2, rtol2, resume_from, cap, interval, mg,
                  maxiter=maxiter,
                  record_history=record_history, jacobi=jacobi,
                  axis_name=axis_name, return_checkpoint=return_checkpoint,
                  check_every=check_every, chebyshev_degree=cheb,
                  flight=flight)


def chebyshev_interval(a, *, ratio: float = 30.0,
                       iters: int = 30) -> Tuple[df.DF, df.DF]:
    """(theta, delta) df64 pairs bounding A's spectrum for the Chebyshev
    preconditioner: [lmax/ratio, lmax] with lmax from HOST-SIDE power
    iteration (percent-level accuracy suffices; doing the estimate
    inside the jitted distributed solve instead exploded compile times -
    30 unrolled df64 halo-exchange matvecs on a virtual mesh).

    ``a`` may be any f32 ``LinearOperator`` (the f32 power iteration of
    ``models.precond.estimate_lmax``) or a df64 operator exposing
    ``matvec_df`` (eager hi-word power iteration).  Deterministic, so
    resumed or re-built solves derive the identical preconditioner.
    """
    if hasattr(a, "matvec_df"):
        n = a.shape[0]
        # same deterministic pseudo-random start as
        # models.precond.estimate_lmax: an aligned start (e.g. all-ones)
        # can be exactly orthogonal to the dominant eigenvector, which
        # would underestimate lmax and let the Chebyshev polynomial go
        # indefinite on the uncovered tail
        idx = jnp.arange(n, dtype=jnp.float32)
        v = jnp.sin(idx * 12.9898 + 78.233) + 1.5
        v = v / jnp.sqrt(jnp.vdot(v, v))
        zeros = jnp.zeros(n, jnp.float32)
        for _ in range(iters):
            w = a.matvec_df((v, zeros))[0]
            v = w / jnp.sqrt(jnp.maximum(jnp.vdot(w, w), 1e-30))
        w = a.matvec_df((v, zeros))[0]
        lmax = 1.1 * float(jnp.vdot(v, w) / jnp.vdot(v, v))
    else:
        from ..models.precond import estimate_lmax

        lmax = float(estimate_lmax(a, iters=iters))
    lmin = lmax / ratio
    return df.const((lmax + lmin) * 0.5), df.const((lmax - lmin) * 0.5)


def _chebyshev_apply(mv, r: df.DF, theta: df.DF, delta: df.DF,
                     degree: int) -> df.DF:
    """z = p(A) r in df64: the ``degree``-term Chebyshev semi-iteration
    for A z = r from z0 = 0 (same recurrence as the f32
    ``models.precond.ChebyshevPreconditioner.matvec``, in double-float
    arithmetic; ``degree - 1`` matvecs, no reductions)."""
    sigma = df.div(theta, delta)
    rho = df.div(df.const(1.0), sigma)
    d = df.div(r, theta)
    z = d
    two = df.const(2.0)
    for _ in range(degree - 1):
        rho_new = df.div(df.const(1.0),
                         df.sub(df.mul(two, sigma), rho))
        resid = df.sub(r, mv(z))
        d = df.add(df.mul(df.mul(rho_new, rho), d),
                   df.mul(df.div(df.mul(two, rho_new), delta), resid))
        z = df.add(z, d)
        rho = rho_new
    return z


def _pcast_varying(pair, axis_name):
    """Mark a fresh (unvarying) df64 pair device-varying over one mesh
    axis name or a tuple of them (pencil meshes).  The identity on jax
    versions without VMA tracking (``utils.compat.pcast_varying``)."""
    from ..utils.compat import pcast_varying

    names = (axis_name if isinstance(axis_name, (tuple, list))
             else (axis_name,))
    out = pair
    for nm in names:
        out = tuple(pcast_varying(v, nm) for v in out)
    return out


def _safe_div(num: df.DF, den: df.DF) -> df.DF:
    """df64 num / den, but a freeze (0) when both hi words are exactly 0.

    Same contract as ``cg._safe_div``: inside a ``check_every`` block,
    iterations past an exact solve have rho = p.Ap = 0 and 0/0 would
    inject NaN into a state the predicate can no longer veto; a genuine
    breakdown (den = 0, num != 0) still produces inf/NaN for the health
    check to catch.
    """
    zero = jnp.logical_and(num[0] == 0.0, den[0] == 0.0)
    den_safe = (jnp.where(zero, jnp.ones_like(den[0]), den[0]),
                jnp.where(zero, jnp.zeros_like(den[1]), den[1]))
    q = df.div(num, den_safe)
    return (jnp.where(zero, jnp.zeros_like(q[0]), q[0]),
            jnp.where(zero, jnp.zeros_like(q[1]), q[1]))


def _solve(op, b_df, tol2, rtol2, resume, cap=None, cheb_interval=None,
           mg=None,
           *, maxiter, record_history, jacobi, axis_name,
           return_checkpoint=False, check_every=1, chebyshev_degree=None,
           flight=None):
    n = b_df[0].shape[0]
    if cap is None:
        cap = jnp.asarray(maxiter, jnp.int32)
    hist_len = maxiter + 1 if record_history else 0
    d = (op.diag_hi, op.diag_lo)
    # double-float operators (shift-ELL) expose matvec_df; the internal
    # _DF64Operator dispatches through matvec
    mv = op.matvec_df if hasattr(op, "matvec_df") else op.matvec

    preconditioned = (jacobi or chebyshev_degree is not None
                      or mg is not None)
    if mg is not None:
        # f32 V-cycle on the hi word; the result enters the df64
        # recurrence with a zero lo word (mixed-precision PCG: M need
        # only be a fixed SPD operator, see cg_df64)
        def apply_m(r):
            z = mg.matvec(r[0])
            return (z, jnp.zeros_like(z))
    elif chebyshev_degree is not None:
        theta, delta = cheb_interval

        def apply_m(r):
            return _chebyshev_apply(mv, r, theta, delta,
                                    chebyshev_degree)
    elif jacobi:
        def apply_m(r):
            return df.div(r, d)
    else:
        apply_m = None
    if resume is not None:
        x0 = (resume.x_hi, resume.x_lo)
        r0 = (resume.r_hi, resume.r_lo)
        p0 = (resume.p_hi, resume.p_lo)
        rho0 = (resume.rho_hi, resume.rho_lo)
        rr0 = (resume.rr_hi, resume.rr_lo)
        rr_base = (resume.rr0_hi, resume.rr0_lo)
        k0 = resume.k
        indef0 = resume.indefinite
    else:
        x0 = (jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32))
        if axis_name is not None:
            # fresh zeros are unvarying; the while_loop carry must match
            # the body's output (device-varying) under vma tracking
            x0 = _pcast_varying(x0, axis_name)
        r0 = b_df     # x0 = 0 fast path (CUDACG.cu:247-259)
        z0 = apply_m(r0) if preconditioned else r0
        p0 = z0
        rr0 = df.dot(r0, r0, axis_name=axis_name)
        rho0 = (df.dot(r0, z0, axis_name=axis_name) if preconditioned
                else rr0)
        rr_base = rr0
        k0 = jnp.zeros((), jnp.int32)
        indef0 = jnp.zeros((), bool)
    # threshold^2 = max(tol^2, rtol^2 * ||r0||^2) as a df64 pair, with
    # the ORIGINAL solve's rr0 under resume
    thr = _threshold(tol2, rtol2, rr_base)
    history0 = jnp.full(hist_len, jnp.nan, jnp.float32)
    if record_history:
        history0 = history0.at[k0].set(
            jnp.sqrt(jnp.maximum(rr0[0], 0.0)))

    def cond(s: _State):
        unconverged = jnp.logical_not(df.less(s.rr, thr))
        # rr == 0: solved exactly - further steps would only freeze
        nontrivial = s.rr[0] > 0.0
        return (s.k < maxiter) & (s.k < cap) & s.finite & unconverged \
            & nontrivial

    def body_ab(s: _State):
        ap = mv(s.p)
        pap = df.dot(s.p, ap, axis_name=axis_name)
        alpha = _safe_div(s.rho, pap)
        x = df.axpy(alpha, s.p, s.x)
        r = df.axpy(df.neg(alpha), ap, s.r)
        rr_new = df.dot(r, r, axis_name=axis_name)
        if preconditioned:
            z = apply_m(r)
            rho_new = df.dot(r, z, axis_name=axis_name)
        else:
            z, rho_new = r, rr_new
        beta = _safe_div(rho_new, s.rho)
        p = df.axpy(beta, s.p, z)
        k = s.k + 1
        history = s.history
        if record_history:
            history = history.at[k].set(
                jnp.sqrt(jnp.maximum(rr_new[0], 0.0)))
        finite = jnp.logical_and(jnp.isfinite(rho_new[0]),
                                 jnp.isfinite(pap[0]))
        return _State(
            k=k, x=x, r=r, p=p, rho=rho_new, rr=rr_new,
            # s.rr > 0 excludes frozen post-exact-solve steps (p = 0
            # gives p.Ap = 0, not evidence of indefiniteness)
            indefinite=jnp.logical_or(
                s.indefinite,
                jnp.logical_and(pap[0] <= 0.0, s.rr[0] > 0.0)),
            finite=finite, history=history), \
            k, rr_new[0], alpha[0], beta[0]

    def body(s: _State):
        return body_ab(s)[0]

    def fits(t):
        return (t.k + check_every <= maxiter) \
            & (t.k + check_every <= cap)

    s0 = _State(k=k0, x=x0, r=r0, p=p0, rho=rho0,
                rr=rr0, indefinite=indef0,
                finite=jnp.isfinite(rho0[0]),
                history=history0)
    if flight is None:
        s = _blocked_while(cond, body, s0, check_every, fits)
        fbuf = None
    else:
        from .cg import _flight_while

        # rows carry the HI words (f32 diagnostic precision, like the
        # residual_history trace); under axis_name the dots are already
        # globally reduced, so the buffer is replicated across shards
        s, fbuf, _ = _flight_while(
            cond, body_ab, s0, check_every, fits, flight,
            dtype=jnp.float32, k0=k0, rr0=rr0[0],
            heartbeat_ok=axis_name is None)
    converged = jnp.logical_or(df.less(s.rr, thr), s.rr[0] == 0.0)
    status = jnp.where(
        jnp.logical_not(s.finite), CGStatus.BREAKDOWN.value,
        jnp.where(converged, CGStatus.CONVERGED.value,
                  CGStatus.MAXITER.value))
    checkpoint = None
    if return_checkpoint:
        checkpoint = DF64Checkpoint(
            x_hi=s.x[0], x_lo=s.x[1], r_hi=s.r[0], r_lo=s.r[1],
            p_hi=s.p[0], p_lo=s.p[1], rho_hi=s.rho[0], rho_lo=s.rho[1],
            rr_hi=s.rr[0], rr_lo=s.rr[1], rr0_hi=rr_base[0],
            rr0_lo=rr_base[1], k=s.k, indefinite=s.indefinite)
    return DF64CGResult(
        x_hi=s.x[0], x_lo=s.x[1], iterations=s.k,
        residual_norm_sq_hi=s.rr[0], residual_norm_sq_lo=s.rr[1],
        converged=converged, status=status, indefinite=s.indefinite,
        residual_history=s.history if record_history else None,
        checkpoint=checkpoint, flight=fbuf)


_solve_jit = jax.jit(_solve, static_argnames=("maxiter", "record_history",
                                              "jacobi", "axis_name",
                                              "return_checkpoint",
                                              "check_every",
                                              "chebyshev_degree",
                                              "flight"))


# -- single-reduction / pipelined variants ------------------------------------
#
# The df64 analogues of solver.cg's method="cg1" (Chronopoulos-Gear:
# every per-iteration inner product fused into ONE collective) and
# method="pipecg" (Ghysels-Vanroose: that one collective additionally
# overlaps the iteration's matvec).  They matter most combined with
# distribution: textbook df64 CG pays two psums per iteration
# (solve_distributed_df64), cg1/pipecg pay one - the same
# latency-hiding trade as the f32 variants, at f64-class precision.
# Same iterates as method="cg" in exact arithmetic (tests check
# trajectory parity); same safe-div freeze semantics under check_every.


def _threshold(tol2: df.DF, rtol2: df.DF, rr0: df.DF) -> df.DF:
    """threshold^2 = max(tol^2, rtol^2 * ||r0||^2) as a df64 pair."""
    rt = df.mul(rtol2, rr0)
    return (jnp.maximum(tol2[0], rt[0]),
            jnp.where(tol2[0] >= rt[0], tol2[1], rt[1]))


class _CG1State(NamedTuple):
    k: jax.Array
    x: df.DF
    r: df.DF
    p: df.DF
    s: df.DF              # A @ p, maintained by recurrence
    gamma: df.DF          # r . u (u = M^-1 r; == ||r||^2 unpreconditioned)
    rr: df.DF             # ||r||^2
    alpha: df.DF          # step length for the NEXT x/r update
    indefinite: jax.Array
    history: jax.Array


class _PipeState(NamedTuple):
    k: jax.Array
    x: df.DF
    r: df.DF
    u: df.DF              # M^-1 r
    w: df.DF              # A u
    p: df.DF
    s: df.DF              # A p
    q: df.DF              # M^-1 s
    z: df.DF              # A q
    gamma: df.DF
    rr: df.DF
    alpha: df.DF
    indefinite: jax.Array
    history: jax.Array


def _variant_cond(maxiter, thr):
    def cond(st):
        unconverged = jnp.logical_not(df.less(st.rr, thr))
        nontrivial = st.rr[0] > 0.0
        healthy = (jnp.isfinite(st.rr[0]) & jnp.isfinite(st.gamma[0])
                   & jnp.isfinite(st.alpha[0]) & (st.gamma[0] > 0.0))
        return (st.k < maxiter) & unconverged & nontrivial & healthy
    return cond


def _variant_package(final, thr, record_history) -> DF64CGResult:
    converged = jnp.logical_or(df.less(final.rr, thr), final.rr[0] == 0.0)
    healthy = (jnp.isfinite(final.rr[0]) & jnp.isfinite(final.gamma[0])
               & jnp.isfinite(final.alpha[0])
               & jnp.logical_or(final.gamma[0] > 0.0, final.rr[0] == 0.0))
    status = jnp.where(
        converged, CGStatus.CONVERGED.value,
        jnp.where(jnp.logical_not(healthy), CGStatus.BREAKDOWN.value,
                  CGStatus.MAXITER.value))
    return DF64CGResult(
        x_hi=final.x[0], x_lo=final.x[1], iterations=final.k,
        residual_norm_sq_hi=final.rr[0], residual_norm_sq_lo=final.rr[1],
        converged=converged, status=status, indefinite=final.indefinite,
        residual_history=final.history if record_history else None,
        checkpoint=None)


def _variant_init(op, b_df, jacobi, axis_name):
    """Shared x0=0 init for cg1/pipecg: returns (mv, d, x0, r0, u0, w0,
    rr0, gamma0, delta0, alpha0)."""
    n = b_df[0].shape[0]
    d = (op.diag_hi, op.diag_lo)
    mv = op.matvec_df if hasattr(op, "matvec_df") else op.matvec
    x0 = (jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32))
    if axis_name is not None:
        x0 = _pcast_varying(x0, axis_name)
    r0 = b_df  # x0 = 0 fast path (CUDACG.cu:247-259)
    u0 = df.div(r0, d) if jacobi else r0
    w0 = mv(u0)
    if jacobi:
        rr0, gamma0, delta0 = df.fused_dots(
            [(r0, r0), (r0, u0), (w0, u0)], axis_name=axis_name)
    else:
        rr0, delta0 = df.fused_dots([(r0, r0), (w0, r0)],
                                    axis_name=axis_name)
        gamma0 = rr0
    alpha0 = _safe_div(gamma0, delta0)
    return mv, d, x0, r0, u0, w0, rr0, gamma0, delta0, alpha0


def _history0(record_history, maxiter, rr0):
    hist = jnp.full(maxiter + 1 if record_history else 0, jnp.nan,
                    jnp.float32)
    if record_history:
        hist = hist.at[0].set(jnp.sqrt(jnp.maximum(rr0[0], 0.0)))
    return hist


def _solve_cg1(op, b_df, tol2, rtol2, *, maxiter, record_history, jacobi,
               axis_name, check_every=1):
    mv, d, x0, r0, u0, w0, rr0, gamma0, delta0, alpha0 = _variant_init(
        op, b_df, jacobi, axis_name)
    thr = _threshold(tol2, rtol2, rr0)
    st0 = _CG1State(
        k=jnp.zeros((), jnp.int32), x=x0, r=r0, p=u0, s=w0,
        gamma=gamma0, rr=rr0, alpha=alpha0,
        indefinite=jnp.logical_and(delta0[0] <= 0.0, rr0[0] > 0.0),
        history=_history0(record_history, maxiter, rr0))

    def step(st: _CG1State) -> _CG1State:
        x = df.axpy(st.alpha, st.p, st.x)
        r = df.axpy(df.neg(st.alpha), st.s, st.r)
        u = df.div(r, d) if jacobi else r
        w = mv(u)
        if jacobi:
            rr, gamma, delta = df.fused_dots(
                [(r, r), (r, u), (w, u)], axis_name=axis_name)
        else:
            rr, delta = df.fused_dots([(r, r), (w, r)],
                                      axis_name=axis_name)
            gamma = rr
        beta = _safe_div(gamma, st.gamma)
        # denom == p_new . A p_new in exact arithmetic
        denom = df.sub(delta, df.mul(beta, _safe_div(gamma, st.alpha)))
        alpha = _safe_div(gamma, denom)
        p = df.axpy(beta, st.p, u)
        s = df.axpy(beta, st.s, w)
        k = st.k + 1
        history = st.history
        if record_history:
            history = history.at[k].set(
                jnp.sqrt(jnp.maximum(rr[0], 0.0)))
        return _CG1State(
            k=k, x=x, r=r, p=p, s=s, gamma=gamma, rr=rr, alpha=alpha,
            indefinite=jnp.logical_or(
                st.indefinite,
                jnp.logical_and(denom[0] <= 0.0, rr[0] > 0.0)),
            history=history)

    final = _blocked_while(_variant_cond(maxiter, thr), step, st0,
                           check_every,
                           lambda t: t.k + check_every <= maxiter)
    return _variant_package(final, thr, record_history)


# df64 drift behaves like f64's (slow): the long replacement cadence
# keeps the ~3-matvec recompute negligible (see cg._replace_cadence)
_REPLACE_CADENCE_DF64 = 512


def _solve_pipecg(op, b_df, tol2, rtol2, *, maxiter, record_history,
                  jacobi, axis_name, check_every=1):
    mv, d, x0, r0, u0, w0, rr0, gamma0, delta0, alpha0 = _variant_init(
        op, b_df, jacobi, axis_name)
    m0 = df.div(w0, d) if jacobi else w0
    n0 = mv(m0)
    thr = _threshold(tol2, rtol2, rr0)
    st0 = _PipeState(
        k=jnp.zeros((), jnp.int32), x=x0, r=r0, u=u0, w=w0,
        p=u0, s=w0, q=m0, z=n0,
        gamma=gamma0, rr=rr0, alpha=alpha0,
        indefinite=jnp.logical_and(delta0[0] <= 0.0, rr0[0] > 0.0),
        history=_history0(record_history, maxiter, rr0))

    def replace(x, p):
        """Recompute derived vectors from definition (drift reset)."""
        r = df.sub(b_df, mv(x))
        u = df.div(r, d) if jacobi else r
        w = mv(u)
        s = mv(p)
        q = df.div(s, d) if jacobi else s
        z = mv(q)
        return r, u, w, s, q, z

    def step(st: _PipeState) -> _PipeState:
        x = df.axpy(st.alpha, st.p, st.x)
        r = df.axpy(df.neg(st.alpha), st.s, st.r)
        u = df.axpy(df.neg(st.alpha), st.q, st.u)
        w = df.axpy(df.neg(st.alpha), st.z, st.w)
        k = st.k + 1
        r, u, w, s_old, q_old, z_old = lax.cond(
            (k % _REPLACE_CADENCE_DF64) == 0,
            lambda: replace(x, st.p),
            lambda: (r, u, w, st.s, st.q, st.z))
        # the fused reduction depends only on (r, u, w); the matvec below
        # only on w - independent, so the psum overlaps the matvec
        if jacobi:
            rr, gamma, delta = df.fused_dots(
                [(r, r), (r, u), (w, u)], axis_name=axis_name)
            mm = df.div(w, d)
        else:
            rr, delta = df.fused_dots([(r, r), (w, r)],
                                      axis_name=axis_name)
            gamma = rr
            mm = w
        nn = mv(mm)
        beta = _safe_div(gamma, st.gamma)
        denom = df.sub(delta, df.mul(beta, _safe_div(gamma, st.alpha)))
        alpha = _safe_div(gamma, denom)
        p = df.axpy(beta, st.p, u)
        s = df.axpy(beta, s_old, w)
        q = df.axpy(beta, q_old, mm)
        z = df.axpy(beta, z_old, nn)
        history = st.history
        if record_history:
            history = history.at[k].set(
                jnp.sqrt(jnp.maximum(rr[0], 0.0)))
        return _PipeState(
            k=k, x=x, r=r, u=u, w=w, p=p, s=s, q=q, z=z,
            gamma=gamma, rr=rr, alpha=alpha,
            indefinite=jnp.logical_or(
                st.indefinite,
                jnp.logical_and(denom[0] <= 0.0, rr[0] > 0.0)),
            history=history)

    final = _blocked_while(_variant_cond(maxiter, thr), step, st0,
                           check_every,
                           lambda t: t.k + check_every <= maxiter)
    return _variant_package(final, thr, record_history)


_VARIANTS = {"cg1": _solve_cg1, "pipecg": _solve_pipecg}
_variant_jits = {
    name: jax.jit(fn, static_argnames=("maxiter", "record_history",
                                       "jacobi", "axis_name",
                                       "check_every"))
    for name, fn in _VARIANTS.items()}
