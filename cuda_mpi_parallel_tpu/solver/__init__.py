"""Solvers: jitted Krylov methods (reference: the inlined CG loop at
``CUDACG.cu:269-352``)."""

from .cg import CGCheckpoint, CGResult, cg, solve
from .status import CGStatus

__all__ = ["CGCheckpoint", "CGResult", "CGStatus", "cg", "solve"]
