"""Solvers: jitted Krylov methods (reference: the inlined CG loop at
``CUDACG.cu:269-352``)."""

from .cg import CGCheckpoint, CGResult, cg, solve
from .df64 import DF64CGResult, DF64Checkpoint, cg_df64
from .many import CGBatchResult, cg_many, solve_many, stack_columns
from .recycle import (
    BasisConfig,
    HarvestError,
    RecycleMismatch,
    RecycleSpace,
    harvest_space,
    recycled_sequence,
)
from .status import CGStatus

__all__ = ["BasisConfig", "CGBatchResult", "CGCheckpoint", "CGResult",
           "CGStatus", "DF64CGResult", "HarvestError",
           "RecycleMismatch", "RecycleSpace", "cg", "cg_df64",
           "cg_many", "harvest_space", "recycled_sequence", "solve",
           "solve_many", "stack_columns"]
