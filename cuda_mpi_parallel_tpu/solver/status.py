"""Typed solver statuses.

The reference has no notion of solver status: it prints "Success"
(``CUDACG.cu:365``) whether CG converged or silently hit maxit, and divides
by p.Ap with no breakdown check (``:311``, SURVEY quirks Q4/Q7).  The new
framework surfaces these as a typed status carried through the jitted solve
as a device scalar (an IntEnum value, so it can live inside ``lax.while_loop``
state and cross ``jit`` boundaries).
"""
from __future__ import annotations

import enum


class CGStatus(enum.IntEnum):
    """Outcome of a CG solve (device-scalar friendly int codes).

    Codes 0-2 are produced ON DEVICE by the solvers.  Codes 3-4 are
    HOST-SIDE refinements of MAXITER produced by the flight-recorder
    health diagnostics (``telemetry.health.classify_trace``): the
    solver cannot distinguish "budget too small" from "stalled" or
    "moving away" without the recorded trace, and the refinement must
    never perturb the compiled loop - so it lives off-device.
    """

    CONVERGED = 0     # ||r|| dropped below the tolerance
    MAXITER = 1       # iteration budget exhausted (reference: silent "Success")
    BREAKDOWN = 2     # non-finite recurrence scalar (e.g. p.Ap == 0 division)
    STAGNATED = 3     # trace verdict: residual decay flatlined above tol
    DIVERGED = 4      # trace verdict: residual grew away from its minimum

    def describe(self) -> str:
        return {
            CGStatus.CONVERGED: "converged",
            CGStatus.MAXITER: "maximum iterations reached without convergence",
            CGStatus.BREAKDOWN: (
                "numerical breakdown: a non-finite recurrence scalar "
                "(NaN/Inf in ||r||^2 or p.Ap - corrupted input data, "
                "a poisoned halo payload, or overflow) or a non-SPD "
                "preconditioner (r.Mr <= 0 with r != 0).  This is the "
                "PROBLEM's fault, not the engine's: the solve exited "
                "typed within one check_every block of the poisoned "
                "step (result.iterations names it); see the "
                "solve_fault event, and robust.solve_with_recovery "
                "for bounded restart"),
            CGStatus.STAGNATED: (
                "stagnated: residual decay flatlined above the "
                "tolerance (attainable-accuracy floor or lost "
                "orthogonality; see the solve_health event)"),
            CGStatus.DIVERGED: (
                "diverged: residual grew away from its recorded "
                "minimum (indefinite operator or preconditioner)"),
        }[self]
