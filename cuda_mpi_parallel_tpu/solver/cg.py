"""Conjugate-gradient solver: one jitted function, zero host round-trips.

This is the framework's core, rebuilt TPU-first from the reference's hot loop
(``CUDACG.cu:269-352``).  The reference's structure - a host-side ``for`` that
per iteration issues 8 library launches, 1 ``cudaMalloc``, and 2 *blocking*
device->host scalar reductions (``cublasDdot`` ``:304``, ``cublasDnrm2``
``:328``), with alpha/beta computed in host arithmetic (``:311,336-339``) -
is exactly what a TPU design must eliminate.  Here the entire solve is a
single ``lax.while_loop`` inside ``jit``:

* the convergence predicate evaluates **on device** every iteration (same
  check-every-iteration semantics as ``CUDACG.cu:333``, for free);
* all BLAS-1 work fuses into a few XLA fusions per iteration;
* recurrence scalars (rho, alpha, beta) are 0-d device arrays that never
  leave HBM;
* under ``shard_map`` the same body runs row-partitioned with the two inner
  products becoming ``lax.psum`` over ICI (``axis_name`` parameter) - the
  TPU-native stand-in for the MPI_Allreduce the reference's name promises.

Reference-parity semantics preserved deliberately:

* default ``tol=1e-7`` **absolute** on ||r||_2 (``CUDACG.cu:245,333`` - the
  comment at ``:238`` says "relative" but the code is absolute, quirk Q3);
  a relative tolerance is available via ``rtol``;
* default ``maxiter=2000`` (``:244``);
* x0 = 0 fast path: r0 = b, p0 = b as plain copies, no initial SpMV
  (``:247-259``); nonzero x0 takes the general r0 = b - A@x0 path the
  reference lacks;
* iteration-2 p.Ap < 0 on the 3x3 oracle system (indefinite matrix, quirk
  Q1) is *recorded* (``indefinite`` flag) but does not abort, so the oracle
  trajectory (3 iterations to ||r|| ~ 8e-15) is reproduced exactly.

Divergences from the reference (all improvements, see SURVEY quirks):

* no per-iteration workspace allocation (Q2 - XLA plans buffers once);
* non-finite scalars stop the loop with ``CGStatus.BREAKDOWN`` instead of
  iterating on NaNs (Q4);
* iteration count, final residual, and an optional per-iteration residual
  history are returned (Q7 - the reference reports neither);
* optional Jacobi (or any SPD) preconditioner M ~ A^-1 (BASELINE config #3).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..models.operators import IdentityOperator, LinearOperator
from ..ops import blas1
from .status import CGStatus


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x", "r", "p", "rho", "rr", "nrm0", "k", "indefinite"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class CGCheckpoint:
    """Complete CG recurrence state: resuming from it continues the *exact*
    trajectory (same iterates bit-for-bit), unlike a restart from x alone.

    The reference has no checkpointing - its solver state lives only in
    device memory for the life of the process (SURVEY SS5); long N=256^3
    runs need save/resume (see ``utils/checkpoint.py``).
    """

    x: jax.Array
    r: jax.Array
    p: jax.Array
    rho: jax.Array
    rr: jax.Array
    nrm0: jax.Array        # ||r0|| of the ORIGINAL solve (rtol threshold)
    k: jax.Array           # iterations completed so far
    indefinite: jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x", "iterations", "residual_norm", "converged", "status",
                 "indefinite", "residual_history", "checkpoint"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class CGResult:
    """Everything the reference never reported (SURVEY quirk Q7)."""

    x: jax.Array                # solution estimate
    iterations: jax.Array       # number of CG iterations performed
    residual_norm: jax.Array    # final ||r||_2
    converged: jax.Array        # bool: residual_norm < threshold
    status: jax.Array           # CGStatus int code
    indefinite: jax.Array       # bool: p.Ap <= 0 was observed (quirk Q1)
    residual_history: Optional[jax.Array]  # (maxiter+1,) ||r|| trace or None
    checkpoint: Optional[CGCheckpoint] = None  # set when return_checkpoint

    def status_enum(self) -> CGStatus:
        return CGStatus(int(self.status))


class _CGState(NamedTuple):
    k: jax.Array
    x: jax.Array
    r: jax.Array
    p: jax.Array
    rho: jax.Array        # r . z   (== ||r||^2 when unpreconditioned)
    rr: jax.Array         # ||r||^2 (convergence is checked on r, not z)
    indefinite: jax.Array
    history: jax.Array    # (maxiter+1,) or (0,) when not recording


def cg(
    a: LinearOperator,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    record_history: bool = False,
    axis_name: Optional[str] = None,
    resume_from: Optional[CGCheckpoint] = None,
    return_checkpoint: bool = False,
    iter_cap=None,
) -> CGResult:
    """Solve A x = b by (preconditioned) conjugate gradients.

    Args:
      a: SPD linear operator (any ``LinearOperator``; also accepts a raw
        2-D array, wrapped as dense).
      b: right-hand side, shape ``(n,)`` (local shard inside ``shard_map``).
      x0: initial guess; ``None`` means x0 = 0 and takes the reference's
        copy-only init fast path (``CUDACG.cu:247-259``).
      tol: absolute tolerance on ||r||_2 (reference semantics, quirk Q3).
      rtol: additional relative tolerance; convergence threshold is
        ``max(tol, rtol * ||r0||)``.
      maxiter: iteration cap (static - sizes the history buffer).
      m: optional preconditioner applying M^-1 (e.g.
        ``JacobiPreconditioner``); ``None`` = unpreconditioned.
      record_history: if True, return the per-iteration ||r|| trace.
      axis_name: mesh axis for row-partitioned execution; inner products
        become ``lax.psum`` over this axis.  ``None`` = single device.
      resume_from: a ``CGCheckpoint`` from a previous (partial) solve;
        continues the exact trajectory.  ``maxiter`` remains the TOTAL
        iteration cap (checkpoint ``k`` counts against it).
      return_checkpoint: if True, ``result.checkpoint`` carries the full
        recurrence state for later resumption.
      iter_cap: optional *traced* iteration bound <= maxiter.  Segmented
        runs vary this instead of ``maxiter`` (which is static and would
        recompile); see ``utils/checkpoint.solve_resumable``.

    The function is pure and traceable: call it under ``jit`` (or use
    ``solve()`` which jits for you).
    """
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    if axis_name is None and a.shape[1] != b.shape[0]:
        raise ValueError(f"operator shape {a.shape} does not match rhs "
                         f"shape {b.shape}")
    preconditioned = m is not None
    if m is None:
        m = IdentityOperator(dim=b.shape[0],
                             _dtype_name=jnp.dtype(b.dtype).name)

    dot = partial(blas1.dot, axis_name=axis_name)

    if resume_from is not None and x0 is not None:
        raise ValueError("pass either x0 or resume_from, not both: a "
                         "checkpoint carries its own iterate")
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)

    if resume_from is not None:
        x, r, p0 = resume_from.x, resume_from.r, resume_from.p
        rho0, rr0 = resume_from.rho, resume_from.rr
        nrm0 = resume_from.nrm0
        k0 = resume_from.k
        indef0 = resume_from.indefinite
    else:
        if x0 is None:
            x = jnp.zeros_like(b)
            r = b  # r0 = b - A@0 = b: the reference's copy-only init (:248)
        else:
            x = jnp.asarray(x0, b.dtype)
            r = b - a @ x

        # Unpreconditioned: z == r, so rho == rr and one reduction (one psum
        # over ICI in the distributed case) suffices per iteration.
        rr0 = dot(r, r)
        if preconditioned:
            z = m @ r
            rho0 = dot(r, z)
        else:
            z, rho0 = r, rr0
        p0 = z
        nrm0 = jnp.sqrt(rr0)
        k0 = jnp.zeros((), jnp.int32)
        indef0 = jnp.zeros((), jnp.bool_)

    threshold = jnp.maximum(jnp.asarray(tol, b.dtype),
                            jnp.asarray(rtol, b.dtype) * nrm0)
    thresh_sq = threshold * threshold

    if record_history:
        history = jnp.full((maxiter + 1,), jnp.nan, dtype=b.dtype)
        history = history.at[k0].set(jnp.sqrt(rr0))
    else:
        history = jnp.zeros((0,), dtype=b.dtype)

    state = _CGState(
        k=k0,
        x=x, r=r, p=p0,
        rho=rho0, rr=rr0,
        indefinite=indef0,
        history=history,
    )

    def cond(s: _CGState) -> jax.Array:
        unconverged = s.rr >= thresh_sq
        # rr == 0 means the system is solved exactly; iterating further
        # would divide 0/0 (p = 0 => p.Ap = 0).
        nontrivial = s.rr > 0
        healthy = jnp.isfinite(s.rr) & jnp.isfinite(s.rho)
        return (s.k < maxiter) & (s.k < cap) & unconverged & nontrivial \
            & healthy

    def body(s: _CGState) -> _CGState:
        ap = a @ s.p
        p_ap = dot(s.p, ap)                       # cublasDdot :304 -> psum
        alpha = s.rho / p_ap                      # host arithmetic :311 -> device
        x = blas1.axpy(alpha, s.p, s.x)           # :314
        r = blas1.axpy(-alpha, ap, s.r)           # :320-321
        rr = dot(r, r)                            # cublasDnrm2 :328 -> psum
        if preconditioned:
            z = m @ r
            rho = dot(r, z)
        else:
            z, rho = r, rr
        beta = rho / s.rho                        # :336-339
        p = blas1.xpby(z, beta, s.p)              # Dscal :342 + Daxpy :347, fused
        k = s.k + 1
        history = s.history
        if record_history:
            history = history.at[k].set(jnp.sqrt(rr))
        return _CGState(
            k=k, x=x, r=r, p=p, rho=rho, rr=rr,
            indefinite=s.indefinite | (p_ap <= 0),
            history=history,
        )

    final = lax.while_loop(cond, body, state)

    nrm = jnp.sqrt(final.rr)
    converged = (final.rr < thresh_sq) | (final.rr == 0)
    breakdown = ~(jnp.isfinite(final.rr) & jnp.isfinite(final.rho))
    status = jnp.where(
        converged,
        jnp.int32(CGStatus.CONVERGED),
        jnp.where(breakdown, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)),
    )
    checkpoint = None
    if return_checkpoint:
        checkpoint = CGCheckpoint(
            x=final.x, r=final.r, p=final.p, rho=final.rho, rr=final.rr,
            nrm0=nrm0, k=final.k, indefinite=final.indefinite)
    return CGResult(
        x=final.x,
        iterations=final.k,
        residual_norm=nrm,
        converged=converged,
        status=status,
        indefinite=final.indefinite,
        residual_history=final.history if record_history else None,
        checkpoint=checkpoint,
    )


def _as_operator(a) -> LinearOperator:
    from ..models.operators import DenseOperator

    arr = jnp.asarray(a)
    if arr.ndim != 2:
        raise ValueError(f"expected a 2-D matrix or LinearOperator, got "
                         f"ndim={arr.ndim}")
    return DenseOperator(a=arr)


@partial(jax.jit, static_argnames=("maxiter", "record_history", "axis_name",
                                   "return_checkpoint"))
def _solve_jit(a, b, x0, tol, rtol, maxiter, m, record_history, axis_name,
               resume_from, return_checkpoint, iter_cap):
    return cg(a, b, x0, tol=tol, rtol=rtol, maxiter=maxiter, m=m,
              record_history=record_history, axis_name=axis_name,
              resume_from=resume_from, return_checkpoint=return_checkpoint,
              iter_cap=iter_cap)


def solve(
    a,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    record_history: bool = False,
    resume_from: Optional[CGCheckpoint] = None,
    return_checkpoint: bool = False,
    iter_cap: Optional[int] = None,
) -> CGResult:
    """Jitted single-call entry point: compile once per (operator-structure,
    shape, maxiter) and reuse - the whole solve is one XLA executable.

    ``tol``/``rtol``/``iter_cap`` are passed as device scalars so sweeping
    them does not recompile.
    """
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    tol_a = jnp.asarray(tol, b.dtype)
    rtol_a = jnp.asarray(rtol, b.dtype)
    cap_a = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    return _solve_jit(a, b, x0, tol_a, rtol_a, maxiter, m, record_history,
                      None, resume_from, return_checkpoint, cap_a)
