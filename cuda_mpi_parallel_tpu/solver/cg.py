"""Conjugate-gradient solver: one jitted function, zero host round-trips.

This is the framework's core, rebuilt TPU-first from the reference's hot loop
(``CUDACG.cu:269-352``).  The reference's structure - a host-side ``for`` that
per iteration issues 8 library launches, 1 ``cudaMalloc``, and 2 *blocking*
device->host scalar reductions (``cublasDdot`` ``:304``, ``cublasDnrm2``
``:328``), with alpha/beta computed in host arithmetic (``:311,336-339``) -
is exactly what a TPU design must eliminate.  Here the entire solve is a
single ``lax.while_loop`` inside ``jit``:

* the convergence predicate evaluates **on device** every iteration (same
  check-every-iteration semantics as ``CUDACG.cu:333``, for free);
* all BLAS-1 work fuses into a few XLA fusions per iteration;
* recurrence scalars (rho, alpha, beta) are 0-d device arrays that never
  leave HBM;
* under ``shard_map`` the same body runs row-partitioned with the two inner
  products becoming ``lax.psum`` over ICI (``axis_name`` parameter) - the
  TPU-native stand-in for the MPI_Allreduce the reference's name promises.

Reference-parity semantics preserved deliberately:

* default ``tol=1e-7`` **absolute** on ||r||_2 (``CUDACG.cu:245,333`` - the
  comment at ``:238`` says "relative" but the code is absolute, quirk Q3);
  a relative tolerance is available via ``rtol``;
* default ``maxiter=2000`` (``:244``);
* x0 = 0 fast path: r0 = b, p0 = b as plain copies, no initial SpMV
  (``:247-259``); nonzero x0 takes the general r0 = b - A@x0 path the
  reference lacks;
* iteration-2 p.Ap < 0 on the 3x3 oracle system (indefinite matrix, quirk
  Q1) is *recorded* (``indefinite`` flag) but does not abort, so the oracle
  trajectory (3 iterations to ||r|| ~ 8e-15) is reproduced exactly.

Divergences from the reference (all improvements, see SURVEY quirks):

* no per-iteration workspace allocation (Q2 - XLA plans buffers once);
* non-finite scalars stop the loop with ``CGStatus.BREAKDOWN`` instead of
  iterating on NaNs (Q4);
* iteration count, final residual, and an optional per-iteration residual
  history are returned (Q7 - the reference reports neither);
* optional Jacobi (or any SPD) preconditioner M ~ A^-1 (BASELINE config #3).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..models.operators import IdentityOperator, LinearOperator
from ..ops import blas1
from .status import CGStatus


def _note_engine(engine: str, method: str, check_every: int,
                 **extra) -> None:
    """Telemetry: record which engine actually runs the solve.  Host-side
    only (an event + a counter); never touches device values, so the
    traced/compiled solve is identical with telemetry on or off.
    ``extra`` rides on the event (not the metric labels - cardinality
    stays bounded)."""
    from ..telemetry import events as _tev
    from ..telemetry.registry import REGISTRY

    REGISTRY.counter(
        "solver_engine_selected_total",
        "dispatches, by engine/method/phase (phase='warmup' = the "
        "CLI's compile dispatch; filter phase='solve' for per-solve "
        "counts)",
        labelnames=("engine", "method", "phase")).inc(
            engine=engine, method=method, phase=_tev.scope_phase())
    _tev.emit("engine_selected", engine=engine, method=method,
              check_every=check_every, **extra)


def _note_rejected(engine: str, reason: str) -> None:
    """Telemetry: a fast path was considered and declined (or an explicit
    engine request failed its eligibility gate)."""
    from ..telemetry import events as _tev
    from ..telemetry.registry import REGISTRY

    REGISTRY.counter(
        "solver_engine_rejected_total",
        "fast-path eligibility rejections, by engine and phase",
        labelnames=("engine", "phase")).inc(
            engine=engine, phase=_tev.scope_phase())
    _tev.emit("eligibility_rejected", engine=engine, reason=reason)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x", "r", "p", "rho", "rr", "nrm0", "k", "indefinite"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class CGCheckpoint:
    """Complete CG recurrence state: resuming from it continues the *exact*
    trajectory (same iterates bit-for-bit), unlike a restart from x alone.

    The reference has no checkpointing - its solver state lives only in
    device memory for the life of the process (SURVEY SS5); long N=256^3
    runs need save/resume (see ``utils/checkpoint.py``).
    """

    x: jax.Array
    r: jax.Array
    p: jax.Array
    rho: jax.Array
    rr: jax.Array
    nrm0: jax.Array        # ||r0|| of the ORIGINAL solve (rtol threshold)
    k: jax.Array           # iterations completed so far
    indefinite: jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("x", "iterations", "residual_norm", "converged", "status",
                 "indefinite", "residual_history", "checkpoint", "flight",
                 "basis"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class CGResult:
    """Everything the reference never reported (SURVEY quirk Q7)."""

    x: jax.Array                # solution estimate
    iterations: jax.Array       # number of CG iterations performed
    residual_norm: jax.Array    # final ||r||_2
    converged: jax.Array        # bool: residual_norm < threshold
    status: jax.Array           # CGStatus int code
    indefinite: jax.Array       # bool: p.Ap <= 0 was observed (quirk Q1)
    residual_history: Optional[jax.Array]  # (maxiter+1,) ||r|| trace or None
    checkpoint: Optional[CGCheckpoint] = None  # set when return_checkpoint
    #: flight-recorder ring buffer (capacity, 4) when a FlightConfig was
    #: passed; decode with telemetry.flight.FlightRecord.from_buffer
    flight: Optional[jax.Array] = None
    #: Krylov-recycling basis ring ``(iterations, vectors)`` when a
    #: recycle.BasisConfig was passed; feed to recycle.harvest_space
    basis: Optional[tuple] = None

    def status_enum(self) -> CGStatus:
        return CGStatus(int(self.status))


class _CGState(NamedTuple):
    k: jax.Array
    x: jax.Array
    r: jax.Array
    p: jax.Array
    rho: jax.Array        # r . z   (== ||r||^2 when unpreconditioned)
    rr: jax.Array         # ||r||^2 (convergence is checked on r, not z)
    indefinite: jax.Array
    history: jax.Array    # (maxiter+1,) or (0,) when not recording


def cg(
    a: LinearOperator,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    record_history: bool = False,
    axis_name: Optional[str] = None,
    resume_from: Optional[CGCheckpoint] = None,
    return_checkpoint: bool = False,
    iter_cap=None,
    check_every: int = 1,
    method: str = "cg",
    compensated: bool = False,
    flight=None,
    fault=None,
    deflate=None,
    basis=None,
) -> CGResult:
    """Solve A x = b by (preconditioned) conjugate gradients.

    Args:
      a: SPD linear operator (any ``LinearOperator``; also accepts a raw
        2-D array, wrapped as dense).
      b: right-hand side, shape ``(n,)`` (local shard inside ``shard_map``).
      x0: initial guess; ``None`` means x0 = 0 and takes the reference's
        copy-only init fast path (``CUDACG.cu:247-259``).
      tol: absolute tolerance on ||r||_2 (reference semantics, quirk Q3).
      rtol: additional relative tolerance; convergence threshold is
        ``max(tol, rtol * ||r0||)``.
      maxiter: iteration cap (static - sizes the history buffer).
      m: optional preconditioner applying M^-1 (e.g.
        ``JacobiPreconditioner``); ``None`` = unpreconditioned.
      record_history: if True, return the per-iteration ||r|| trace.
      axis_name: mesh axis for row-partitioned execution; inner products
        become ``lax.psum`` over this axis.  ``None`` = single device.
      resume_from: a ``CGCheckpoint`` from a previous (partial) solve;
        continues the exact trajectory.  ``maxiter`` remains the TOTAL
        iteration cap (checkpoint ``k`` counts against it).
      return_checkpoint: if True, ``result.checkpoint`` carries the full
        recurrence state for later resumption.
      iter_cap: optional *traced* iteration bound <= maxiter.  Segmented
        runs vary this instead of ``maxiter`` (which is static and would
        recompile); see ``utils/checkpoint.solve_resumable``.
      check_every: evaluate the ``while_loop`` convergence predicate only
        every k iterations (SURVEY SS7 "hard parts": the exact
        check-every-iteration semantics of ``CUDACG.cu:333`` serializes on
        the residual reduction each trip; a k-deep inner ``fori_loop``
        gives XLA k predicate-free iterations to pipeline).  The solve
        proceeds in blocks of k: iterates are identical to
        ``check_every=1``, but up to k-1 extra iterations may run past
        convergence (they further reduce the residual) and the reported
        iteration count lands on the block boundary.
      method: ``"cg"`` (textbook recurrence, the reference's algorithm,
        two reductions per iteration), ``"cg1"`` (Chronopoulos-Gear
        single-reduction CG: algebraically the same iterates, but all
        per-iteration inner products are evaluated at one point and fused
        into ONE collective - halves the per-iteration ICI latency on a
        mesh, at the cost of one extra vector recurrence), or ``"pipecg"``
        (Ghysels-Vanroose pipelined CG: one fused reduction per iteration
        whose inputs are ready BEFORE the iteration's matvec+precond, so
        XLA can overlap the psum with local compute - the strongest
        latency-hiding variant on a mesh, at the cost of three extra
        vector recurrences and mild finite-precision residual drift),
        or ``"minres"`` (Paige-Saunders MINRES, ``solver.minres``: the
        principled solver for symmetric INDEFINITE systems like the
        reference's own hardcoded matrix, quirk Q1; unpreconditioned,
        no checkpoint/resume).
      compensated: use double-float (two-prod / two-sum) inner products
        (``blas1.dot_compensated``) - the f32-storage answer to the
        reference's all-f64 arithmetic (``CUDA_R_64F``, ``CUDACG.cu:216``)
        on hardware with no native f64.
      flight: optional ``telemetry.flight.FlightConfig`` - carry the
        convergence flight recorder (a fixed-size, stride-decimated
        ring of ``(iteration, ||r||^2, alpha, beta)`` rows) in the
        loop state and return it as ``result.flight``.  ``None`` (the
        default) leaves the solve code path - and hence the traced
        jaxpr - UNTOUCHED.  Under ``axis_name`` the recorded scalars
        are the already-psum'd global values, so the buffer is
        replicated across shards.  Works with every ``method`` here
        (cg/cg1/pipecg); ``minres`` has its own recurrence and no
        recorder yet.
      fault: optional ``robust.FaultPlan`` - deterministic chaos
        injection: corrupt the halo payload, the local SpMV output or
        the reduction scalar at a chosen iteration/shard, in-trace via
        ``lax.cond`` (the fault fires inside the compiled while_loop;
        the health predicate then exits with ``CGStatus.BREAKDOWN``
        within ``check_every`` iterations).  ``None`` (the default)
        leaves the traced jaxpr bit-identical to a call that never
        mentions injection.  ``method="cg"`` only - the chaos harness
        drills the textbook recurrence.
      deflate: optional ``recycle.RecycleSpace`` - Krylov-recycling
        deflation (``solver.recycle``): at entry the recycled space's
        component of the error is solved exactly
        (``x0 += W (W^T A W)^{-1} W^T r0``) and every new search
        direction is projected against ``A W``, so the effective
        spectrum CG sees excludes the harvested extreme Ritz values.
        Under ``axis_name`` the per-iteration ``(k,)``-wide
        ``(AW)^T z`` reduction FUSES into the residual-norm psum - the
        per-iteration collective count is unchanged.  ``None`` (the
        default) leaves the traced jaxpr bit-identical.
        ``method="cg"`` only; refuses compensated/checkpoint/fault
        composition (the deflated recurrence is its own lane).
      basis: optional ``recycle.BasisConfig`` - carry the Krylov-
        recycling basis ring (last ``capacity`` normalized residuals)
        in the loop state and return it as ``result.basis`` for
        ``recycle.harvest_space``.  Requires ``flight`` (the harvest
        needs the alpha/beta tridiagonal too); ``method="cg"`` only;
        ``None`` compiles to nothing.

    The function is pure and traceable: call it under ``jit`` (or use
    ``solve()`` which jits for you).
    """
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    b = jnp.asarray(b)
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    if axis_name is None and a.shape[1] != b.shape[0]:
        raise ValueError(f"operator shape {a.shape} does not match rhs "
                         f"shape {b.shape}")
    preconditioned = m is not None
    if m is None:
        m = IdentityOperator(dim=b.shape[0],
                             _dtype_name=jnp.dtype(b.dtype).name)

    if resume_from is not None and x0 is not None:
        raise ValueError("pass either x0 or resume_from, not both: a "
                         "checkpoint carries its own iterate")
    cap = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)

    if method not in ("cg", "cg1", "pipecg", "minres"):
        raise ValueError(f"unknown method {method!r}; expected 'cg', 'cg1', "
                         f"'pipecg' or 'minres'")
    if fault is not None:
        if method != "cg":
            raise ValueError(
                f"fault injection (robust.FaultPlan) rides "
                f"method='cg' only (got {method!r}): the chaos "
                f"harness drills the textbook recurrence")
        fault.validate_for_operator(a, n_shards=1 if axis_name is None
                                    else getattr(a, "n_shards", 1))
    if deflate is not None:
        from .recycle import RecycleSpace

        if not isinstance(deflate, RecycleSpace):
            raise TypeError(
                f"deflate must be a solver.recycle.RecycleSpace, got "
                f"{type(deflate).__name__}")
        if method != "cg":
            raise ValueError(
                f"deflate= (Krylov recycling) rides method='cg' only "
                f"(got {method!r}): the projection assumes the "
                f"textbook direction recurrence")
        if compensated or resume_from is not None or return_checkpoint:
            raise ValueError(
                "deflate= does not compose with compensated dots or "
                "checkpoint/resume (the deflated recurrence carries "
                "extra projection state the CGCheckpoint does not)")
        if fault is not None:
            raise ValueError(
                "deflate= with fault injection is unsupported (the "
                "chaos harness drills the undeflated textbook "
                "recurrence)")
    if basis is not None:
        from .recycle import BasisConfig

        if not isinstance(basis, BasisConfig):
            raise TypeError(
                f"basis must be a solver.recycle.BasisConfig, got "
                f"{type(basis).__name__}")
        if method != "cg":
            raise ValueError(
                f"basis= (the recycling harvest ring) rides "
                f"method='cg' only (got {method!r})")
        if flight is None:
            raise ValueError(
                "basis= needs flight= (a stride-1 FlightConfig): the "
                "harvest combines the basis ring with the flight "
                "recorder's alpha/beta tridiagonal")
        if resume_from is not None:
            raise ValueError(
                "basis= with resume_from is unsupported (a resumed "
                "ring would window a spliced trajectory)")
    if method == "minres":
        # the symmetric-INDEFINITE solver (quirk Q1: the reference's own
        # system is indefinite and CG converges on it only by luck)
        if flight is not None:
            raise ValueError(
                "method='minres' does not carry the flight recorder "
                "(its Lanczos recurrence has no CG alpha/beta; use "
                "record_history for its per-iteration trace)")
        if preconditioned:
            raise ValueError(
                "method='minres' supports m=None (preconditioned MINRES "
                "needs an SPD preconditioner and a different inner "
                "product; SPD problems belong on the CG variants)")
        if resume_from is not None or return_checkpoint or compensated:
            raise ValueError(
                "method='minres' does not support checkpoint/resume or "
                "compensated dots")
        from .minres import minres as _minres

        return _minres(a, b, x0, tol=tol, rtol=rtol, maxiter=maxiter,
                       record_history=record_history, axis_name=axis_name,
                       iter_cap=iter_cap, check_every=check_every)
    if method != "cg":
        if resume_from is not None or return_checkpoint:
            raise ValueError(
                "checkpoint/resume requires method='cg': CGCheckpoint "
                "carries the standard recurrence state, not the variants' "
                "extra vectors")
        impl = _cg1 if method == "cg1" else _pipecg
        return impl(a, b, x0, m=m, preconditioned=preconditioned,
                    tol=tol, rtol=rtol, maxiter=maxiter, cap=cap,
                    record_history=record_history, axis_name=axis_name,
                    check_every=check_every, compensated=compensated,
                    flight=flight)

    dot = partial(blas1.dot_compensated if compensated else blas1.dot,
                  axis_name=axis_name)

    if resume_from is not None:
        x, r, p0 = resume_from.x, resume_from.r, resume_from.p
        rho0, rr0 = resume_from.rho, resume_from.rr
        nrm0 = resume_from.nrm0
        k0 = resume_from.k
        indef0 = resume_from.indefinite
    else:
        if x0 is None:
            x = jnp.zeros_like(b)
            r = b  # r0 = b - A@0 = b: the reference's copy-only init (:248)
        else:
            x = jnp.asarray(x0, b.dtype)
            r = b - a @ x
        if deflate is not None:
            # Galerkin entry correction: solve the recycled space's
            # component of the error exactly, so r0 starts orthogonal
            # to W (one extra k-wide psum, at entry only)
            from .recycle import entry_project

            x, r = entry_project(deflate, x, r, axis_name)

        # Unpreconditioned: z == r, so rho == rr and one reduction (one psum
        # over ICI in the distributed case) suffices per iteration.
        rr0 = dot(r, r)
        if preconditioned:
            z = m @ r
            rho0 = dot(r, z)
        else:
            z, rho0 = r, rr0
        if deflate is None:
            p0 = z
        else:
            from .recycle import project_direction

            p0 = project_direction(deflate, z, axis_name)
        nrm0 = jnp.sqrt(rr0)
        k0 = jnp.zeros((), jnp.int32)
        indef0 = jnp.zeros((), jnp.bool_)

    thresh_sq = _threshold_sq(tol, rtol, nrm0, b.dtype)
    history = _history_init(record_history, maxiter, b.dtype, k0,
                            jnp.sqrt(rr0))

    state = _CGState(
        k=k0,
        x=x, r=r, p=p0,
        rho=rho0, rr=rr0,
        indefinite=indef0,
        history=history,
    )

    def cond(s: _CGState) -> jax.Array:
        unconverged = s.rr >= thresh_sq
        # rr == 0 means the system is solved exactly; iterating further
        # would divide 0/0 (p = 0 => p.Ap = 0).
        nontrivial = s.rr > 0
        # rho = r.M^-1 r <= 0 with r != 0 is a preconditioner breakdown
        # (M not SPD): stop now - _safe_div would otherwise freeze the
        # iterate and spin to maxiter.
        healthy = jnp.isfinite(s.rr) & jnp.isfinite(s.rho) & (s.rho > 0)
        return (s.k < maxiter) & (s.k < cap) & unconverged & nontrivial \
            & healthy

    def step_ab(s: _CGState):
        """One CG step; also returns the step's recording scalars
        ``(k, rr, alpha, beta)`` for the flight recorder (unused - and
        traced away - when the recorder is off).  With a ``fault``
        armed, the matvec/reduction is routed through the injection
        helpers - a ``lax.cond`` on ``s.k`` that corrupts the chosen
        site exactly once; ``fault=None`` takes the untouched path."""
        if fault is None:
            ap = a @ s.p
        else:
            ap = fault.apply_matvec(a, s.p, s.k, axis_name)
        p_ap = dot(s.p, ap)                       # cublasDdot :304 -> psum
        if fault is not None:
            p_ap = fault.poison_reduction(p_ap, s.k)
        alpha = _safe_div(s.rho, p_ap)            # host arithmetic :311 -> device
        x = blas1.axpy(alpha, s.p, s.x)           # :314
        r = blas1.axpy(-alpha, ap, s.r)           # :320-321
        if deflate is None:
            rr = dot(r, r)                        # cublasDnrm2 :328 -> psum
            if preconditioned:
                z = m @ r
                rho = dot(r, z)
            else:
                z, rho = r, rr
            beta = _safe_div(rho, s.rho)          # :336-339
            p = blas1.xpby(z, beta, s.p)          # Dscal :342 + Daxpy :347
        else:
            # deflated lane: the (k,)-wide (AW)^T z projection
            # reduction FUSES into the residual-norm psum, so the
            # per-iteration collective COUNT matches the undeflated
            # solve (and the preconditioned lane's rr/rho pair shares
            # the same fused collective)
            from .recycle import chol_solve

            z = m @ r if preconditioned else r
            parts = [jnp.vdot(r, r)]
            if preconditioned:
                parts.append(jnp.vdot(r, z))
            fused = jnp.concatenate([jnp.stack(parts),
                                     deflate.aw.T @ z])
            if axis_name is not None:
                fused = lax.psum(fused, axis_name)
            rr = fused[0]
            rho = fused[1] if preconditioned else rr
            wz = fused[-deflate.k:]
            beta = _safe_div(rho, s.rho)
            p = blas1.xpby(z, beta, s.p) \
                - deflate.w @ chol_solve(deflate.chol, wz)
        k = s.k + 1
        history = s.history
        if record_history:
            history = history.at[k].set(jnp.sqrt(rr))
        return _CGState(
            k=k, x=x, r=r, p=p, rho=rho, rr=rr,
            # s.rr > 0 excludes frozen post-exact-solve steps (p = 0 gives
            # p.Ap = 0, which is not evidence of indefiniteness)
            indefinite=s.indefinite | ((p_ap <= 0) & (s.rr > 0)),
            history=history,
        ), k, rr, alpha, beta

    def step(s: _CGState) -> _CGState:
        return step_ab(s)[0]

    fits = _block_fits(maxiter, cap, check_every)
    if flight is None:
        final = _blocked_while(cond, step, state, check_every, fits)
        fbuf = bbuf = None
    else:
        final, fbuf, bbuf = _flight_while(
            cond, step_ab, state, check_every, fits, flight,
            dtype=b.dtype, k0=k0, rr0=rr0,
            heartbeat_ok=axis_name is None,
            basis=basis, r0=state.r)

    checkpoint = None
    if return_checkpoint:
        checkpoint = CGCheckpoint(
            x=final.x, r=final.r, p=final.p, rho=final.rho, rr=final.rr,
            nrm0=nrm0, k=final.k, indefinite=final.indefinite)
    healthy = jnp.isfinite(final.rr) & jnp.isfinite(final.rho) \
        & ((final.rho > 0) | (final.rr == 0))
    return _package(final, healthy, thresh_sq, record_history, checkpoint,
                    flight_buf=fbuf, basis_buf=bbuf)


def _blocked_while(cond, step, state, check_every: int, block_fits=None):
    """``while cond: step`` with the predicate evaluated every k steps.

    ``check_every > 1`` wraps ``step`` in a k-deep ``fori_loop``, so the
    loop proceeds in blocks of k iterations with one convergence check
    per block (SURVEY SS7: the early-exit ``while_loop`` serializes on
    the residual reduction every trip; on a mesh that is a full ICI
    round-trip before the next iteration may start).  Iterates are
    IDENTICAL to ``check_every=1``; the only difference is that up to
    k-1 extra iterations run past convergence (they keep improving the
    residual; ``step`` must guard its divisions so an exactly-zero
    residual freezes rather than NaNs - see ``_safe_div``).  Masking the
    extra steps instead would need a full-state vector select per inner
    step, which costs more than it saves (measured 3x on v5e).

    ``block_fits(s)`` says whether a whole k-block stays within the
    iteration budget; once it goes false, a per-iteration tail loop
    finishes the remainder so the cap (maxiter / iter_cap) is never
    overshot - only convergence may be.
    """
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    if check_every == 1:
        return lax.while_loop(cond, step, state)

    def block_body(s):
        return lax.fori_loop(0, check_every, lambda _, t: step(t), s)

    def block_cond(s):
        ok = cond(s)
        if block_fits is not None:
            ok = ok & block_fits(s)
        return ok

    state = lax.while_loop(block_cond, block_body, state)
    return lax.while_loop(cond, step, state)   # tail: < k iterations


def _block_fits(maxiter: int, cap: jax.Array, check_every: int):
    """Predicate: a full check_every block stays within maxiter AND cap."""
    def fits(s):
        return (s.k + check_every <= maxiter) & (s.k + check_every <= cap)
    return fits


def _flight_while(cond, step_ab, state, check_every: int, fits, flight,
                  *, dtype, k0, rr0, heartbeat_ok: bool = True,
                  basis=None, r0=None):
    """``_blocked_while`` with the flight-recorder ring buffer threaded
    through the loop carry.

    ``step_ab(s)`` must return ``(new_state, k, rr, alpha, beta)`` -
    the step plus its recording scalars.  The buffer write is one
    masked dynamic-slice update per iteration; everything else about
    the loop (predicates, blocking, tail pass) is EXACTLY
    ``_blocked_while``, so iterates are identical with the recorder on
    or off.  Returns ``(final_state, final_buffer, final_basis)``
    (``final_basis`` is ``None`` unless a ``recycle.BasisConfig`` was
    passed - the Krylov-recycling harvest ring records the new
    state's normalized residual ``s2.r / sqrt(rr)`` beside the flight
    row, same masked-ring-write discipline, nothing in the carry when
    off).

    ``heartbeat_ok=False`` suppresses the optional ``jax.debug``
    heartbeat even when ``flight.heartbeat > 0`` (shard_map bodies -
    one callback per shard per sample would multiply the stream).
    """
    from ..telemetry.flight import (
        flight_init,
        flight_record,
        maybe_heartbeat,
    )

    buf0 = flight_init(flight, dtype, k0, rr0)

    if basis is None:
        def fcond(fs):
            return cond(fs[0])

        def fstep(fs):
            s, buf = fs
            s2, k, rr, alpha, beta = step_ab(s)
            buf = flight_record(buf, flight, k, rr, alpha, beta)
            if heartbeat_ok:
                maybe_heartbeat(flight, k, rr)
            return s2, buf

        ffits = None if fits is None else (lambda fs: fits(fs[0]))
        final, fbuf = _blocked_while(fcond, fstep, (state, buf0),
                                     check_every, ffits)
        return final, fbuf, None

    from .recycle import basis_init, basis_record

    bbuf0 = basis_init(basis, dtype, k0, r0, rr0)

    def bcond(fs):
        return cond(fs[0])

    def bstep(fs):
        s, buf, bbuf = fs
        s2, k, rr, alpha, beta = step_ab(s)
        buf = flight_record(buf, flight, k, rr, alpha, beta)
        bbuf = basis_record(bbuf, basis, k, s2.r, rr)
        if heartbeat_ok:
            maybe_heartbeat(flight, k, rr)
        return s2, buf, bbuf

    bfits = None if fits is None else (lambda fs: fits(fs[0]))
    return _blocked_while(bcond, bstep, (state, buf0, bbuf0),
                          check_every, bfits)


def _threshold_sq(tol, rtol, nrm0: jax.Array, dtype) -> jax.Array:
    """Squared convergence threshold: max(tol, rtol*||r0||)^2 (quirk Q3:
    absolute by default, matching ``CUDACG.cu:333``)."""
    threshold = jnp.maximum(jnp.asarray(tol, dtype),
                            jnp.asarray(rtol, dtype) * nrm0)
    return threshold * threshold


def _history_init(record_history: bool, maxiter: int, dtype, k0, nrm0):
    if record_history:
        history = jnp.full((maxiter + 1,), jnp.nan, dtype=dtype)
        return history.at[k0].set(nrm0)
    return jnp.zeros((0,), dtype=dtype)


def _package(final, healthy: jax.Array, thresh_sq: jax.Array,
             record_history: bool, checkpoint,
             flight_buf=None, basis_buf=None) -> CGResult:
    """Shared epilogue: convergence/breakdown status + CGResult assembly
    (everything the reference never reported, quirks Q4/Q7)."""
    nrm = jnp.sqrt(final.rr)
    converged = (final.rr < thresh_sq) | (final.rr == 0)
    status = jnp.where(
        converged,
        jnp.int32(CGStatus.CONVERGED),
        jnp.where(~healthy, jnp.int32(CGStatus.BREAKDOWN),
                  jnp.int32(CGStatus.MAXITER)),
    )
    return CGResult(
        x=final.x,
        iterations=final.k,
        residual_norm=nrm,
        converged=converged,
        status=status,
        indefinite=final.indefinite,
        residual_history=final.history if record_history else None,
        checkpoint=checkpoint,
        flight=flight_buf,
        basis=basis_buf,
    )


def _safe_div(num: jax.Array, den: jax.Array) -> jax.Array:
    """num / den, but a freeze (0) when both are exactly zero.

    Inside a ``check_every`` block, iterations past an exact solve have
    rho = p.Ap = 0; 0/0 would inject NaN into a state the predicate can
    no longer veto.  A genuine breakdown (den = 0 with num != 0) still
    produces inf -> caught by the health check.
    """
    zero = (num == 0) & (den == 0)
    return jnp.where(zero, jnp.zeros_like(num),
                     num / jnp.where(zero, jnp.ones_like(den), den))


def _make_fdots(compensated: bool, axis_name):
    """Fused-inner-products strategy shared by the cg1/pipecg variants:
    compensated double-float, plain stacked-psum (mesh), or plain vdots
    (single device, where stacking would only hinder XLA fusion)."""
    if compensated:
        def fdots(pairs):
            return blas1.fused_dots_compensated(pairs, axis_name=axis_name)
    elif axis_name is None:
        def fdots(pairs):
            return [jnp.vdot(x, y) for x, y in pairs]
    else:
        def fdots(pairs):
            return list(blas1.fused_dots(pairs, axis_name=axis_name))
    return fdots


def _init_xr(a, b, x0):
    """x0/r0 init shared by all variants (x0=None takes the reference's
    copy-only fast path, CUDACG.cu:247-259)."""
    if x0 is None:
        return jnp.zeros_like(b), b
    x = jnp.asarray(x0, b.dtype)
    return x, b - a @ x


def _variant_cond(maxiter, cap, thresh_sq):
    """Loop predicate shared by cg1/pipecg: unconverged, nontrivial, and
    healthy (gamma = r . M^-1 r <= 0 with r != 0 is a preconditioner
    breakdown - stop now rather than spin to maxiter)."""
    def cond(st) -> jax.Array:
        unconverged = st.rr >= thresh_sq
        nontrivial = st.rr > 0
        healthy = jnp.isfinite(st.rr) & jnp.isfinite(st.gamma) \
            & jnp.isfinite(st.alpha) & (st.gamma > 0)
        return (st.k < maxiter) & (st.k < cap) & unconverged & nontrivial \
            & healthy
    return cond


class _CG1State(NamedTuple):
    k: jax.Array
    x: jax.Array
    r: jax.Array
    p: jax.Array
    s: jax.Array          # A @ p, maintained by recurrence
    gamma: jax.Array      # r . u  (u = M^-1 r; == ||r||^2 unpreconditioned)
    rr: jax.Array         # ||r||^2
    alpha: jax.Array      # step length for the NEXT x/r update
    indefinite: jax.Array
    history: jax.Array


def _cg1(a, b, x0, *, m, preconditioned, tol, rtol, maxiter, cap,
         record_history, axis_name, check_every, compensated,
         flight=None) -> CGResult:
    """Chronopoulos-Gear single-reduction CG.

    Algebraically the textbook recurrence (same alpha_k / beta_k in exact
    arithmetic - tests check trajectory parity against ``method="cg"``),
    rearranged so every per-iteration inner product is evaluated at one
    point and fused into ONE reduction (``blas1.fused_dots`` - one psum
    over ICI where the reference pays two blocking host syncs,
    ``CUDACG.cu:304,328``).  Price: one extra vector recurrence
    ``s = A p`` (an axpy) and +2 vectors of state.
    """
    fdots = _make_fdots(compensated, axis_name)
    x, r = _init_xr(a, b, x0)

    u0 = m @ r if preconditioned else r
    w0 = a @ u0
    if preconditioned:
        rr0, gamma0, delta0 = fdots([(r, r), (r, u0), (w0, u0)])
    else:
        rr0, delta0 = fdots([(r, r), (w0, r)])
        gamma0 = rr0
    alpha0 = _safe_div(gamma0, delta0)
    nrm0 = jnp.sqrt(rr0)

    thresh_sq = _threshold_sq(tol, rtol, nrm0, b.dtype)
    k0 = jnp.zeros((), jnp.int32)
    history = _history_init(record_history, maxiter, b.dtype, k0, nrm0)

    state = _CG1State(
        k=k0,
        x=x, r=r, p=u0, s=w0,
        gamma=gamma0, rr=rr0, alpha=alpha0,
        indefinite=(delta0 <= 0) & (rr0 > 0),
        history=history,
    )

    cond = _variant_cond(maxiter, cap, thresh_sq)

    def step_ab(st: _CG1State):
        # recording scalars: st.alpha is THIS step's step length (the
        # Chronopoulos-Gear carry holds alpha one step ahead), beta is
        # this step's rho_k/rho_{k-1} - the same (alpha_k, beta_k)
        # pairing as the textbook recurrence, so the CG-Lanczos
        # reconstruction in telemetry.health applies unchanged
        x = blas1.axpy(st.alpha, st.p, st.x)
        r = blas1.axpy(-st.alpha, st.s, st.r)
        u = m @ r if preconditioned else r
        w = a @ u
        if preconditioned:
            rr, gamma, delta = fdots([(r, r), (r, u), (w, u)])
        else:
            rr, delta = fdots([(r, r), (w, r)])
            gamma = rr
        beta = _safe_div(gamma, st.gamma)
        denom = delta - beta * _safe_div(gamma, st.alpha)  # == p_new . A p_new
        alpha = _safe_div(gamma, denom)
        p = blas1.xpby(u, beta, st.p)
        s_vec = blas1.xpby(w, beta, st.s)
        k = st.k + 1
        history = st.history
        if record_history:
            history = history.at[k].set(jnp.sqrt(rr))
        return _CG1State(
            k=k, x=x, r=r, p=p, s=s_vec,
            gamma=gamma, rr=rr, alpha=alpha,
            # rr > 0 excludes frozen post-exact-solve steps (see _CGState)
            indefinite=st.indefinite | ((denom <= 0) & (rr > 0)),
            history=history,
        ), k, rr, st.alpha, beta

    def step(st: _CG1State) -> _CG1State:
        return step_ab(st)[0]

    fits = _block_fits(maxiter, cap, check_every)
    if flight is None:
        final = _blocked_while(cond, step, state, check_every, fits)
        fbuf = None
    else:
        final, fbuf, _ = _flight_while(
            cond, step_ab, state, check_every, fits, flight,
            dtype=b.dtype, k0=k0, rr0=rr0,
            heartbeat_ok=axis_name is None)

    healthy = jnp.isfinite(final.rr) & jnp.isfinite(final.gamma) \
        & jnp.isfinite(final.alpha) & ((final.gamma > 0) | (final.rr == 0))
    return _package(final, healthy, thresh_sq, record_history, None,
                    flight_buf=fbuf)


def _replace_cadence(dtype) -> int:
    """Pipelined-CG residual-replacement cadence.

    The recurrence drift grows fast enough in f32 that replacement must
    fire BEFORE the drift is large - once the recurrence residual and the
    true residual have separated, replacing no longer rescues the solve
    (measured on 128^2 Poisson f32: no replacement stalls at true
    ||r||/||r0|| ~ 2e-3, cadence 64 also stalls ~2e-3, cadence 16
    converges to ~3e-6).  The ~3-matvec recompute is ~19% extra matvec
    work at cadence 16 - the price of f32 pipelining.  In f64 drift is
    slow and a long cadence keeps the overhead negligible.
    """
    return 16 if jnp.dtype(dtype).itemsize <= 4 else 512


class _PipeCGState(NamedTuple):
    k: jax.Array
    x: jax.Array
    r: jax.Array
    u: jax.Array          # M^-1 r
    w: jax.Array          # A u
    p: jax.Array
    s: jax.Array          # A p
    q: jax.Array          # M^-1 s
    z: jax.Array          # A q
    gamma: jax.Array      # r . u
    rr: jax.Array         # ||r||^2
    alpha: jax.Array
    indefinite: jax.Array
    history: jax.Array


def _pipecg(a, b, x0, *, m, preconditioned, tol, rtol, maxiter, cap,
            record_history, axis_name, check_every, compensated,
            flight=None) -> CGResult:
    """Ghysels-Vanroose pipelined CG (same iterates as ``"cg"`` in exact
    arithmetic; tests check trajectory parity).

    The defining property: each iteration's ONE fused reduction consumes
    only vectors from the previous iteration's updates (r, u, w), while
    the iteration's matvec ``n = A(M^-1 w)`` has no data dependence on the
    reduction - so on a mesh, XLA can overlap the psum's ICI latency with
    the local stencil/SpMV compute.  The reference, for contrast, pays two
    *blocking host* syncs per iteration with nothing overlapped
    (``CUDACG.cu:304,328``).  Cost: three extra vector recurrences
    (s = A p, q = M^-1 p-analog, z = A q) and the usual pipelined-CG
    finite-precision residual drift (the recurrence r drifts from
    b - A x over many iterations; tightest-tolerance solves should prefer
    method='cg').

    Drift is bounded by periodic RESIDUAL REPLACEMENT (Ghysels-Vanroose
    SS4): every ``_replace_cadence(dtype)`` iterations the derived vectors are
    recomputed from definition (r = b - A x, u = M r, w = A u, s = A p,
    q = M s, z = A q) under a ``lax.cond``.  Without it, f32 pipecg
    stagnates far above the tolerance on ill-conditioned systems
    (measured: 512^2 Poisson f32 never reached rtol 1e-5; with
    replacement it matches cg's iteration count).
    """
    fdots = _make_fdots(compensated, axis_name)
    x, r = _init_xr(a, b, x0)

    u0 = m @ r if preconditioned else r
    w0 = a @ u0
    if preconditioned:
        rr0, gamma0, delta0 = fdots([(r, r), (r, u0), (w0, u0)])
    else:
        rr0, delta0 = fdots([(r, r), (w0, r)])
        gamma0 = rr0
    m0 = m @ w0 if preconditioned else w0
    n0 = a @ m0
    alpha0 = _safe_div(gamma0, delta0)
    nrm0 = jnp.sqrt(rr0)

    thresh_sq = _threshold_sq(tol, rtol, nrm0, b.dtype)
    k0 = jnp.zeros((), jnp.int32)
    history = _history_init(record_history, maxiter, b.dtype, k0, nrm0)

    state = _PipeCGState(
        k=k0, x=x, r=r, u=u0, w=w0,
        p=u0, s=w0, q=m0, z=n0,
        gamma=gamma0, rr=rr0, alpha=alpha0,
        indefinite=(delta0 <= 0) & (rr0 > 0),
        history=history,
    )

    cond = _variant_cond(maxiter, cap, thresh_sq)

    def replace(x, p):
        """Recompute every derived vector from definition (drift reset)."""
        r = b - a @ x
        u = m @ r if preconditioned else r
        w = a @ u
        s = a @ p
        q = m @ s if preconditioned else s
        z = a @ q
        return r, u, w, s, q, z

    def step_ab(st: _PipeCGState):
        # recording scalars mirror _cg1: st.alpha is this step's step
        # length, beta this step's gamma ratio
        x = blas1.axpy(st.alpha, st.p, st.x)
        r = blas1.axpy(-st.alpha, st.s, st.r)
        u = blas1.axpy(-st.alpha, st.q, st.u)
        w = blas1.axpy(-st.alpha, st.z, st.w)
        k = st.k + 1
        # periodic residual replacement bounds the recurrence drift; the
        # replaced (s, q, z) feed this step's beta-updates below so the
        # direction-vector recurrences are reset too
        r, u, w, s_old, q_old, z_old = lax.cond(
            (k % _replace_cadence(b.dtype)) == 0,
            lambda: replace(x, st.p),
            lambda: (r, u, w, st.s, st.q, st.z))
        # The fused reduction below depends only on (r, u, w); mv/precond
        # depend only on w - no dependence either way, so the collective
        # overlaps with the matvec on a mesh.
        if preconditioned:
            rr, gamma, delta = fdots([(r, r), (r, u), (w, u)])
            mm = m @ w
        else:
            rr, delta = fdots([(r, r), (w, r)])
            gamma = rr
            mm = w
        n = a @ mm
        beta = _safe_div(gamma, st.gamma)
        denom = delta - beta * _safe_div(gamma, st.alpha)
        alpha = _safe_div(gamma, denom)
        p = blas1.xpby(u, beta, st.p)
        s = blas1.xpby(w, beta, s_old)
        q = blas1.xpby(mm, beta, q_old)
        z = blas1.xpby(n, beta, z_old)
        history = st.history
        if record_history:
            history = history.at[k].set(jnp.sqrt(rr))
        return _PipeCGState(
            k=k, x=x, r=r, u=u, w=w, p=p, s=s, q=q, z=z,
            gamma=gamma, rr=rr, alpha=alpha,
            indefinite=st.indefinite | ((denom <= 0) & (rr > 0)),
            history=history,
        ), k, rr, st.alpha, beta

    def step(st: _PipeCGState) -> _PipeCGState:
        return step_ab(st)[0]

    fits = _block_fits(maxiter, cap, check_every)
    if flight is None:
        final = _blocked_while(cond, step, state, check_every, fits)
        fbuf = None
    else:
        final, fbuf, _ = _flight_while(
            cond, step_ab, state, check_every, fits, flight,
            dtype=b.dtype, k0=k0, rr0=rr0,
            heartbeat_ok=axis_name is None)

    healthy = jnp.isfinite(final.rr) & jnp.isfinite(final.gamma) \
        & jnp.isfinite(final.alpha) & ((final.gamma > 0) | (final.rr == 0))
    return _package(final, healthy, thresh_sq, record_history, None,
                    flight_buf=fbuf)


def _as_operator(a) -> LinearOperator:
    from ..models.operators import DenseOperator, ShiftELLDF64Matrix

    if isinstance(a, ShiftELLDF64Matrix):
        raise TypeError(
            "ShiftELLDF64Matrix is a double-float operator: use "
            "solver.df64.cg_df64, not the f32 solve path")
    arr = jnp.asarray(a)
    if arr.ndim != 2:
        raise ValueError(f"expected a 2-D matrix or LinearOperator, got "
                         f"ndim={arr.ndim}")
    return DenseOperator(a=arr)


@partial(jax.jit, static_argnames=("maxiter", "record_history", "axis_name",
                                   "return_checkpoint", "check_every",
                                   "method", "compensated", "flight",
                                   "fault", "basis"))
def _solve_jit(a, b, x0, tol, rtol, maxiter, m, record_history, axis_name,
               resume_from, return_checkpoint, iter_cap, check_every,
               method, compensated, flight, fault=None, deflate=None,
               basis=None):
    return cg(a, b, x0, tol=tol, rtol=rtol, maxiter=maxiter, m=m,
              record_history=record_history, axis_name=axis_name,
              resume_from=resume_from, return_checkpoint=return_checkpoint,
              iter_cap=iter_cap, check_every=check_every, method=method,
              compensated=compensated, flight=flight, fault=fault,
              deflate=deflate, basis=basis)


def solve(
    a,
    b: jax.Array,
    x0: Optional[jax.Array] = None,
    *,
    tol: float = 1e-7,
    rtol: float = 0.0,
    maxiter: int = 2000,
    m: Optional[LinearOperator] = None,
    record_history: bool = False,
    resume_from: Optional[CGCheckpoint] = None,
    return_checkpoint: bool = False,
    iter_cap: Optional[int] = None,
    check_every: int = 1,
    method: str = "cg",
    compensated: bool = False,
    engine: str = "general",
    flight=None,
    fault=None,
    deflate=None,
    basis=None,
) -> CGResult:
    """Jitted single-call entry point: compile once per (operator-structure,
    shape, maxiter) and reuse - the whole solve is one XLA executable.

    ``tol``/``rtol``/``iter_cap`` are passed as device scalars so sweeping
    them does not recompile.

    ``flight``: optional ``telemetry.flight.FlightConfig`` (see ``cg``).
    Carried by the general and streaming engines; the VMEM-resident
    engine records at check-block granularity only (its in-kernel SMEM
    trace), so ``engine="auto"`` skips the resident path when a
    recorder is requested - same never-silently-change-granularity rule
    as ``record_history`` - and an explicit ``engine="resident"`` with
    ``flight`` raises (use ``cg_resident(record_history=True)`` +
    ``FlightRecord.from_history`` for the block-granular record).

    ``engine``: ``"general"`` (default - the ``lax.while_loop`` solver,
    every operator/feature), ``"resident"`` (the single-pallas-kernel
    VMEM-resident engine, ``solver.resident`` - raises if the problem is
    outside its scope), ``"streaming"`` (the fused-iteration
    HBM-streaming engine, ``solver.streaming`` - f32 stencils of ANY
    slab-supported size, the 256^3 north-star path; raises if out of
    scope), or ``"auto"`` (on a compiled TPU backend: resident when
    eligible, else streaming when eligible, else general).
    """
    if engine not in ("general", "auto", "resident", "streaming"):
        raise ValueError(f"unknown engine {engine!r}; expected 'general', "
                         f"'auto', 'resident' or 'streaming'")
    if not isinstance(a, LinearOperator):
        a = _as_operator(a)
    if deflate is not None or basis is not None:
        # Krylov recycling rides the general while_loop recurrence
        # (the one carrying the projections / the basis ring); the
        # one-kernel engines refuse, auto skips them.
        feature = "deflate= (Krylov recycling)" if deflate is not None \
            else "basis= (the recycling harvest ring)"
        if engine in ("resident", "streaming"):
            _note_rejected(engine, f"{feature} requested (the "
                           "one-kernel engines carry neither the "
                           "projection nor the basis ring)")
            raise ValueError(
                f"engine={engine!r} does not support {feature}; use "
                f"engine='general' (or 'auto', which keeps recycling "
                f"solves on the general engine)")
        if deflate is not None:
            from .recycle import check_space

            check_space(deflate, a)     # typed RecycleMismatch
    if engine in ("auto", "resident"):
        from ..models.operators import _pallas_interpret
        from .resident import cg_resident, resident_eligible

        # Cheap backend gate first: resident_eligible's Chebyshev scale
        # comparison is a device sync, pointless off-TPU under "auto".
        # Explicit engine="resident" accepts record_history (the kernel
        # emits a check-block-granular trace); "auto" keeps routing
        # history requests to the general solver, whose trace is
        # per-iteration - auto must never silently change a result's
        # meaning.
        if engine == "resident" and fault is not None:
            _note_rejected("resident", "fault injection requested "
                           "(the one-kernel engine carries no "
                           "injection sites)")
            raise ValueError(
                "engine='resident' does not support fault injection "
                "(robust.FaultPlan arms the general recurrence); use "
                "engine='general'")
        eligible = ((engine == "resident"
                     or jax.default_backend() == "tpu")
                    and flight is None
                    and fault is None
                    and deflate is None and basis is None
                    and resident_eligible(
                        a, b, m, method=method,
                        record_history=(record_history
                                        and engine != "resident"),
                        x0=x0,
                        resume_from=resume_from,
                        return_checkpoint=return_checkpoint,
                        compensated=compensated))
        if engine == "resident" and flight is not None:
            _note_rejected("resident", "flight recorder requested "
                           "(per-iteration; the kernel trace is "
                           "check-block granular)")
            raise ValueError(
                "engine='resident' does not carry the per-iteration "
                "flight recorder (the one-kernel solve keeps its "
                "scalars in SMEM); use cg_resident(record_history="
                "True) + telemetry.flight.FlightRecord.from_history "
                "for the check-block-granular record, or "
                "engine='general'/'streaming' for a stride-decimated "
                "per-iteration one")
        if engine == "resident" and not eligible:
            _note_rejected("resident", "explicit engine='resident' "
                           "failed the eligibility gate")
            raise ValueError(
                "engine='resident' needs a float32 2D/3D stencil whose "
                "CG working set fits VMEM, a float32 rhs, m=None or a "
                "Chebyshev preconditioner built over this operator, "
                "method='cg' (or the unpreconditioned 'cg1'), f32 x0 or "
                "none, and no checkpointing - use engine='general' (or "
                "'auto') otherwise")
        if eligible:
            return cg_resident(a, b, x0, tol=tol, rtol=rtol,
                               maxiter=maxiter, check_every=check_every,
                               iter_cap=iter_cap, m=m,
                               record_history=record_history,
                               method=method,
                               interpret=_pallas_interpret())
        if engine == "auto":
            _note_rejected("resident", "auto: resident_eligible "
                           "returned False")
    if engine in ("auto", "streaming"):
        from ..models.operators import _pallas_interpret
        from .streaming import cg_streaming, streaming_eligible

        if engine == "streaming" and fault is not None:
            _note_rejected("streaming", "fault injection requested "
                           "(the fused-slab engine carries no "
                           "injection sites)")
            raise ValueError(
                "engine='streaming' does not support fault injection "
                "(robust.FaultPlan arms the general recurrence); use "
                "engine='general'")
        eligible = ((engine == "streaming"
                     or jax.default_backend() == "tpu")
                    and fault is None
                    and deflate is None and basis is None
                    and streaming_eligible(
                        a, b, m, method=method, x0=x0,
                        resume_from=resume_from,
                        return_checkpoint=return_checkpoint,
                        compensated=compensated,
                        record_history=record_history))
        if engine == "streaming" and not eligible:
            _note_rejected("streaming", "explicit engine='streaming' "
                           "failed the eligibility gate")
            raise ValueError(
                "engine='streaming' needs a float32 2D/3D stencil "
                "satisfying the slab tiling (2D: nx % 8 == 0, "
                "ny % 128 == 0; 3D: nx % 2 == 0, ny % 8 == 0, "
                "nz % 128 == 0), a float32 rhs, m=None or a Chebyshev "
                "preconditioner built over this operator, method='cg', "
                "and no checkpointing - use engine='general' (or "
                "'auto') otherwise")
        if eligible:
            return cg_streaming(a, b, x0, tol=tol, rtol=rtol,
                                maxiter=maxiter, check_every=check_every,
                                iter_cap=iter_cap, m=m,
                                record_history=record_history,
                                flight=flight,
                                interpret=_pallas_interpret())
        if engine == "auto":
            _note_rejected("streaming", "auto: streaming_eligible "
                           "returned False")
    b = jnp.asarray(b)
    if not jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.result_type(float))
    tol_a = jnp.asarray(tol, b.dtype)
    rtol_a = jnp.asarray(rtol, b.dtype)
    cap_a = jnp.asarray(maxiter if iter_cap is None else iter_cap, jnp.int32)
    _note_engine("general", method, check_every,
                 **({"flight_stride": flight.stride}
                    if flight is not None else {}),
                 **({"fault": fault.fingerprint()}
                    if fault is not None else {}),
                 **({"deflate_k": deflate.k}
                    if deflate is not None else {}))
    return _solve_jit(a, b, x0, tol_a, rtol_a, maxiter, m, record_history,
                      None, resume_from, return_checkpoint, cap_a,
                      check_every, method, compensated, flight,
                      fault=fault, deflate=deflate, basis=basis)


# The many-RHS tier (masked batched CG + block-CG) lives in .many; it
# builds on this module's helpers, so the import must come after they
# are defined.  Re-exported here because solve_many is this module's
# column-stacked sibling of solve().
from .many import CGBatchResult, cg_many, solve_many  # noqa: E402,F401
