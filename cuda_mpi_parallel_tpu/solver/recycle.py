"""Krylov subspace recycling: deflated CG for repeat traffic.

The serving tier solves the SAME operator thousands of times with
fresh right-hand sides (``serve/``, ROADMAP item 2) - the textbook
setting where recycling pays: every CG solve is a Lanczos process in
disguise, so the spectral information it bought (approximate extreme
eigenpairs) can be harvested after the solve and DEFLATED from the
next one, and the service gets measurably faster the longer it runs
(prototype on the committed skewed fixture: 48 -> 46 -> 45 -> 44 -> 43
iterations over five solves; 24^2 Poisson: 83 -> 67 -> 56 -> 55,
against an exact-eigenvector deflation floor of 54).

Three pieces, each riding machinery earlier PRs built:

* **Harvest** (:func:`harvest_space`).  The solve carries a small
  fixed-size **basis ring** (:class:`BasisConfig` - the flight ring's
  sibling: last ``capacity`` normalized residuals, one masked ring
  write per iteration, compiled to NOTHING when off) and the flight
  recorder's alpha/beta columns define the CG-Lanczos tridiagonal
  (``telemetry.health.lanczos_tridiagonal`` - the EXACT
  ``V_w^T A V_w`` of the ring's window, stride-1 enforced loudly).
  Eigenvectors of that small tridiagonal are Ritz-vector
  COEFFICIENTS; combined with the ring they give n-dimensional
  approximate extreme eigenvectors of A.  Harvests ACCUMULATE: passing
  the previous :class:`RecycleSpace` Rayleigh-Ritz-compresses
  ``[W_old | W_window]`` back to ``k`` columns, so repeat solves
  refine the space toward the true extreme invariant subspace
  (GCRO-DR's recycling loop, adapted to CG).
* **Deflated-CG lane** (``cg``/``cg_many`` ``deflate=``).  The
  standard SPD deflation: at entry ``x0 += W (W^T A W)^{-1} W^T r0``
  (a Galerkin solve in the recycled space - the residual starts
  A-orthogonal to W), and every iteration's new direction is projected
  against ``A W``.  Distributed, the per-iteration ``(k,)``-wide
  ``(AW)^T z`` reduction FUSES into the residual-norm psum, so the
  per-iteration collective COUNT is unchanged (comm_cost-asserted).
  ``deflate=None`` leaves the traced jaxpr bit-identical.
* **Serve integration** (``serve.RecyclePolicy``): a per-handle
  ``RecycleSpace`` keyed by the handle fingerprint, harvested from
  early live dispatches, refreshed on a quality schedule, consulted
  automatically with zero API change, and dropped together with the
  handle's compiled solvers when the dist_cg LRU evicts them.

Scope: ``method="cg"`` / ``method="batched"`` recurrences on the
assembled-CSR allgather/gather lanes (plus every single-device
``LinearOperator``).  The ring and the projections cost
``O(capacity * n)`` carry and ``O(n k)`` work per iteration - sized
for the service's "thousands of medium systems", not the 256^3
streaming north star (the one-kernel engines refuse the recorder).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import numpy as np

__all__ = [
    "BASIS_CAPACITY_LIMIT",
    "BasisConfig",
    "DEFAULT_K",
    "HarvestError",
    "HarvestInfo",
    "RecycleMismatch",
    "RecycleSpace",
    "basis_init",
    "basis_init_many",
    "basis_record",
    "basis_record_many",
    "check_space",
    "harvest_space",
    "recycled_sequence",
    "space_layout",
]

#: default recycled-space dimension (columns of W)
DEFAULT_K = 8

#: hard cap on basis-ring capacity: the ring rides the solve carry at
#: ``capacity * n`` elements, so 128 rows keep a 1M-row f32 solve's
#: recorder under 512 MB and a serve-scale (10^3..10^5 rows) one at
#: tens of MB.  Solves longer than the capacity wrap and harvest from
#: the trailing window only (weaker, still convergent - accumulation
#: across solves recovers the lost modes).
BASIS_CAPACITY_LIMIT = 128


class RecycleMismatch(ValueError):
    """A :class:`RecycleSpace` was offered to a solve it does not fit:
    different operator fingerprint or row count.  Typed so callers
    (the serve tier, tests) can refuse wrong-space deflation without
    string matching - a wrong space would not corrupt the ANSWER (the
    projection is algebraically valid for any full-rank W) but it
    would silently waste every projection and could stall
    convergence."""


class HarvestError(ValueError):
    """The basis ring / flight record cannot support a harvest (solve
    too short, decimated record, non-SPD Gram)."""


@dataclasses.dataclass(frozen=True)
class BasisConfig:
    """Static basis-ring configuration (hashable - rides jit static
    args and compiled-solver cache keys, exactly like
    ``FlightConfig``).

    ``capacity``: ring rows of normalized residuals kept in the solve
    carry; once ``capacity * stride`` iterations have run, the oldest
    rows are overwritten (trailing window).
    ``stride``: decimation, flight-ring style.  The ring records at
    any stride, but :func:`harvest_space` REFUSES stride != 1 - the
    Lanczos tridiagonal couples consecutive iterations (see
    ``telemetry.health.lanczos_tridiagonal``).
    ``lane``: which column of a batched (many-RHS) solve the ring
    records (the harvest's Lanczos process must be ONE lane's).
    """

    capacity: int = 32
    stride: int = 1
    lane: int = 0

    def __post_init__(self):
        if self.capacity < 2:
            raise ValueError(
                f"capacity must be >= 2, got {self.capacity}")
        if self.capacity > BASIS_CAPACITY_LIMIT:
            raise ValueError(
                f"capacity {self.capacity} exceeds "
                f"BASIS_CAPACITY_LIMIT={BASIS_CAPACITY_LIMIT} (the "
                f"ring rides the solve carry at capacity * n elements)")
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {self.stride}")
        if self.lane < 0:
            raise ValueError(f"lane must be >= 0, got {self.lane}")

    @classmethod
    def for_solve(cls, maxiter: int, lane: int = 0,
                  limit: int = BASIS_CAPACITY_LIMIT) -> "BasisConfig":
        """Capacity sized so a ``maxiter``-iteration solve never wraps
        (bounded by ``limit``) - the same rule as
        ``FlightConfig.for_solve``."""
        return cls(capacity=max(2, min(maxiter + 1, limit)), lane=lane)


# ---------------------------------------------------------------------------
# the in-loop ring: (iterations, vectors) carried in the solve state


def basis_init(cfg: BasisConfig, dtype, k0, r, rr):
    """Fresh basis ring with the initial residual recorded.  The
    buffer is a ``(its, vecs)`` pair: ``its (capacity,) int32`` slot
    iterations (-1 = never written) and ``vecs (capacity, n)`` rows of
    ``r / ||r||`` (zeros where unwritten - a zero row is inert in
    every downstream matmul, unlike NaN)."""
    import jax.numpy as jnp

    its = jnp.full((cfg.capacity,), -1, jnp.int32)
    vecs = jnp.zeros((cfg.capacity,) + r.shape, dtype)
    return basis_record((its, vecs), cfg, k0, r, rr)


def basis_record(buf, cfg: BasisConfig, k, r, rr, active=None):
    """One masked ring write of the normalized residual - the flight
    ring's write rule (``k % stride == 0`` -> slot
    ``(k // stride) % capacity``), pure device ops, loop-carry
    friendly.  ``rr`` is the (psum'd, global) ``||r||^2`` so the
    stored row is the unit GLOBAL residual's local shard.  ``active``
    (a traced bool) additionally gates the write - a batched solve's
    recorded lane stops writing once it FREEZES, so its frozen
    residual can never wrap the ring and evict the real rows while
    slower batchmates keep iterating."""
    import jax.numpy as jnp

    its, vecs = buf
    k = jnp.asarray(k)
    write = (k % cfg.stride) == 0
    if active is not None:
        write = write & active
    slot = (k // cfg.stride) % cfg.capacity
    inv = jnp.where(rr > 0, 1.0 / jnp.sqrt(rr), 0.0).astype(vecs.dtype)
    row = r.astype(vecs.dtype) * inv
    its = its.at[slot].set(jnp.where(write, k.astype(jnp.int32),
                                     its[slot]))
    vecs = vecs.at[slot].set(jnp.where(write, row, vecs[slot]))
    return its, vecs


def basis_init_many(cfg: BasisConfig, dtype, k0, r, rr):
    """Batched-solve ring init: records lane ``cfg.lane`` of the
    ``(n, k_rhs)`` residual stack (``rr`` per-lane ``(k_rhs,)``)."""
    return basis_init(cfg, dtype, k0, r[:, cfg.lane], rr[cfg.lane])


def basis_record_many(buf, cfg: BasisConfig, k, r, rr, active=None):
    return basis_record(buf, cfg, k, r[:, cfg.lane], rr[cfg.lane],
                        active=active)


# ---------------------------------------------------------------------------
# the recycled space


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("w", "aw", "chol"),
    meta_fields=("n", "k", "layout"),
)
@dataclasses.dataclass(frozen=True)
class RecycleSpace:
    """A harvested deflation space: ``W`` (n x k, orthonormal columns,
    row-partitioned exactly like ``x`` in distributed solves), the
    precomputed ``A W``, and the Cholesky factor of ``W^T A W`` -
    everything the deflated lane's projections consume, with no solve
    of the small system ever re-factorized in the hot loop.

    Registered as a pytree whose META is only the STABLE identity
    ``(n, k, layout)``: a refreshed space with the same shape/layout
    reuses the compiled deflated solver (no retrace per harvest).
    Quality/age live on the companion :class:`HarvestInfo` instead.
    """

    w: object            # (n, k) orthonormal Ritz basis
    aw: object           # (n, k) = A @ W
    chol: object         # (k, k) lower Cholesky of W^T A W
    n: int
    k: int
    layout: str          # operator fingerprint + row count

    def fingerprint(self) -> str:
        return f"{self.layout}:k{self.k}"


@dataclasses.dataclass(frozen=True)
class HarvestInfo:
    """One harvest's quality digest (host-side; JSON-ready)."""

    k: int
    window: int                 # tridiagonal rows the harvest used
    iterations: int             # source solve's iteration count
    ritz: tuple                 # kept Ritz values, ascending
    quality: tuple              # ||A w - theta w|| / |theta| per pair
    accumulated: bool           # previous space was folded in

    def to_json(self) -> dict:
        return {
            "k": self.k, "window": self.window,
            "iterations": self.iterations,
            "ritz_min": float(self.ritz[0]) if self.ritz else None,
            "ritz_max": float(self.ritz[-1]) if self.ritz else None,
            "quality_max": (float(max(self.quality))
                            if self.quality else None),
            "accumulated": self.accumulated,
        }


def _as_linear_operator(a):
    from ..models.operators import LinearOperator

    if isinstance(a, LinearOperator):
        return a
    from .cg import _as_operator

    return _as_operator(a)


#: id-keyed weakref memo of layout tokens: the fingerprint walk is
#: O(nnz) host work, and a deflated dispatch path (solve/solve_many
#: per batch) must not re-hash the whole matrix every call - the memo
#: makes repeat checks on a LIVE operator object O(1).  Dead entries
#: are pruned opportunistically; a fresh operator object (new id)
#: simply recomputes.
_LAYOUT_MEMO: dict = {}


def space_layout(a) -> str:
    """The layout token a space is checked against: the operator's
    mathematical fingerprint (``utils.checkpoint.operator_fingerprint``
    - the serve handle's scheme) plus the row count.  Spaces are
    harvested and stored in the CALLER's global row ordering, so the
    same token serves single-device and every distributed lane (the
    dispatch path applies its own plan permutation/padding to W just
    like it does to b).  Memoized per live operator object (the walk
    is O(nnz); repeat dispatches on one operator pay it once)."""
    import weakref

    from ..utils.checkpoint import operator_fingerprint

    a = _as_linear_operator(a)
    hit = _LAYOUT_MEMO.get(id(a))
    if hit is not None and hit[0]() is a:
        return hit[1]
    token = f"{operator_fingerprint(a)[:12]}:{int(a.shape[0])}"
    try:
        ref = weakref.ref(a)
    except TypeError:
        return token
    if len(_LAYOUT_MEMO) > 256:
        for key in [k for k, (r, _) in _LAYOUT_MEMO.items()
                    if r() is None]:
            _LAYOUT_MEMO.pop(key, None)
    _LAYOUT_MEMO[id(a)] = (ref, token)
    return token


def check_space(space, a) -> None:
    """Typed refusal (never a wrong-space deflation): the space must
    have been harvested from THIS operator."""
    if not isinstance(space, RecycleSpace):
        raise TypeError(
            f"deflate must be a solver.recycle.RecycleSpace, got "
            f"{type(space).__name__}")
    expected = space_layout(a)
    if space.layout != expected:
        raise RecycleMismatch(
            f"RecycleSpace layout {space.layout!r} does not match this "
            f"operator ({expected!r}): the space was harvested from a "
            f"different matrix (or row count) and deflating with it "
            f"would silently waste every projection. Harvest a space "
            f"from THIS operator (solver.recycle.harvest_space).")


# ---------------------------------------------------------------------------
# harvest: basis ring + tridiagonal -> RecycleSpace


def _decode_basis(basis) -> tuple:
    """Host view of a fetched ring: ``(iterations (m,), vectors
    (m, n))`` sorted by iteration, unwritten slots dropped."""
    its, vecs = basis
    its = np.asarray(its)
    vecs = np.asarray(vecs, dtype=np.float64)
    # a broken-down solve writes non-finite rows (NaN residuals) -
    # drop them here so the harvest fails TYPED (too-small window ->
    # HarvestError) instead of feeding NaN into the SVD
    ok = (its >= 0) & np.isfinite(vecs).all(axis=1)
    its, vecs = its[ok], vecs[ok]
    order = np.argsort(its, kind="stable")
    return its[order].astype(np.int64), vecs[order]


def harvest_space(
    a,
    result,
    *,
    k: int = DEFAULT_K,
    prev: Optional[RecycleSpace] = None,
    lane: int = 0,
    n_rhs: Optional[int] = None,
    note: bool = True,
) -> tuple:
    """Combine a solve's basis ring with its flight record into a
    :class:`RecycleSpace`; returns ``(space, HarvestInfo)``.

    Args:
      a: the operator the solve ran (the global object - harvesting
        pays one ``matmat`` of an ``(n, <= 2k)`` stack to form ``A W``
        and the Gram factor).
      result: a ``CGResult`` / ``CGBatchResult`` carrying ``.basis``
        (the ring - solve with ``basis=BasisConfig(...)``) and
        ``.flight`` (stride-1 recorder - solve with
        ``flight=FlightConfig(stride=1)``).
      k: recycled-space dimension (smallest-Ritz-value pairs kept; the
        small end of the spectrum is what throttles CG).
      prev: accumulate - Rayleigh-Ritz-compress ``[prev.W | window
        Ritz vectors]`` back to ``k`` columns.  Repeat harvests
        converge the space toward the true extreme invariant subspace
        even when each solve's ring only windows its tail.
      lane/n_rhs: batched solves - which lane the ring recorded and
        the stack width (decodes the batched flight buffer).

    Raises :class:`HarvestError` when the record cannot support the
    reconstruction (and, via ``telemetry.health``, a loud stride-1
    refusal for decimated rings - never silent junk Ritz values).
    """
    import jax.numpy as jnp

    from ..telemetry import health
    from ..telemetry.flight import FlightRecord, lanes_from_buffer

    a = _as_linear_operator(a)
    if getattr(result, "basis", None) is None:
        raise HarvestError(
            "the solve carried no basis ring: pass "
            "basis=BasisConfig(...) (and flight=FlightConfig(stride=1)"
            ") to the solve that should be harvested")
    if getattr(result, "flight", None) is None:
        raise HarvestError(
            "the solve carried no flight recorder: the harvest needs "
            "the alpha/beta tridiagonal - pass "
            "flight=FlightConfig(stride=1)")
    if n_rhs is not None and n_rhs > 1:
        record = lanes_from_buffer(result.flight, n_rhs)[lane]
    else:
        record = FlightRecord.from_buffer(result.flight)
    try:
        diag, off, res_its = health.lanczos_tridiagonal(record)
    except ValueError as e:
        raise HarvestError(str(e)) from e

    bits, bvecs = _decode_basis(result.basis)
    # intersect: tridiagonal rows whose residual vector the ring kept
    pos = {int(t): i for i, t in enumerate(bits)}
    keep = np.array([int(t) in pos for t in res_its])
    if int(keep.sum()) < 2:
        raise HarvestError(
            f"basis ring (iterations {bits[0] if bits.size else '-'}"
            f"..{bits[-1] if bits.size else '-'}) and tridiagonal rows "
            f"({res_its[0]}..{res_its[-1]}) share < 2 iterations - "
            f"ring capacity too small for this solve?")
    # the shared window must stay consecutive for the tridiagonal to
    # remain a principal submatrix: take the trailing consecutive run
    kept_idx = np.nonzero(keep)[0]
    brk = np.nonzero(np.diff(kept_idx) != 1)[0]
    first = kept_idx[int(brk[-1]) + 1] if brk.size else kept_idx[0]
    sel = np.arange(first, kept_idx[-1] + 1)
    w_dim = sel.shape[0]
    if w_dim < 2:
        raise HarvestError("usable consecutive window < 2 rows")
    t_w = np.diag(diag[sel])
    if w_dim > 1:
        o = off[sel[:-1]]
        t_w += np.diag(o, 1) + np.diag(o, -1)
    try:
        lam, coeff = np.linalg.eigh(t_w)
    except np.linalg.LinAlgError as e:
        raise HarvestError(f"tridiagonal eigendecomposition failed: "
                           f"{e}") from e
    kd = int(min(k, w_dim))
    idx = np.argsort(lam)[:kd]
    # Lanczos vectors alternate sign against the stored residuals:
    # v_t = (-1)^t r_t/||r_t||; only the RELATIVE alternation matters
    # (a global sign scales whole columns)
    rows = np.array([pos[int(t)] for t in res_its[sel]])
    signs = ((-1.0) ** np.arange(w_dim))[:, None]
    w_window = bvecs[rows].T @ (signs * coeff[:, idx])

    basis = w_window if prev is None \
        else np.hstack([np.asarray(prev.w, dtype=np.float64), w_window])
    # orthonormalize by SVD (rank-revealing: an accumulated harvest
    # overlaps the previous space, and QR's R would be near-singular)
    try:
        u, s, _ = np.linalg.svd(basis, full_matrices=False)
    except np.linalg.LinAlgError as e:
        # a typed refusal, never an escaping LinAlgError: the serve
        # schedule and recycled_sequence catch HarvestError and carry
        # on undeflated
        raise HarvestError(f"basis orthonormalization failed: "
                           f"{e}") from e
    good = s > max(1e-8 * float(s[0]), 1e-30)
    q = u[:, good]
    if q.shape[1] < 1:
        raise HarvestError("harvested basis is numerically rank-0")
    dtype = np.asarray(result.x).dtype
    aq = np.asarray(a.matmat(jnp.asarray(q, dtype)), dtype=np.float64)
    g = q.T @ aq
    g = 0.5 * (g + g.T)
    try:
        mu, z = np.linalg.eigh(g)
    except np.linalg.LinAlgError as e:
        raise HarvestError(f"Rayleigh-Ritz eigendecomposition "
                           f"failed: {e}") from e
    if not np.all(np.isfinite(mu)):
        raise HarvestError("Rayleigh-Ritz projection is non-finite "
                           "(non-finite basis vectors?)")
    kd = int(min(k, q.shape[1]))
    order = np.argsort(mu)[:kd]
    while kd >= 1:
        zsel = z[:, order[:kd]]
        g_w = zsel.T @ g @ zsel
        g_w = 0.5 * (g_w + g_w.T)
        try:
            chol = np.linalg.cholesky(g_w)
            break
        except np.linalg.LinAlgError:
            kd -= 1          # drop the worst-conditioned direction
    else:
        raise HarvestError(
            "W^T A W is not positive definite at any k (non-SPD "
            "operator, or a poisoned trace)")
    zsel = z[:, order[:kd]]
    w_final = q @ zsel
    aw_final = aq @ zsel
    ritz = mu[order[:kd]]
    quality = tuple(
        float(np.linalg.norm(aw_final[:, i] - ritz[i] * w_final[:, i])
              / max(abs(float(ritz[i])), 1e-300))
        for i in range(kd))

    space = RecycleSpace(
        w=jnp.asarray(w_final, dtype),
        aw=jnp.asarray(aw_final, dtype),
        chol=jnp.asarray(chol, dtype),
        n=int(a.shape[0]), k=kd, layout=space_layout(a))
    info = HarvestInfo(
        k=kd, window=w_dim,
        iterations=int(record.iterations[-1]) if len(record) else 0,
        ritz=tuple(float(v) for v in ritz),
        quality=quality, accumulated=prev is not None)
    if note:
        note_harvest(info)
    return space, info


def note_harvest(info: HarvestInfo, **extra) -> None:
    """Route one harvest through the observability stack: the
    ``recycle_harvest`` event plus the space-quality gauges."""
    from ..telemetry import events
    from ..telemetry.registry import REGISTRY

    REGISTRY.counter(
        "recycle_harvests_total",
        "RecycleSpace harvests (Ritz extraction from a solve's basis "
        "ring + flight record)").inc()
    REGISTRY.gauge(
        "recycle_space_k",
        "columns of the most recently harvested RecycleSpace").set(
            info.k)
    if info.ritz:
        REGISTRY.gauge(
            "recycle_ritz_min",
            "smallest kept Ritz value of the most recent harvest").set(
                float(info.ritz[0]))
    events.emit("recycle_harvest", **info.to_json(), **extra)


def note_applied(k: int, iterations: int, baseline: Optional[float],
                 **extra) -> None:
    """The deflation-consumer side: a solve ran with a recycled space;
    record the measured iterations against the undeflated baseline
    (the iters-saved gauge the ROADMAP acceptance names)."""
    from ..telemetry import events
    from ..telemetry.registry import REGISTRY

    saved = None if baseline is None else float(baseline) - iterations
    if saved is not None:
        REGISTRY.gauge(
            "recycle_iters_saved",
            "iterations saved by the most recent deflated solve vs "
            "the handle's undeflated baseline").set(saved)
    events.emit("recycle_applied", k=k, iterations=int(iterations),
                **({"baseline_iterations": float(baseline),
                    "iters_saved": saved}
                   if baseline is not None else {}),
                **extra)


# ---------------------------------------------------------------------------
# the repeat-solve driver (CLI --recycle; also the example's loop)


@dataclasses.dataclass(frozen=True)
class RecycleEntry:
    """One solve of a :func:`recycled_sequence` run."""

    index: int
    result: object
    elapsed_s: float
    harvest_s: float
    deflated: bool
    info: Optional[HarvestInfo]

    def to_json(self) -> dict:
        return {
            "index": self.index,
            "iterations": int(self.result.iterations),
            "converged": bool(self.result.converged),
            "elapsed_s": float(self.elapsed_s),
            "harvest_s": float(self.harvest_s),
            "deflated": self.deflated,
            **({"harvest": self.info.to_json()}
               if self.info is not None else {}),
        }


@dataclasses.dataclass(frozen=True)
class RecycleSequenceResult:
    entries: tuple = ()

    @property
    def result(self):
        return self.entries[-1].result

    def iterations(self):
        return [int(e.result.iterations) for e in self.entries]

    def summary(self) -> dict:
        its = self.iterations()
        solve_wall = sum(e.elapsed_s for e in self.entries)
        harvest_wall = sum(e.harvest_s for e in self.entries)
        last = self.entries[-1]
        return {
            "repeats": len(self.entries),
            "iterations": its,
            "first_solve_iterations": its[0],
            "final_solve_iterations": its[-1],
            "iters_saved": its[0] - its[-1],
            "harvest_overhead_pct": round(
                100.0 * harvest_wall / max(solve_wall, 1e-30), 3),
            "k": last.info.k if last.info is not None else None,
            "solves": [e.to_json() for e in self.entries],
        }

    def describe_lines(self):
        lines = []
        for e in self.entries:
            tag = "deflated" if e.deflated else "harvest source"
            h = (f", harvest {e.harvest_s * 1e3:.1f} ms "
                 f"(k={e.info.k}, ritz_min {e.info.ritz[0]:.3g})"
                 if e.info is not None else "")
            lines.append(
                f"solve {e.index + 1} : "
                f"{int(e.result.iterations)} iters, "
                f"{e.elapsed_s * 1e3:.3f} ms [{tag}]{h}")
        its = self.iterations()
        lines.append(f"recycling : {its[0]} -> {its[-1]} iters/solve "
                     f"({its[0] - its[-1]} saved)")
        return lines


def recycled_sequence(
    a,
    b,
    *,
    repeats: int = 2,
    k: int = DEFAULT_K,
    capacity: Optional[int] = None,
    mesh=None,
    maxiter: int = 2000,
    rhs_for=None,
    **kw,
) -> RecycleSequenceResult:
    """Solve the same operator ``repeats`` times, harvesting after
    every solve and deflating the next - the measured
    iters/solve-falls-every-solve loop (CLI ``--recycle``, bench's
    ``recycle`` section, ``examples/18_recycling.py``).

    ``rhs_for(i)`` supplies solve ``i``'s right-hand side (repeat
    traffic); ``None`` reuses ``b``.  ``mesh`` routes through
    ``parallel.solve_distributed``; ``None`` runs the single-device
    ``solver.solve``.  Each solve is dispatched twice (compile warmup
    + timed, the CLI's protocol) so the timings never ingest compile
    wall.  ``**kw`` forwards to the solve entry point.
    """
    from ..telemetry import events
    from ..telemetry.flight import FlightConfig
    from ..utils.timing import time_fn

    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    cfg = BasisConfig.for_solve(maxiter) if capacity is None \
        else BasisConfig(capacity=capacity)
    flight = FlightConfig.for_solve(maxiter, stride=1)

    def dispatch(b_i, space, basis_cfg):
        if mesh is not None:
            from ..parallel import solve_distributed

            return solve_distributed(a, b_i, mesh=mesh,
                                     maxiter=maxiter, flight=flight,
                                     basis=basis_cfg, deflate=space,
                                     **kw)
        from .cg import solve

        return solve(a, b_i, maxiter=maxiter, flight=flight,
                     basis=basis_cfg, deflate=space, **kw)

    import time as _time

    space = None
    info = None
    entries = []
    for i in range(repeats):
        b_i = b if rhs_for is None else rhs_for(i)
        calls = [0]

        def once():
            calls[0] += 1
            if calls[0] == 1:
                with events.scoped(phase="warmup"):
                    return dispatch(b_i, space, cfg)
            return dispatch(b_i, space, cfg)

        elapsed, res = time_fn(once, warmup=1, repeats=1)
        deflated = space is not None
        if deflated:
            note_applied(space.k, int(res.iterations),
                         float(entries[0].result.iterations))
        t0 = _time.perf_counter()
        try:
            space, info = harvest_space(a, res, k=k, prev=space)
        except HarvestError:
            info = None          # keep the previous space (if any)
        harvest_s = _time.perf_counter() - t0
        entries.append(RecycleEntry(
            index=i, result=res, elapsed_s=float(elapsed),
            harvest_s=float(harvest_s), deflated=deflated, info=info))
    return RecycleSequenceResult(entries=tuple(entries))


# ---------------------------------------------------------------------------
# the deflated lane's device-side projections (consumed by cg/cg_many)


def chol_solve(l, rhs):
    """``(W^T A W)^{-1} rhs`` via the space's precomputed Cholesky
    factor (``rhs`` a ``(k,)`` vector or ``(k, m)`` stack)."""
    import jax

    return jax.scipy.linalg.cho_solve((l, True), rhs)


def entry_project(space: RecycleSpace, x, r, axis_name):
    """Galerkin entry correction: ``x += W (W^T A W)^{-1} W^T r`` -
    after it, ``W^T r = 0`` (the recycled space's component of the
    error is solved exactly, before the first iteration).  Works for
    ``(n,)`` vectors and ``(n, k_rhs)`` stacks.  One ``(k,)``- (or
    ``(k, k_rhs)``-) wide psum at entry on a mesh."""
    from jax import lax

    wtr = space.w.T @ r
    if axis_name is not None:
        wtr = lax.psum(wtr, axis_name)
    c = chol_solve(space.chol, wtr)
    return x + space.w @ c, r - space.aw @ c


def project_direction(space: RecycleSpace, z, axis_name):
    """A-orthogonalize a candidate direction against the space:
    ``z - W (W^T A W)^{-1} (A W)^T z`` (the deflation projector's
    action; ``A`` symmetric, so ``(AW)^T z = W^T A z``)."""
    from jax import lax

    wz = space.aw.T @ z
    if axis_name is not None:
        wz = lax.psum(wz, axis_name)
    return z - space.w @ chol_solve(space.chol, wz)
