"""Bearer-token authentication for the serve tier's network planes.

The in-process ``SolverService`` takes its ``tenant``/``slo_class``
tags on trust - fine between Python callers in one process, a spoofing
hole the moment a network shim forwards them.  This module closes it:

* :class:`TokenKeyring` maps bearer token -> :class:`TenantIdentity`
  SERVER-side, so the tenant the admission controller and the SLO /
  usage accounting key on is **derived from the credential**, never
  claimed by the request body.  A request body that *does* claim a
  tenant is cross-checked: a mismatch is a typed 403
  (:class:`AuthError`), and it never reaches admission - a spoofed tag
  must not even consume a token-bucket token.
* :func:`constant_time_eq` / :func:`bearer_ok` are THE repo-wide
  credential comparisons (``hmac.compare_digest``) - the data plane
  (``serve.net``) and the read-only ops plane (``serve.ops``) both
  route through them, so there is exactly one comparison definition
  and no timing-leaky ``==`` on a secret anywhere.

Transport note: this is bearer-token authentication over whatever
transport the deployment provides; run it behind TLS termination in
anything but loopback testing.  Tokens never appear in logs, events,
or error bodies - identities are named by tenant, not by secret.
"""
from __future__ import annotations

import dataclasses
import hmac
import json
from typing import Dict, Iterable, Optional, Tuple

__all__ = [
    "AuthError",
    "TenantIdentity",
    "TokenKeyring",
    "bearer_ok",
    "constant_time_eq",
]


def constant_time_eq(a: str, b: str) -> bool:
    """Credential comparison without a timing side channel - THE one
    definition (``hmac.compare_digest`` over utf-8 bytes) every
    network-plane auth check in this repo uses."""
    return hmac.compare_digest(str(a).encode("utf-8"),
                               str(b).encode("utf-8"))


def bearer_ok(header_value: Optional[str], token: str) -> bool:
    """Does an ``Authorization`` header value carry exactly
    ``Bearer <token>``?  Constant-time on the credential part; a
    missing header or a non-Bearer scheme is simply False."""
    if not header_value:
        return False
    return constant_time_eq(str(header_value), f"Bearer {token}")


class AuthError(Exception):
    """A typed authentication/authorization refusal.

    ``status`` is the HTTP status the network plane maps it to
    (401 = no/unknown credential, 403 = a valid credential asking for
    someone else's identity), ``code`` a machine-readable reason the
    JSON body carries.  Never contains a token.
    """

    def __init__(self, message: str, *, status: int, code: str):
        super().__init__(message)
        self.status = int(status)
        self.code = str(code)


@dataclasses.dataclass(frozen=True)
class TenantIdentity:
    """What a resolved bearer token IS: the tenant every tag derives
    from, plus an optional SLO-class allowlist (``None`` = any class
    the service's table knows)."""

    tenant: str
    slo_classes: Optional[Tuple[str, ...]] = None

    def allows_class(self, slo_class: str) -> bool:
        return self.slo_classes is None \
            or slo_class in self.slo_classes

    def to_json(self) -> dict:
        out = {"tenant": self.tenant}
        if self.slo_classes is not None:
            out["slo_classes"] = list(self.slo_classes)
        return out


class TokenKeyring:
    """token -> :class:`TenantIdentity`, resolved in constant time.

    :meth:`resolve` walks EVERY entry and compares via
    :func:`constant_time_eq` (no dict-lookup short circuit, no early
    exit on the first mismatched byte), so response timing does not
    leak which tokens exist.  Tokens must be non-empty and unique;
    multiple tokens may map to one tenant (key rotation).
    """

    def __init__(self, entries: Optional[Dict[str, TenantIdentity]]
                 = None):
        self._entries: Dict[str, TenantIdentity] = {}
        for token, identity in (entries or {}).items():
            self.add(token, identity)

    def add(self, token: str, identity) -> "TokenKeyring":
        token = str(token)
        if not token:
            raise ValueError("empty bearer token")
        if token in self._entries:
            raise ValueError("duplicate bearer token in keyring")
        if isinstance(identity, str):
            identity = TenantIdentity(tenant=identity)
        if not isinstance(identity, TenantIdentity):
            raise TypeError(f"identity must be a TenantIdentity or "
                            f"tenant name, got "
                            f"{type(identity).__name__}")
        if not identity.tenant:
            raise ValueError("empty tenant name")
        self._entries[token] = identity
        return self

    def __len__(self) -> int:
        return len(self._entries)

    def tenants(self) -> Tuple[str, ...]:
        """The distinct tenants this keyring can authenticate (sorted;
        safe to log - no tokens)."""
        return tuple(sorted({i.tenant for i in
                             self._entries.values()}))

    def resolve(self, token: str) -> Optional[TenantIdentity]:
        """The identity of ``token``, or ``None`` - after comparing
        against every entry regardless of where (or whether) it
        matched."""
        token = str(token)
        found = None
        for known, identity in self._entries.items():
            if constant_time_eq(token, known):
                found = identity
        return found

    def authenticate(self,
                     authorization: Optional[str]) -> TenantIdentity:
        """Resolve an ``Authorization`` header to an identity or raise
        a typed 401 :class:`AuthError` (missing header, non-Bearer
        scheme, unknown token - deliberately one indistinguishable
        refusal)."""
        if not authorization:
            raise AuthError(
                "this data plane requires a bearer token: "
                "Authorization: Bearer <token>",
                status=401, code="unauthenticated")
        parts = str(authorization).split(" ", 1)
        if len(parts) != 2 or parts[0] != "Bearer" or not parts[1]:
            raise AuthError(
                "malformed Authorization header (expected "
                "'Bearer <token>')", status=401, code="unauthenticated")
        identity = self.resolve(parts[1])
        if identity is None:
            raise AuthError("unknown bearer token",
                            status=401, code="unauthenticated")
        return identity

    def authorize(self, identity: TenantIdentity, *,
                  claimed_tenant: Optional[str],
                  slo_class: Optional[str]) -> None:
        """The anti-spoofing cross-check: a request body claiming a
        tenant other than the credential's, or an SLO class outside
        the identity's allowlist, is a typed 403 - it never reaches
        admission, so a spoofed tag cannot even burn a token-bucket
        token or touch the SLO tracker."""
        if claimed_tenant is not None \
                and str(claimed_tenant) != identity.tenant:
            raise AuthError(
                f"request claims tenant {claimed_tenant!r} but the "
                f"bearer token authenticates tenant "
                f"{identity.tenant!r} - tenant tags are derived from "
                f"the credential, not the body",
                status=403, code="tenant_mismatch")
        if slo_class is not None \
                and not identity.allows_class(str(slo_class)):
            raise AuthError(
                f"tenant {identity.tenant!r} is not entitled to SLO "
                f"class {slo_class!r} (allowed: "
                f"{sorted(identity.slo_classes or ())})",
                status=403, code="slo_class_forbidden")

    # -- construction helpers -------------------------------------------

    @classmethod
    def single(cls, token: str, tenant: str,
               slo_classes: Optional[Iterable[str]] = None
               ) -> "TokenKeyring":
        """One-token keyring (tests, single-tenant deployments)."""
        classes = tuple(slo_classes) if slo_classes is not None \
            else None
        return cls({token: TenantIdentity(tenant=tenant,
                                          slo_classes=classes)})

    @classmethod
    def from_spec(cls, spec: str) -> "TokenKeyring":
        """Parse the CLI spelling ``token:tenant[:class[+class...]]``
        with entries comma-separated, e.g.
        ``tokA:acme,tokB:beta:bulk+silver``."""
        ring = cls()
        for i, entry in enumerate(str(spec).split(",")):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) not in (2, 3) or not parts[0] \
                    or not parts[1]:
                raise ValueError(
                    f"token spec entry {i} must be "
                    f"'token:tenant[:class+class...]', got {entry!r}")
            classes = tuple(parts[2].split("+")) if len(parts) == 3 \
                else None
            ring.add(parts[0], TenantIdentity(tenant=parts[1],
                                              slo_classes=classes))
        if not len(ring):
            raise ValueError("token spec names no tokens")
        return ring

    @classmethod
    def from_file(cls, path: str) -> "TokenKeyring":
        """Load a JSON keyring file::

            {"version": 1,
             "tokens": [{"token": "...", "tenant": "acme",
                         "slo_classes": ["gold", "silver"]}, ...]}

        ``slo_classes`` omitted = any class.
        """
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict) or data.get("version") != 1:
            raise ValueError(f"{path}: not a version-1 keyring file")
        rows = data.get("tokens")
        if not isinstance(rows, list) or not rows:
            raise ValueError(f"{path}: empty keyring")
        ring = cls()
        for i, row in enumerate(rows):
            if not isinstance(row, dict) or "token" not in row \
                    or "tenant" not in row:
                raise ValueError(
                    f"{path}: tokens[{i}] must be an object with "
                    f"'token' and 'tenant'")
            classes = row.get("slo_classes")
            if classes is not None and (
                    not isinstance(classes, list)
                    or not all(isinstance(c, str) for c in classes)):
                raise ValueError(
                    f"{path}: tokens[{i}].slo_classes must be a list "
                    f"of class names")
            ring.add(str(row["token"]), TenantIdentity(
                tenant=str(row["tenant"]),
                slo_classes=tuple(classes) if classes is not None
                else None))
        return ring
