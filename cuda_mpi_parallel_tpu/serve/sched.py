"""SLO classes + weighted-fair dispatch (deficit round-robin).

The PR 10 service popped its microbatch queues oldest-queue-first:
with one tenant that is exactly fair, but one hot tenant submitting
faster than the mesh solves starves every other flow - its queue is
always the oldest, so it is always next.  This module replaces that
pop with the classic deficit-round-robin scheduler (Shreedhar &
Varghese) over ``(handle, tenant, slo-class)`` FLOWS:

* every flow accumulates *solve-cost credits* each round in proportion
  to its weight (``SLOClass.weight`` x optional per-tenant weight);
* a flow dispatches when its deficit covers the priced cost of its
  next batch, and pays that cost down;
* an idle flow's deficit resets (no banking: a tenant cannot hoard
  credits while quiet and then burst past everyone).

Costs are *priced*, not guessed uniform: :class:`BatchCostModel`
seeds each handle's per-dispatch cost from the calibrated machine
model (``telemetry.calibrate.preferred_model`` - the measured
mem-bandwidth sweep cost of one operator application) and then
replaces the seed with the measured EWMA of the handle's real batch
solve walls, so a heavy operator's dispatches drain proportionally
more credit than a cheap one's.  Only *relative* cost matters to DRR.

Starvation bound (asserted in tests): with weights ``w_i`` and batch
costs ``<= quantum``, a backlogged flow dispatches at least once per
``ceil(w_max / w_i) + 1`` scheduler rotations - a 10:1 hot tenant
cannot push a 1-req/s tenant's dispatch beyond that bound.

The all-off configuration (one tenant, one class, one handle - a
single flow) degenerates to the PR 10 order exactly: one flow is
always next, and within a flow queues drain in insertion order
(``tests/test_serve_sched.py::TestLegacyCompat`` proves the replay is
bit-for-bit).  ``SchedConfig(fair=False)`` keeps the literal PR 10
pop as the reference implementation.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Tuple

__all__ = [
    "DEFAULT_CLASSES",
    "BatchCostModel",
    "SLOClass",
    "SchedConfig",
    "WeightedFairScheduler",
    "class_table",
]

#: fallback expected iterations for a never-measured handle: only the
#: RELATIVE cost across handles matters to DRR, so a fixed placeholder
#: (replaced by the measured EWMA after the first dispatch) is honest
DEFAULT_COST_ITERS = 50

#: per-value bytes of one CSR sweep (8 B value + 4 B column index) -
#: the same (itemsize + 4) term balance.plan.score_report prices with
_SWEEP_BYTES_PER_NNZ = 12.0


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One service-level class: a dispatch weight plus the latency bar
    its traffic is accounted against.

    ``target_latency_s`` is the in-SLO accounting bound (the
    saturation bench's "goodput" is completions within it); it is NOT
    an enforced deadline unless ``deadline_s`` is set, in which case
    submits of this class that arrive without an explicit deadline get
    it - mapping the class onto the existing deadline/TIMEOUT
    machinery instead of inventing a second expiry path.

    ``degrade_ok`` marks the class eligible for the shed ladder's
    first rung (tolerance widened one decade under pressure);
    ``defer_ok`` for the second (dispatch deferred while the ladder
    holds); ``reject_exempt`` shields it from the third (still
    admitted - subject to its token bucket and the hard queue bound -
    while every other class is turned away).  ``gold`` is
    none-of-the-first-two and exempt from the third: its contract is
    that accepted work runs at full accuracy inside its latency
    bound, and overload is answered by shedding the classes below it.
    """

    name: str
    weight: float = 1.0
    target_latency_s: Optional[float] = None
    deadline_s: Optional[float] = None
    degrade_ok: bool = True
    defer_ok: bool = False
    reject_exempt: bool = False

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"class weight must be > 0, got "
                             f"{self.weight}")


#: the standard three-tier table.  Latency targets are accounting
#: bounds only (no default deadlines - a plain ServiceConfig must not
#: start expiring traffic that PR 10 accepted); weights are the 8:4:1
#: dispatch shares the fairness tests assert.
DEFAULT_CLASSES: Tuple[SLOClass, ...] = (
    SLOClass("gold", weight=8.0, target_latency_s=0.25,
             degrade_ok=False, defer_ok=False, reject_exempt=True),
    SLOClass("silver", weight=4.0, target_latency_s=1.0,
             degrade_ok=True, defer_ok=False),
    SLOClass("bulk", weight=1.0, target_latency_s=None,
             degrade_ok=True, defer_ok=True),
)


def class_table(classes: Tuple[SLOClass, ...]) -> Dict[str, SLOClass]:
    """Name -> class mapping with duplicate-name validation."""
    out: Dict[str, SLOClass] = {}
    for cls in classes:
        if cls.name in out:
            raise ValueError(f"duplicate SLO class {cls.name!r}")
        out[cls.name] = cls
    return out


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Dispatch-policy knobs of the weighted-fair scheduler.

    ``fair=False`` keeps the literal PR 10 oldest-queue-first pop (the
    bit-for-bit reference the compat test replays against).
    ``tenant_weights`` multiplies a tenant's flows' class weights
    (unlisted tenants weigh 1.0).
    """

    classes: Tuple[SLOClass, ...] = DEFAULT_CLASSES
    fair: bool = True
    tenant_weights: Tuple[Tuple[str, float], ...] = ()

    def __post_init__(self):
        class_table(self.classes)          # validates
        for tenant, w in self.tenant_weights:
            if w <= 0:
                raise ValueError(f"tenant weight must be > 0, got "
                                 f"{w} for {tenant!r}")

    def weight_for(self, tenant: str, slo_class: str) -> float:
        cls = class_table(self.classes).get(slo_class)
        base = cls.weight if cls is not None else 1.0
        return base * dict(self.tenant_weights).get(tenant, 1.0)


class BatchCostModel:
    """Per-handle dispatch cost in (estimated) seconds.

    The seed is the calibrated machine model's price of one operator
    sweep - ``nnz x (itemsize + 4) / mem_bytes_per_s`` per iteration,
    the same memory-stream term ``balance.plan.score_report`` uses -
    times a fixed placeholder iteration count; with no confident
    calibration on disk the seed is the same expression against the
    reference table model, so relative costs across handles stay
    meaningful either way.  After a handle's first dispatch the seed
    is dead: :meth:`observe` tracks the EWMA of its measured batch
    solve walls, which is what DRR actually drains credits against.
    """

    def __init__(self, alpha: float = 0.3):
        self._alpha = float(alpha)
        self._measured: Dict[str, float] = {}
        self._seeds: Dict[str, float] = {}
        self._model = None
        self._model_loaded = False

    def _mem_bytes_per_s(self) -> float:
        if not self._model_loaded:
            self._model_loaded = True
            try:
                from ..telemetry.calibrate import preferred_model

                self._model = preferred_model()
            except Exception:
                self._model = None
        if self._model is not None:
            return float(self._model.mem_bytes_per_s)
        from ..balance.plan import reference_model

        return float(reference_model().mem_bytes_per_s)

    def price(self, handle) -> float:
        """Estimated seconds of one dispatch of ``handle`` (any
        bucket: the batched sweep is operator-dominated, so bucket
        size does not change the relative story DRR needs)."""
        measured = self._measured.get(handle.key)
        if measured is not None:
            return measured
        seed = self._seeds.get(handle.key)
        if seed is None:
            nnz = getattr(handle.a, "nnz", None)
            if nnz is None:
                nnz = 8 * handle.n       # dense-ish row fallback
            per_iter = float(nnz) * _SWEEP_BYTES_PER_NNZ \
                / max(self._mem_bytes_per_s(), 1.0)
            seed = per_iter * min(int(handle.maxiter),
                                  DEFAULT_COST_ITERS)
            self._seeds[handle.key] = max(seed, 1e-9)
        return self._seeds[handle.key]

    def observe(self, handle, solve_s: float) -> None:
        if solve_s <= 0:
            return
        prev = self._measured.get(handle.key)
        self._measured[handle.key] = float(solve_s) if prev is None \
            else (1 - self._alpha) * prev + self._alpha * float(solve_s)


class WeightedFairScheduler:
    """Deficit round-robin over flows; see the module docstring.

    Not thread-safe on its own - the service calls :meth:`pick` under
    its queue lock.  Deterministic: the chosen flow is a pure function
    of the pick-call history (registration order breaks ties), which
    is what lets the fake-clock tests assert exact dispatch orders.
    """

    def __init__(self, config: Optional[SchedConfig] = None):
        self.config = config or SchedConfig()
        # weight tables built ONCE: _weight runs on every pointer
        # rotation of every dispatch, under the service lock
        self._class_weight: Dict[str, float] = {
            cls.name: cls.weight for cls in self.config.classes}
        self._tenant_weight: Dict[str, float] = \
            dict(self.config.tenant_weights)
        self._order: List[Tuple] = []       # registration order
        self._deficit: Dict[Tuple, float] = {}
        self._cursor = 0
        #: the flow the pointer is currently serving: its round grant
        #: was already paid, so repeat picks keep draining the deficit
        #: instead of re-granting (one grant per pointer ARRIVAL is
        #: what makes the weight shares real)
        self._serving: Optional[Tuple] = None

    def _weight(self, flow: Tuple) -> float:
        # flow = (handle_key, tenant, slo_class)
        return self._class_weight.get(flow[2], 1.0) \
            * self._tenant_weight.get(flow[1], 1.0)

    def pick(self, candidates: Mapping[Tuple, float]) -> Tuple:
        """Choose the next flow to dispatch.  ``candidates`` maps each
        currently-dispatchable flow to the priced cost of its next
        batch; flows absent from it lose their banked deficit (the
        classic DRR empty-queue reset)."""
        if not candidates:
            raise ValueError("pick() needs >= 1 candidate flow")
        for flow in [f for f in self._order if f not in candidates]:
            idx = self._order.index(flow)
            self._order.remove(flow)
            del self._deficit[flow]
            if idx < self._cursor:
                self._cursor -= 1
            if flow == self._serving:
                self._serving = None
        for flow in candidates:
            if flow not in self._deficit:
                self._order.append(flow)
                self._deficit[flow] = 0.0
        if self._cursor >= len(self._order):
            self._cursor = 0
        max_w = max(self._weight(f) for f in self._order)
        quantum = max(candidates.values())
        # every full rotation grows each deficit by a weight-
        # proportional quantum share >= quantum * w_min / w_max and no
        # cost exceeds quantum, so the loop terminates within
        # ceil(w_max / w_min) + 1 rotations
        while True:
            flow = self._order[self._cursor]
            if flow != self._serving:
                # the pointer just arrived: pay this round's grant
                self._deficit[flow] += \
                    quantum * self._weight(flow) / max_w
                self._serving = flow
            if self._deficit[flow] >= candidates[flow]:
                self._deficit[flow] -= candidates[flow]
                return flow
            # grant exhausted: the turn ends, the pointer moves on
            self._serving = None
            self._cursor = (self._cursor + 1) % len(self._order)

    def deficits(self) -> Dict[Tuple, float]:
        """Snapshot for stats()/debugging."""
        return dict(self._deficit)
