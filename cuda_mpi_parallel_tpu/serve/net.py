"""The authenticated network DATA plane: submit/result RPC over
:class:`serve.service.SolverService`.

PR 19's ops plane (``serve.ops``) made the service *observable* over
HTTP; this module makes it *drivable* - the missing shim ROADMAP item
1 names, and the prerequisite for item 2's replicated fleet.  Same
zero-dependency pattern (stdlib ``ThreadingHTTPServer``, daemon
threads, SSE), but write-side, so the rules are stricter:

======================   =============================================
``POST /v1/submit``      async submit: a ``serve.wire`` envelope in,
                         ``202 {request_id, result_url}`` out - unless
                         the service resolved it at the door, in which
                         case the HONEST status comes back now
                         (``ADMISSION_REJECTED`` -> 429 with
                         ``Retry-After`` from the result's
                         ``retry_after_s``; breaker ``REFUSED`` and
                         ``QueueFull``/closed -> 503).  Never a raw
                         traceback.
``POST /v1/solve``       sync convenience: submit + wait (bounded by
                         ``?timeout_s=``); a solve still running at
                         the bound degrades to the async 202.
``GET /v1/result/<id>``  long-poll (``?timeout_s=``): the terminal
                         result envelope, ``202 done:false`` while
                         pending, 404 unknown/evicted, 403 when the
                         caller's tenant does not own the request.
``GET /v1/stream``       SSE of TERMINAL result envelopes for the
                         authenticated tenant (optionally ``?ids=``) -
                         push instead of poll.
``GET /v1/handles``      the registered operators (key, n, dtype,
                         method) - what a client may submit against.
======================   =============================================

**Auth is identity, not a doorknob.**  Every route requires a bearer
token resolved through a :class:`serve.auth.TokenKeyring`; the
resolved identity's tenant IS the tenant tag the admission controller,
SLO tracker and usage ledger see.  A body claiming another tenant is a
typed 403 *before* admission (no token-bucket token burned, no SLO
flow touched); an unauthenticated submit never reaches the service at
all.

**The wire never perturbs the math.**  Vectors cross as bit-exact
base64 little-endian bytes (``serve.wire``), the handler threads do
host-side work only (parse, enqueue, wait on a Future), and the solve
path is the SAME in-process dispatch loop - which is why the loopback
replay gate can demand per-request ``(status, iterations,
max_abs_error)`` exactly equal to the no-network replay, and the
zero-perturbation test can demand a bit-identical solve jaxpr while
the plane is live.
"""
from __future__ import annotations

import itertools
import json
import queue as queue_mod
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from ..telemetry.registry import REGISTRY
from . import wire
from .auth import AuthError, TenantIdentity, TokenKeyring
from .queue import QueueFull
from .service import ServiceClosed

__all__ = ["NetServer"]

_JSON_CONTENT_TYPE = "application/json; charset=utf-8"
_SSE_CONTENT_TYPE = "text/event-stream; charset=utf-8"

#: long-poll bounds: a missing ?timeout_s= waits this long, and no
#: client may pin a handler thread longer than the cap
_DEFAULT_POLL_S = 30.0
_MAX_POLL_S = 300.0


class _Tracked:
    """One submitted request as the plane tracks it: the service
    future, the owning tenant (from the CREDENTIAL, used for the 403
    ownership check on reads), and the public net request id."""

    __slots__ = ("net_id", "tenant", "future", "handle_key")

    def __init__(self, net_id: str, tenant: str, future,
                 handle_key: str):
        self.net_id = net_id
        self.tenant = tenant
        self.future = future
        self.handle_key = handle_key


class NetServer:
    """One service's data plane: a daemon ``ThreadingHTTPServer``
    routing authenticated submits into ``service.submit()`` and
    results back out as ``serve.wire`` envelopes.

    ``port=0`` binds an ephemeral port (tests); :attr:`port` reports
    the bound one.  Start via :meth:`SolverService.serve_net` or
    ``ServiceConfig(net_port=..., net_keyring=...)`` rather than
    constructing directly.  ``result_store`` bounds how many tracked
    requests (pending or terminal) the plane remembers; the oldest are
    evicted first and read back as 404.
    """

    def __init__(self, service, *, port: int = 0,
                 host: str = "127.0.0.1",
                 keyring: Optional[TokenKeyring] = None,
                 result_store: int = 4096):
        if not isinstance(keyring, TokenKeyring) or not len(keyring):
            raise ValueError(
                "the data plane requires a non-empty "
                "serve.auth.TokenKeyring (an unauthenticated data "
                "plane would take tenant tags on trust - the exact "
                "hole this plane exists to close)")
        self.service = service
        self.keyring = keyring
        self._host = str(host)
        self._want_port = int(port)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tracked: Dict[str, _Tracked] = {}
        self._order: deque = deque()
        self._store_cap = max(int(result_store), 1)
        #: per-tenant SSE follower queues (terminal result envelopes)
        self._streams: Dict[str, List[queue_mod.Queue]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._requests = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "NetServer":
        if self._httpd is not None:
            raise RuntimeError("NetServer already started")
        handler = type("_BoundNetHandler", (_NetHandler,),
                       {"net": self})
        httpd = ThreadingHTTPServer((self._host, self._want_port),
                                    handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self._stopping = False
        serve = threading.Thread(
            target=httpd.serve_forever,
            name="cuda-mpi-parallel-tpu-net-http", daemon=True)
        serve.start()
        self._thread = serve
        return self

    def stop(self) -> None:
        """Stop accepting connections and wake every SSE follower.
        Idempotent.  In-flight solves keep their futures - the plane
        stops serving them, the service resolves them."""
        if self._httpd is None:
            return
        self._stopping = True
        with self._lock:
            followers = [q for qs in self._streams.values()
                         for q in qs]
        for q in followers:
            try:
                q.put_nowait(None)          # wake -> follower exits
            except queue_mod.Full:
                pass
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd.server_close()
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("NetServer not started")
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def request_count(self) -> int:
        """HTTP requests served so far (any route)."""
        with self._lock:
            return self._requests

    def _note_request(self) -> None:
        with self._lock:
            self._requests += 1

    # -- request tracking ----------------------------------------------

    def _track(self, tenant: str, future, handle_key: str) -> _Tracked:
        with self._lock:
            net_id = f"n{next(self._ids):06d}"
            entry = _Tracked(net_id, tenant, future, handle_key)
            self._tracked[net_id] = entry
            self._order.append(net_id)
            while len(self._order) > self._store_cap:
                self._tracked.pop(self._order.popleft(), None)
        # terminal results fan out to the owning tenant's SSE
        # followers the moment the service resolves the future (the
        # callback runs on the resolving thread - keep it queue-put
        # cheap)
        future.add_done_callback(
            lambda fut, e=entry: self._fan_out(e, fut))
        return entry

    def _lookup(self, net_id: str) -> Optional[_Tracked]:
        with self._lock:
            return self._tracked.get(net_id)

    def _fan_out(self, entry: _Tracked, fut) -> None:
        try:
            result = fut.result(timeout=0)
        except Exception:            # cancelled; nothing to stream
            return
        with self._lock:
            followers = list(self._streams.get(entry.tenant, ()))
        if not followers:
            return
        env = wire.result_envelope(result, request_id=entry.net_id)
        for q in followers:
            try:
                q.put_nowait(env)
            except queue_mod.Full:
                pass                 # slow follower: drop, never block

    def _stream_attach(self, tenant: str) -> queue_mod.Queue:
        q: queue_mod.Queue = queue_mod.Queue(maxsize=1024)
        with self._lock:
            self._streams.setdefault(tenant, []).append(q)
        return q

    def _stream_detach(self, tenant: str, q: queue_mod.Queue) -> None:
        with self._lock:
            qs = self._streams.get(tenant)
            if qs is not None:
                try:
                    qs.remove(q)
                except ValueError:
                    pass
                if not qs:
                    self._streams.pop(tenant, None)


class _NetHandler(BaseHTTPRequestHandler):
    """Route table of one :class:`NetServer` (bound via a subclass
    holding ``net``)."""

    net: NetServer                   # set by the bound subclass
    protocol_version = "HTTP/1.1"
    server_version = "cuda-mpi-parallel-tpu-net/1"

    def log_message(self, fmt: str, *args: Any) -> None:
        pass                         # quiet; metrics count requests

    # -- plumbing ------------------------------------------------------

    def _send(self, code: int, body: bytes, content_type: str,
              extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Cache-Control", "no-store")
        for key, val in (extra or {}).items():
            self.send_header(key, val)
        self.end_headers()
        self.wfile.write(body)
        self._count(code)

    def _send_json(self, code: int, payload: Any,
                   extra: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(payload, sort_keys=True, allow_nan=False)
                + "\n").encode("utf-8")
        self._send(code, body, _JSON_CONTENT_TYPE, extra)

    def _send_wire_error(self, code: int, message: str, *,
                         err_code: str,
                         extra: Optional[Dict[str, str]] = None
                         ) -> None:
        self._send_json(code, wire.error_envelope(message,
                                                  code=err_code),
                        extra=extra)

    def _route(self) -> str:
        path = urlparse(self.path).path
        if path.startswith("/v1/result/"):
            return "/v1/result"
        return path.rstrip("/") or "/"

    def _count(self, code: int) -> None:
        self.net._note_request()
        REGISTRY.counter(
            "net_requests_total",
            "data-plane HTTP requests by route and status code",
            labelnames=("route", "code")).inc(
                route=self._route(), code=str(int(code)))

    def _send_result(self, entry: _Tracked, result) -> None:
        """A terminal result as its envelope + honest HTTP status:
        429/503/500 still carry the FULL typed result body, so a
        client always learns the same facts the in-process caller
        would."""
        env = wire.result_envelope(result, request_id=entry.net_id)
        code, semantics = wire.status_to_http(result.status)
        extra = None
        if semantics == "retry_after" \
                and result.retry_after_s is not None:
            # ceil to an int >= 1: Retry-After is delta-seconds, and
            # "0" would tell a compliant client to hammer
            extra = {"Retry-After":
                     str(max(1, int(-(-result.retry_after_s // 1))))}
        self._send_json(code, env, extra=extra)

    def _authenticate(self) -> Optional[TenantIdentity]:
        """Resolve the bearer token or answer 401 and return None."""
        try:
            return self.net.keyring.authenticate(
                self.headers.get("Authorization"))
        except AuthError as e:
            self._send_wire_error(
                e.status, str(e), err_code=e.code,
                extra={"WWW-Authenticate": "Bearer"}
                if e.status == 401 else None)
            return None

    def _query(self) -> Dict[str, List[str]]:
        return parse_qs(urlparse(self.path).query)

    def _poll_timeout(self, query: Dict[str, List[str]],
                      default: float = _DEFAULT_POLL_S) -> float:
        try:
            t = float(query.get("timeout_s", [default])[0])
        except (TypeError, ValueError):
            return default
        return min(max(t, 0.0), _MAX_POLL_S)

    # -- routes --------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802  (stdlib handler API)
        try:
            path = self._route()
            if path == "/v1/submit":
                self._post_submit(sync=False)
            elif path == "/v1/solve":
                self._post_submit(sync=True)
            else:
                self._send_wire_error(
                    404, f"no such route {path!r}",
                    err_code="not_found")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:       # typed 500, NEVER a traceback
            try:
                self._send_wire_error(
                    500, f"internal error: {type(e).__name__}",
                    err_code="internal")
            except Exception:
                pass

    def do_GET(self) -> None:  # noqa: N802
        try:
            path = self._route()
            if path == "/v1/result":
                self._get_result()
            elif path == "/v1/stream":
                self._get_stream()
            elif path == "/v1/handles":
                self._get_handles()
            else:
                self._send_wire_error(
                    404, f"no such route {path!r}",
                    err_code="not_found",
                    extra={"X-Routes": "/v1/submit /v1/solve "
                           "/v1/result/<id> /v1/stream /v1/handles"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:
            try:
                self._send_wire_error(
                    500, f"internal error: {type(e).__name__}",
                    err_code="internal")
            except Exception:
                pass

    # -- submit --------------------------------------------------------

    def _post_submit(self, *, sync: bool) -> None:
        recv_t0 = time.monotonic()
        # 1. authenticate BEFORE reading state or touching the
        #    service: an unauthenticated submit never reaches
        #    admission
        identity = self._authenticate()
        if identity is None:
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            length = 0
        if length <= 0:
            self._send_wire_error(400, "submit requires a JSON body",
                                  err_code="bad_request")
            return
        raw = self.rfile.read(length)
        # 2. parse the envelope (typed 400 on any malformation)
        try:
            req = wire.parse_submit(raw)
        except wire.WireError as e:
            self._send_wire_error(400, str(e), err_code=e.code)
            return
        # 3. authorize: the credential's tenant is THE tenant; a
        #    mismatched claim or a forbidden SLO class dies here,
        #    before admission ever sees it
        slo_class = req["slo_class"] or "silver"
        try:
            self.net.keyring.authorize(
                identity, claimed_tenant=req["tenant"],
                slo_class=slo_class)
        except AuthError as e:
            self._send_wire_error(e.status, str(e), err_code=e.code)
            return
        handle = self.net.service.handles().get(req["handle"])
        if handle is None:
            self._send_wire_error(
                404, f"unknown handle {req['handle']!r} (see "
                f"GET /v1/handles)", err_code="unknown_handle")
            return
        # 4. submit under the DERIVED tenant
        hop_s = time.monotonic() - recv_t0
        try:
            fut = self.net.service.submit(
                handle, req["b"], tol=req["tol"],
                deadline_s=req["deadline_s"],
                tenant=identity.tenant, slo_class=slo_class,
                net_hop={"duration_s": hop_s,
                         "route": "/v1/solve" if sync
                         else "/v1/submit",
                         "bytes_in": len(raw)})
        except QueueFull as e:
            self._send_wire_error(503, str(e), err_code="queue_full")
            return
        except ServiceClosed as e:
            self._send_wire_error(503, str(e),
                                  err_code="service_closed")
            return
        except ValueError as e:
            self._send_wire_error(400, str(e), err_code="bad_request")
            return
        entry = self.net._track(identity.tenant, fut, handle.key)
        # 5. answer honestly.  Door rejections (admission / breaker)
        #    resolve synchronously inside submit(), so fut.done() here
        #    means the backpressure verdict maps to 429/503 NOW
        if fut.done():
            self._send_result(entry, fut.result(timeout=0))
            return
        if sync:
            wait_s = self._poll_timeout(self._query())
            try:
                result = fut.result(timeout=wait_s)
            except Exception:
                result = None
            if result is not None:
                self._send_result(entry, result)
                return
        self._send_json(202, {
            "wire": wire.WIRE_VERSION, "kind": "pending",
            "done": False, "request_id": entry.net_id,
            "result_url": f"/v1/result/{entry.net_id}",
            "stream_url": f"/v1/stream?ids={entry.net_id}",
        })

    # -- result / stream / handles -------------------------------------

    def _get_result(self) -> None:
        identity = self._authenticate()
        if identity is None:
            return
        net_id = urlparse(self.path).path[len("/v1/result/"):]
        entry = self.net._lookup(net_id)
        if entry is None:
            self._send_wire_error(
                404, f"unknown request id {net_id!r} (expired from "
                f"the result store, or never issued)",
                err_code="unknown_request")
            return
        if entry.tenant != identity.tenant:
            # ownership is tenant-scoped: one tenant may never read
            # another's result
            self._send_wire_error(
                403, "request belongs to another tenant",
                err_code="tenant_mismatch")
            return
        wait_s = self._poll_timeout(self._query(), default=0.0)
        result = None
        try:
            result = entry.future.result(timeout=wait_s)
        except Exception:
            result = None
        if result is None:
            self._send_json(202, {
                "wire": wire.WIRE_VERSION, "kind": "pending",
                "done": False, "request_id": entry.net_id,
                "result_url": f"/v1/result/{entry.net_id}",
            })
            return
        self._send_result(entry, result)

    def _get_stream(self) -> None:
        identity = self._authenticate()
        if identity is None:
            return
        query = self._query()
        want = None
        if "ids" in query:
            want = {i for part in query["ids"]
                    for i in part.split(",") if i}
        q = self.net._stream_attach(identity.tenant)
        try:
            self.send_response(200)
            self.send_header("Content-Type", _SSE_CONTENT_TYPE)
            self.send_header("Cache-Control", "no-store")
            self.send_header("Connection", "close")
            self.end_headers()
            self._count(200)
            # results that went terminal BEFORE the stream attached
            # still stream (replay from the tracked store), so
            # submit-then-stream has no race window
            with self.net._lock:
                backlog = [e for e in self.net._tracked.values()
                           if e.tenant == identity.tenant
                           and e.future.done()
                           and (want is None or e.net_id in want)]
            sent = set()
            for entry in backlog:
                try:
                    result = entry.future.result(timeout=0)
                except Exception:
                    continue
                self._sse_write(wire.result_envelope(
                    result, request_id=entry.net_id))
                sent.add(entry.net_id)
            while not self.net._stopping:
                try:
                    env = q.get(timeout=0.5)
                except queue_mod.Empty:
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
                    continue
                if env is None:      # server stopping
                    break
                if env["request_id"] in sent:
                    continue
                if want is not None \
                        and env["request_id"] not in want:
                    continue
                self._sse_write(env)
                sent.add(env["request_id"])
                if want is not None and sent >= want:
                    break            # everything asked for delivered
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            self.net._stream_detach(identity.tenant, q)
            self.close_connection = True

    def _sse_write(self, env: dict) -> None:
        data = json.dumps(env, sort_keys=True, allow_nan=False)
        self.wfile.write(b"event: result\ndata: "
                         + data.encode("utf-8") + b"\n\n")
        self.wfile.flush()

    def _get_handles(self) -> None:
        identity = self._authenticate()
        if identity is None:
            return
        handles = self.net.service.handles()
        self._send_json(200, {
            "wire": wire.WIRE_VERSION, "kind": "handles",
            "handles": [
                {"key": h.key, "n": int(h.n),
                 "dtype": h.dtype_name, "method": h.method,
                 "mesh": h.mesh is not None,
                 "precond": h.precond,
                 "buckets": [int(b) for b in h.buckets]}
                for h in handles.values()
            ]})
