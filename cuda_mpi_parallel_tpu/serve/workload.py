"""Replayable service workloads: arrival times + RHS seeds.

A workload is the serving tier's test signal - a list of
``(arrival_t, seed)`` pairs, optionally with per-request tolerance and
deadline overrides.  Seeds, not vectors: request ``i``'s right-hand
side is ``A @ x_true(seed_i)`` built against the registered operator
(:func:`rhs_for`), so every request has a KNOWN solution and a replay
can verify per-request accuracy, while the workload file itself stays
a few hundred bytes regardless of the matrix size.

Files are strict JSON (``{"version": 1, "requests": [...]}``);
:func:`synthetic_poisson` generates the standard open-loop benchmark
arrival process (exponential gaps at a target rate).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ReplaySummary",
    "WorkloadRequest",
    "load_workload",
    "replay_workload",
    "rhs_for",
    "save_workload",
    "summarize_replay",
    "synthetic_poisson",
    "synthetic_tenant_mix",
]


@dataclasses.dataclass(frozen=True)
class WorkloadRequest:
    """One replayed arrival: offset seconds from replay start + the
    RHS seed; ``tol``/``deadline_s`` of ``None`` take the replay's
    defaults.  ``tenant``/``slo_class`` of ``None`` default at replay
    time (one tenant, ``silver``) - a pre-multi-tenant workload file
    replays byte-identically."""

    t: float
    seed: int
    tol: Optional[float] = None
    deadline_s: Optional[float] = None
    tenant: Optional[str] = None
    slo_class: Optional[str] = None

    def to_json(self) -> dict:
        out = {"t": float(self.t), "seed": int(self.seed)}
        if self.tol is not None:
            out["tol"] = float(self.tol)
        if self.deadline_s is not None:
            out["deadline_s"] = float(self.deadline_s)
        if self.tenant is not None:
            out["tenant"] = str(self.tenant)
        if self.slo_class is not None:
            out["slo_class"] = str(self.slo_class)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "WorkloadRequest":
        if not isinstance(data, dict):
            raise ValueError(
                f"workload request must be an object, got "
                f"{type(data).__name__}")
        for field in ("t", "seed"):
            if field not in data:
                raise ValueError(
                    f"workload request missing field {field!r}")
        return cls(t=float(data["t"]), seed=int(data["seed"]),
                   tol=(float(data["tol"]) if data.get("tol")
                        is not None else None),
                   deadline_s=(float(data["deadline_s"])
                               if data.get("deadline_s") is not None
                               else None),
                   tenant=(str(data["tenant"])
                           if data.get("tenant") is not None else None),
                   slo_class=(str(data["slo_class"])
                              if data.get("slo_class") is not None
                              else None))


def synthetic_poisson(n_requests: int, rate_hz: float, seed: int = 0,
                      tol: Optional[float] = None,
                      deadline_s: Optional[float] = None,
                      tenant: Optional[str] = None,
                      slo_class: Optional[str] = None
                      ) -> List[WorkloadRequest]:
    """Open-loop Poisson arrivals: ``n_requests`` with exponential
    inter-arrival gaps at ``rate_hz`` (the first request arrives at
    t=0 so a replay never idles before its own start)."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    gaps[0] = 0.0
    times = np.cumsum(gaps)
    return [WorkloadRequest(t=float(t), seed=int(seed * 1_000_003 + i),
                            tol=tol, deadline_s=deadline_s,
                            tenant=tenant, slo_class=slo_class)
            for i, t in enumerate(times)]


def synthetic_tenant_mix(n_requests: int, rate_hz: float,
                         tenants: Sequence[Tuple[str, float, str]],
                         seed: int = 0,
                         tol: Optional[float] = None,
                         deadline_s: Optional[float] = None
                         ) -> List[WorkloadRequest]:
    """Open-loop Poisson arrivals tagged by a tenant mix: ``tenants``
    is ``(name, share, slo_class)`` rows (shares need not sum to 1 -
    they are normalized), each arrival sampled independently.
    Deterministic in ``seed`` - the saturation scenarios the overload
    bench and gate replay are committable files, not dice rolls."""
    if not tenants:
        raise ValueError("tenants must name >= 1 (name, share, class)")
    shares = np.asarray([float(s) for _, s, _ in tenants])
    if (shares <= 0).any():
        raise ValueError(f"tenant shares must be > 0, got "
                         f"{shares.tolist()}")
    base = synthetic_poisson(n_requests, rate_hz, seed=seed, tol=tol,
                             deadline_s=deadline_s)
    rng = np.random.default_rng(seed + 0x7E4A47)
    picks = rng.choice(len(tenants), size=n_requests,
                       p=shares / shares.sum())
    return [dataclasses.replace(r, tenant=str(tenants[int(i)][0]),
                                slo_class=str(tenants[int(i)][2]))
            for r, i in zip(base, picks)]


def save_workload(path: str,
                  requests: Sequence[WorkloadRequest]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "requests": [r.to_json() for r in requests]},
                  f, allow_nan=False, indent=1)
        f.write("\n")


def load_workload(path: str) -> List[WorkloadRequest]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"{path}: not a version-1 workload file")
    reqs = data.get("requests")
    if not isinstance(reqs, list) or not reqs:
        raise ValueError(f"{path}: empty workload")
    return [WorkloadRequest.from_json(r) for r in reqs]


@dataclasses.dataclass
class ReplaySummary:
    """Per-class disposition of one open-loop replay (the saturation
    harness's unit of measurement).  ``goodput_rhs_per_sec`` counts
    only in-SLO completions: converged AND inside the class's
    ``target_latency_s`` (classes without a target count on
    convergence alone)."""

    window_s: float
    offered: int
    solved: int
    in_slo: int
    timeouts: int
    rejected: int                   # ADMISSION_REJECTED + QueueFull
    errors: int
    degraded: int
    goodput_rhs_per_sec: float
    #: per-class: {"offered", "in_slo", "timeouts", "rejected",
    #: "p99_latency_s"}
    by_class: Dict[str, Dict[str, object]]
    results: list                   # resolved RequestResults (or None
    #                                 for QueueFull sheds)


def replay_workload(service, handle, requests, prepared_b,
                    *, tol: float = 1e-7,
                    deadline_s: Optional[float] = None,
                    classes=None) -> ReplaySummary:
    """Open-loop replay: submit ``requests[i]`` with RHS
    ``prepared_b[i]`` at its arrival offset on the REAL clock, drain,
    and classify every outcome per SLO class.  The saturation bench,
    the overload example and the tests share this loop so "goodput"
    means one thing repo-wide.  ``classes`` maps class name ->
    ``SLOClass`` for the in-SLO bar (default: the service's table).
    Open-loop means arrivals never wait for results - offered load is
    the independent variable, which is what makes a past-capacity ramp
    meaningful (closed-loop replay self-throttles and cannot overload
    anything)."""
    import time

    from .queue import QueueFull

    if classes is None:
        classes = getattr(service, "_classes", {})
    t0 = time.monotonic()
    futures = []
    for r, b in zip(requests, prepared_b):
        delay = (t0 + r.t) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            futures.append(service.submit(
                handle, b,
                tol=r.tol if r.tol is not None else tol,
                deadline_s=(r.deadline_s if r.deadline_s is not None
                            else deadline_s),
                tenant=r.tenant or "default",
                slo_class=r.slo_class or "silver"))
        except QueueFull:
            futures.append(None)     # hard backpressure shed
    service.drain()
    window_s = time.monotonic() - t0
    results = [fut.result() if fut is not None else None
               for fut in futures]
    return summarize_replay(requests, results, window_s,
                            classes=classes)


def summarize_replay(requests, results, window_s: float,
                     *, classes=None) -> ReplaySummary:
    """Classify one replay's resolved outcomes (``None`` entries =
    hard backpressure sheds that never produced a result) into a
    :class:`ReplaySummary`.

    THE one classification definition: the in-process
    :func:`replay_workload` and the network client's
    ``NetClient.replay_workload`` both call this, which is what makes
    "a loopback network replay produces the same ReplaySummary"
    checkable - the two paths can only differ in the per-request
    results they feed in, never in how outcomes are counted.
    """
    if classes is None:
        classes = {}
    by_class: Dict[str, Dict[str, object]] = {}
    lats: Dict[str, list] = {}

    def tally(name):
        return by_class.setdefault(
            name, {"offered": 0, "in_slo": 0, "timeouts": 0,
                   "rejected": 0, "p99_latency_s": None})

    solved = in_slo = timeouts = rejected = errors = degraded = 0
    for r, res in zip(requests, results):
        name = r.slo_class or "silver"
        row = tally(name)
        row["offered"] += 1
        if res is None:
            rejected += 1
            row["rejected"] += 1
            continue
        if res.status == "ADMISSION_REJECTED":
            rejected += 1
            row["rejected"] += 1
            continue
        if res.timed_out:
            timeouts += 1
            row["timeouts"] += 1
            continue
        if res.status == "ERROR":
            errors += 1
            continue
        if res.degraded:
            degraded += 1
        if res.converged:
            solved += 1
            lats.setdefault(name, []).append(res.latency_s)
            cls = classes.get(name)
            target = getattr(cls, "target_latency_s", None)
            if target is None or res.latency_s <= target:
                in_slo += 1
                row["in_slo"] += 1
    for name, vals in lats.items():
        vals.sort()
        idx = max(0, int(np.ceil(0.99 * len(vals))) - 1)
        by_class[name]["p99_latency_s"] = float(vals[idx])
    return ReplaySummary(
        window_s=window_s, offered=len(requests), solved=solved,
        in_slo=in_slo, timeouts=timeouts,
        rejected=rejected, errors=errors,
        degraded=degraded,
        goodput_rhs_per_sec=in_slo / max(window_s, 1e-9),
        by_class=by_class, results=list(results))


def rhs_for(a, seed: int, dtype=None) -> Tuple[np.ndarray, np.ndarray]:
    """``(b, x_true)`` for one request: ``x_true`` is the seed's
    standard-normal vector, ``b = A @ x_true`` - so the replay can
    check every answer against a known solution."""
    import jax.numpy as jnp

    n = int(a.shape[0])
    dt = np.dtype(dtype if dtype is not None else a.dtype)
    x_true = np.random.default_rng(seed).standard_normal(n).astype(dt)
    b = np.asarray(a @ jnp.asarray(x_true))
    return b, x_true
