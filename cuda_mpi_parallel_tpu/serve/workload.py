"""Replayable service workloads: arrival times + RHS seeds.

A workload is the serving tier's test signal - a list of
``(arrival_t, seed)`` pairs, optionally with per-request tolerance and
deadline overrides.  Seeds, not vectors: request ``i``'s right-hand
side is ``A @ x_true(seed_i)`` built against the registered operator
(:func:`rhs_for`), so every request has a KNOWN solution and a replay
can verify per-request accuracy, while the workload file itself stays
a few hundred bytes regardless of the matrix size.

Files are strict JSON (``{"version": 1, "requests": [...]}``);
:func:`synthetic_poisson` generates the standard open-loop benchmark
arrival process (exponential gaps at a target rate).
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "WorkloadRequest",
    "load_workload",
    "rhs_for",
    "save_workload",
    "synthetic_poisson",
]


@dataclasses.dataclass(frozen=True)
class WorkloadRequest:
    """One replayed arrival: offset seconds from replay start + the
    RHS seed; ``tol``/``deadline_s`` of ``None`` take the replay's
    defaults."""

    t: float
    seed: int
    tol: Optional[float] = None
    deadline_s: Optional[float] = None

    def to_json(self) -> dict:
        out = {"t": float(self.t), "seed": int(self.seed)}
        if self.tol is not None:
            out["tol"] = float(self.tol)
        if self.deadline_s is not None:
            out["deadline_s"] = float(self.deadline_s)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "WorkloadRequest":
        if not isinstance(data, dict):
            raise ValueError(
                f"workload request must be an object, got "
                f"{type(data).__name__}")
        for field in ("t", "seed"):
            if field not in data:
                raise ValueError(
                    f"workload request missing field {field!r}")
        return cls(t=float(data["t"]), seed=int(data["seed"]),
                   tol=(float(data["tol"]) if data.get("tol")
                        is not None else None),
                   deadline_s=(float(data["deadline_s"])
                               if data.get("deadline_s") is not None
                               else None))


def synthetic_poisson(n_requests: int, rate_hz: float, seed: int = 0,
                      tol: Optional[float] = None,
                      deadline_s: Optional[float] = None
                      ) -> List[WorkloadRequest]:
    """Open-loop Poisson arrivals: ``n_requests`` with exponential
    inter-arrival gaps at ``rate_hz`` (the first request arrives at
    t=0 so a replay never idles before its own start)."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    gaps[0] = 0.0
    times = np.cumsum(gaps)
    return [WorkloadRequest(t=float(t), seed=int(seed * 1_000_003 + i),
                            tol=tol, deadline_s=deadline_s)
            for i, t in enumerate(times)]


def save_workload(path: str,
                  requests: Sequence[WorkloadRequest]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "requests": [r.to_json() for r in requests]},
                  f, allow_nan=False, indent=1)
        f.write("\n")


def load_workload(path: str) -> List[WorkloadRequest]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"{path}: not a version-1 workload file")
    reqs = data.get("requests")
    if not isinstance(reqs, list) or not reqs:
        raise ValueError(f"{path}: empty workload")
    return [WorkloadRequest.from_json(r) for r in reqs]


def rhs_for(a, seed: int, dtype=None) -> Tuple[np.ndarray, np.ndarray]:
    """``(b, x_true)`` for one request: ``x_true`` is the seed's
    standard-normal vector, ``b = A @ x_true`` - so the replay can
    check every answer against a known solution."""
    import jax.numpy as jnp

    n = int(a.shape[0])
    dt = np.dtype(dtype if dtype is not None else a.dtype)
    x_true = np.random.default_rng(seed).standard_normal(n).astype(dt)
    b = np.asarray(a @ jnp.asarray(x_true))
    return b, x_true
