"""The network-facing ops plane: a read-only HTTP observatory.

Every observatory grown so far - the metrics registry, the events
stream, request traces, SLO burn accounting, the usage ledger - is
reachable only from Python inside the serving process.  ROADMAP item 1
asks for metered usage EXPORT and item 2's replica router needs a
machine-readable health signal per replica; both are network
questions.  This module answers them with the stdlib only
(`http.server.ThreadingHTTPServer` - no new dependencies):

======================  ==============================================
``GET /metrics``        Prometheus text exposition of the global
                        registry (``text/plain; version=0.0.4``) -
                        byte-identical to the CLI's ``--metrics``
                        one-shot dump (one formatter:
                        :func:`prometheus_exposition`).
``GET /snapshot``       ``MetricsRegistry.snapshot()`` as JSON - the
                        machine-readable form ``telemetry.fleet``
                        merges (bucket bounds included; no parsing
                        Prometheus text back into numbers).
``GET /healthz``        process liveness (200 while the server runs).
``GET /readyz``         routing-grade readiness: 200 only when the
                        service is accepting AND no breaker is open
                        AND the shed ladder is at level 0 AND no SLO
                        flow burns over threshold; otherwise 503 with
                        a typed JSON verdict naming every failing
                        gate (:meth:`SolverService.readiness`).
``GET /stats``          the full ``stats()`` JSON.
``GET /usage``          the usage ledger snapshot (404 when metering
                        is off) - the metered-export half of ROADMAP
                        item 1.
``GET /traces/<id>``    the rendered causal span tree of one trace,
                        served from a bounded in-process span store
                        fed by the event bus - never by tailing files.
``GET /events``         recent events as JSON; ``?follow=1`` upgrades
                        to Server-Sent Events off a dedicated
                        ``telemetry.events.subscribe()`` ring.
======================  ==============================================

**Zero perturbation.**  Every endpoint above reads host-side state
(registry counters, stats tallies, event dicts) under the same locks
the service already takes per batch; nothing here touches a jax value
or forces a device sync, so a concurrent scrape leaves the solve
stream bitwise identical (test- and lint-gate-asserted).

**Read-only.**  No POST, no mutation: the plane observes the service,
it never drives it.  The optional static bearer ``token`` gates every
route (401 without it) - transport auth, not authorization policy; the
write side lives in ``serve.net``, whose keyring derives tenant
identity from the credential.  Both planes compare credentials through
the one ``serve.auth`` helper (``hmac.compare_digest`` - no
timing-leaky ``==`` on a secret).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set
from urllib.parse import parse_qs, urlparse

from ..telemetry import events
from ..telemetry.registry import REGISTRY
from ..telemetry.tracing import build_forest, render_tree
from .auth import bearer_ok

__all__ = ["OpsServer", "PROMETHEUS_CONTENT_TYPE",
           "prometheus_exposition"]

#: the Prometheus text exposition format version this plane speaks
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_JSON_CONTENT_TYPE = "application/json; charset=utf-8"


def prometheus_exposition(registry=None) -> str:
    """THE Prometheus text formatter - ``/metrics`` scrapes and the
    CLI's ``--metrics`` one-shot dump both call this, so the two are
    byte-identical by construction (one formatter, no drift)."""
    reg = REGISTRY if registry is None else registry
    return reg.to_prometheus()


class OpsServer:
    """One service's ops plane: a daemon ``ThreadingHTTPServer`` plus
    a pump thread that drains a subscriber ring into the bounded span
    store / recent-event ring the ``/traces`` and ``/events``
    endpoints serve from.

    ``port=0`` binds an ephemeral port (tests); :attr:`port` reports
    the bound one.  Start via :meth:`SolverService.serve_ops` or
    ``ServiceConfig(ops_port=...)`` rather than constructing directly.
    """

    def __init__(self, service, *, port: int = 0,
                 host: str = "127.0.0.1",
                 token: Optional[str] = None,
                 span_store: int = 4096,
                 event_ring: int = 1024):
        self.service = service
        self._host = str(host)
        self._want_port = int(port)
        self._token = token if token is None else str(token)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(span_store))
        self._recent: deque = deque(maxlen=int(event_ring))
        self._sub: Optional[events.Subscription] = None
        self._sse_subs: Set[events.Subscription] = set()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stopping = False
        self._started_mono = 0.0
        self._scrapes = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "OpsServer":
        if self._httpd is not None:
            raise RuntimeError("OpsServer already started")
        handler = type("_BoundOpsHandler", (_OpsHandler,),
                       {"ops": self})
        httpd = ThreadingHTTPServer((self._host, self._want_port),
                                    handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self._started_mono = time.monotonic()
        # the event pump: one bounded ring off the in-process bus
        # (drop-oldest; never blocks the emitter), drained into the
        # span store - /traces never tails a file
        self._sub = events.subscribe(maxlen=4096)
        pump = threading.Thread(target=self._pump_loop,
                                name="cuda-mpi-parallel-tpu-ops-pump",
                                daemon=True)
        serve = threading.Thread(target=httpd.serve_forever,
                                 name="cuda-mpi-parallel-tpu-ops-http",
                                 daemon=True)
        pump.start()
        serve.start()
        self._threads = [pump, serve]
        return self

    def stop(self) -> None:
        """Shut the plane down: stop accepting, close every live SSE
        ring, unsubscribe the pump.  Idempotent."""
        if self._httpd is None:
            return
        self._stopping = True
        if self._sub is not None:
            events.unsubscribe(self._sub)
        with self._lock:
            followers = list(self._sse_subs)
        for sub in followers:
            events.unsubscribe(sub)
        self._httpd.shutdown()
        for t in self._threads:
            t.join(timeout=5.0)
        self._httpd.server_close()
        self._httpd = None
        self._threads = []

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("OpsServer not started")
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def scrape_count(self) -> int:
        """Requests served so far (any route) - the overhead bench's
        denominator."""
        with self._lock:
            return self._scrapes

    # -- event pump ----------------------------------------------------

    def _pump_loop(self) -> None:
        sub = self._sub
        while not self._stopping:
            rec = sub.pop(timeout=0.25)
            if rec is None:
                if sub.closed:
                    return
                continue
            with self._lock:
                self._recent.append(rec)
                if rec.get("event") == "span":
                    self._spans.append(rec)

    def span_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def recent_events(self, n: Optional[int] = None
                      ) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._recent)
        return out if n is None else out[-int(n):]

    def _note_scrape(self) -> None:
        with self._lock:
            self._scrapes += 1

    def _sse_attach(self, sub: events.Subscription) -> None:
        with self._lock:
            self._sse_subs.add(sub)

    def _sse_detach(self, sub: events.Subscription) -> None:
        with self._lock:
            self._sse_subs.discard(sub)
        events.unsubscribe(sub)


class _OpsHandler(BaseHTTPRequestHandler):
    """Route table of one :class:`OpsServer` (bound via a subclass
    holding ``ops``)."""

    ops: OpsServer = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"
    server_version = "cuda-mpi-parallel-tpu-ops"

    # the stdlib handler logs every request to stderr; an ops plane
    # scraped every few seconds must not spam the service's console
    def log_message(self, format: str, *args: Any) -> None:
        pass

    # -- response helpers ---------------------------------------------

    def _send(self, code: int, body: bytes, content_type: str,
              extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Cache-Control", "no-store")
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload: Any,
                   extra: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(payload, sort_keys=True, allow_nan=False)
                + "\n").encode("utf-8")
        self._send(code, body, _JSON_CONTENT_TYPE, extra)

    def _send_error_json(self, code: int, error: str,
                         **fields: Any) -> None:
        self._send_json(code, {"error": error, "status_code": code,
                               **fields})

    # -- auth ----------------------------------------------------------

    def _authorized(self) -> bool:
        token = self.ops._token
        if token is None:
            return True
        got = self.headers.get("Authorization", "")
        if bearer_ok(got, token):
            return True
        self._send_json(
            401, {"error": "unauthorized", "status_code": 401,
                  "detail": "this ops plane requires a static bearer "
                            "token: Authorization: Bearer <token>"},
            extra={"WWW-Authenticate": "Bearer"})
        return False

    # -- routes --------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802  (stdlib handler API)
        try:
            if not self._authorized():
                return
            self.ops._note_scrape()
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            query = parse_qs(parsed.query)
            if path == "/metrics":
                self._get_metrics()
            elif path == "/snapshot":
                self._send_json(200, REGISTRY.snapshot())
            elif path == "/healthz":
                self._get_healthz()
            elif path == "/readyz":
                self._get_readyz()
            elif path == "/stats":
                self._send_json(200, self.ops.service.stats())
            elif path == "/usage":
                self._get_usage()
            elif path.startswith("/traces/"):
                self._get_trace(path[len("/traces/"):])
            elif path == "/events":
                self._get_events(query)
            else:
                self._send_error_json(
                    404, "not found", path=path,
                    routes=["/metrics", "/snapshot", "/healthz",
                            "/readyz", "/stats", "/usage",
                            "/traces/<trace_id>", "/events"])
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_HEAD(self) -> None:  # noqa: N802
        # HEAD is read-only too; answer liveness probes cheaply
        if self.ops._token is None or bearer_ok(
                self.headers.get("Authorization"), self.ops._token):
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(401)
            self.send_header("Content-Length", "0")
            self.end_headers()

    def _get_metrics(self) -> None:
        text = prometheus_exposition()
        self._send(200, text.encode("utf-8"), PROMETHEUS_CONTENT_TYPE)

    def _get_healthz(self) -> None:
        self._send_json(200, {
            "ok": True,
            "uptime_s": round(
                time.monotonic() - self.ops._started_mono, 3),
            "requests_served": self.ops.scrape_count(),
        })

    def _get_readyz(self) -> None:
        verdict = self.ops.service.readiness()
        self._send_json(200 if verdict["ready"] else 503, verdict)

    def _get_usage(self) -> None:
        ledger = self.ops.service.usage_ledger()
        if ledger is None:
            self._send_error_json(
                404, "usage metering disabled",
                detail="start the service with "
                       "ServiceConfig(usage=True) to meter per-tenant "
                       "usage")
            return
        self._send_json(200, ledger.snapshot())

    def _get_trace(self, trace_id: str) -> None:
        records = self.ops.span_records()
        if trace_id not in build_forest(records):
            self._send_error_json(
                404, "unknown trace", trace_id=trace_id,
                detail="no spans for this trace in the bounded span "
                       "store (expired, or the id is wrong)")
            return
        text = render_tree(records, trace_id) + "\n"
        self._send(200, text.encode("utf-8"),
                   "text/plain; charset=utf-8")

    def _get_events(self, query: Dict[str, List[str]]) -> None:
        follow = query.get("follow", ["0"])[0] not in ("", "0",
                                                       "false")
        if not follow:
            n = query.get("n", [None])[0]
            payload = self.ops.recent_events(
                None if n is None else int(n))
            self._send_json(200, {"events": payload,
                                  "n": len(payload)})
            return
        # SSE: a dedicated bounded ring per follower (drop-oldest;
        # the emitter never blocks on a slow client)
        limit = query.get("limit", [None])[0]
        remaining = None if limit is None else max(int(limit), 0)
        sub = events.subscribe(maxlen=1024)
        self.ops._sse_attach(sub)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Connection", "close")
            self.end_headers()
            while not self.ops._stopping:
                if remaining is not None and remaining <= 0:
                    break
                rec = sub.pop(timeout=0.5)
                if rec is None:
                    if sub.closed:
                        break
                    # comment keepalive: flushes the pipe so a gone
                    # client surfaces as BrokenPipeError promptly
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
                    continue
                data = json.dumps(rec, sort_keys=True,
                                  allow_nan=False)
                self.wfile.write(
                    f"event: {rec.get('event', 'event')}\n"
                    f"data: {data}\n\n".encode("utf-8"))
                self.wfile.flush()
                if remaining is not None:
                    remaining -= 1
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            self.ops._sse_detach(sub)
            self.close_connection = True
