"""serve: the microbatching solver service (ROADMAP item 1b).

The request-queue front end of the many-RHS tier: register an operator
once (partition + plan + per-bucket trace warmup), then submit repeat
``(matrix-fingerprint, b)`` traffic and let the microbatch policy
coalesce it onto ``solve_many`` / ``solve_distributed_many`` - one
matrix sweep and one halo exchange per iteration serving every queued
column.  See :mod:`.service` for the service itself, :mod:`.queue`
for the batching policy, :mod:`.admission` for per-tenant token-bucket
admission control and the shed-before-collapse ladder, :mod:`.sched`
for SLO classes and the weighted-fair (deficit-round-robin)
dispatcher, and :mod:`.workload` for replayable arrival-time workloads
(the ``cli.py serve`` surface) plus the open-loop saturation harness.

The network tier (ROADMAP item 1's front end): :mod:`.wire` is the
versioned bit-exact wire format, :mod:`.auth` the bearer-token ->
tenant keyring, :mod:`.net` the authenticated HTTP data plane over a
running service, :mod:`.client` the stdlib client (and over-the-wire
workload replay), and :mod:`.ops` the read-only observatory plane.
"""
from __future__ import annotations

from .admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionDecision,
    ShedConfig,
    ShedLadder,
    TokenBucket,
)
from .auth import (
    AuthError,
    TenantIdentity,
    TokenKeyring,
    bearer_ok,
    constant_time_eq,
)
from .client import NetClient, NetError
from .net import NetServer
from .queue import (
    Batch,
    MicroBatchQueue,
    QueueFull,
    bucket_for,
    bucket_sizes,
    tol_class,
)
from .ops import (
    PROMETHEUS_CONTENT_TYPE,
    OpsServer,
    prometheus_exposition,
)
from .sched import (
    DEFAULT_CLASSES,
    BatchCostModel,
    SLOClass,
    SchedConfig,
    WeightedFairScheduler,
)
from .service import (
    OperatorHandle,
    RecyclePolicy,
    RequestResult,
    RetryPolicy,
    ServiceClosed,
    ServiceConfig,
    SolverService,
)
from .usage import UsageLedger
from .wire import (
    WIRE_VERSION,
    WireError,
    decode_array,
    encode_array,
    result_envelope,
    result_from_json,
    status_to_http,
    submit_envelope,
)
from .workload import (
    ReplaySummary,
    WorkloadRequest,
    load_workload,
    replay_workload,
    rhs_for,
    save_workload,
    summarize_replay,
    synthetic_poisson,
    synthetic_tenant_mix,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionDecision",
    "AuthError",
    "Batch",
    "BatchCostModel",
    "DEFAULT_CLASSES",
    "MicroBatchQueue",
    "NetClient",
    "NetError",
    "NetServer",
    "OperatorHandle",
    "OpsServer",
    "PROMETHEUS_CONTENT_TYPE",
    "QueueFull",
    "RecyclePolicy",
    "ReplaySummary",
    "RequestResult",
    "RetryPolicy",
    "SLOClass",
    "SchedConfig",
    "ServiceClosed",
    "ServiceConfig",
    "ShedConfig",
    "ShedLadder",
    "SolverService",
    "TenantIdentity",
    "TokenBucket",
    "TokenKeyring",
    "UsageLedger",
    "WIRE_VERSION",
    "WeightedFairScheduler",
    "WireError",
    "WorkloadRequest",
    "bearer_ok",
    "bucket_for",
    "bucket_sizes",
    "constant_time_eq",
    "decode_array",
    "encode_array",
    "load_workload",
    "prometheus_exposition",
    "replay_workload",
    "result_envelope",
    "result_from_json",
    "rhs_for",
    "save_workload",
    "status_to_http",
    "submit_envelope",
    "summarize_replay",
    "synthetic_poisson",
    "synthetic_tenant_mix",
    "tol_class",
]
