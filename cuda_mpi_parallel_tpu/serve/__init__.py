"""serve: the microbatching solver service (ROADMAP item 1b).

The request-queue front end of the many-RHS tier: register an operator
once (partition + plan + per-bucket trace warmup), then submit repeat
``(matrix-fingerprint, b)`` traffic and let the microbatch policy
coalesce it onto ``solve_many`` / ``solve_distributed_many`` - one
matrix sweep and one halo exchange per iteration serving every queued
column.  See :mod:`.service` for the service itself, :mod:`.queue`
for the batching policy, and :mod:`.workload` for replayable
arrival-time workloads (the ``cli.py serve`` surface).
"""
from __future__ import annotations

from .queue import (
    Batch,
    MicroBatchQueue,
    QueueFull,
    bucket_for,
    bucket_sizes,
    tol_class,
)
from .service import (
    OperatorHandle,
    RecyclePolicy,
    RequestResult,
    RetryPolicy,
    ServiceClosed,
    ServiceConfig,
    SolverService,
)
from .workload import (
    WorkloadRequest,
    load_workload,
    rhs_for,
    save_workload,
    synthetic_poisson,
)

__all__ = [
    "Batch",
    "MicroBatchQueue",
    "OperatorHandle",
    "QueueFull",
    "RecyclePolicy",
    "RequestResult",
    "RetryPolicy",
    "ServiceClosed",
    "ServiceConfig",
    "SolverService",
    "WorkloadRequest",
    "bucket_for",
    "bucket_sizes",
    "load_workload",
    "rhs_for",
    "save_workload",
    "synthetic_poisson",
    "tol_class",
]
