"""Admission control + the shed-before-collapse ladder.

The PR 10 service had exactly one overload answer: the bounded queue's
``QueueFull`` exception, thrown when the damage was already done - the
queue it protects was full of work that would now time out en masse.
This module is the front door that keeps it from getting there:

* :class:`AdmissionController` - per-tenant token buckets (rate +
  burst), refilled on the SERVICE clock (``ServiceConfig.clock``), so
  every refill/exhaustion branch is drivable by the fake-clock tests.
  A rejected submit resolves to a typed ``ADMISSION_REJECTED`` result
  carrying a ``retry_after_s`` hint - never an exception, never a
  silent drop.

* :class:`ShedLadder` - the explicit degradation ladder over measured
  queue pressure.  Rungs, in order, each a strictly milder failure
  than letting accepted work time out:

  1. **degrade** - incoming ``degrade_ok`` classes get their tolerance
     widened one decade (the PR 12 ``degrade_depth`` behavior,
     generalized per class; the result says ``degraded=True``);
  2. **defer** - ``defer_ok`` classes (``bulk``) stop dispatching;
     their queues hold while ``gold``/``silver`` drain inside SLO;
  3. **reject** - non-``gold`` submits are refused at admission with a
     ``retry_after_s`` estimated from the measured service rate.

  Thresholds are queue depths: explicit (`degrade_depth` etc., the
  deterministic test surface) or - with ``auto=True`` - derived from
  the measured capacity estimate (the solved-RHS/s EWMA the service
  keeps, seeded from the phasetrace profile when one was taken at
  registration): a rung fires when the backlog is worth more than
  ``horizon_s`` x capacity x its multiplier of queued work.  Downward
  transitions are hysteretic (``exit_fraction``) so the ladder does
  not flap at a threshold.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionDecision",
    "ShedConfig",
    "ShedLadder",
    "TokenBucket",
]


@dataclasses.dataclass(frozen=True)
class TokenBucket:
    """Rate + burst of one tenant's admission budget.  ``rate`` is
    requests/second of sustained admission; ``burst`` the bucket
    capacity (momentary excursions above the rate)."""

    rate: float
    burst: float

    def __post_init__(self):
        if self.rate <= 0:
            raise ValueError(f"rate must be > 0, got {self.rate}")
        if self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Per-tenant token-bucket table.  ``default`` applies to tenants
    without their own row; ``None`` leaves unlisted tenants unmetered
    (the queue bound still backstops them)."""

    default: Optional[TokenBucket] = None
    tenants: Tuple[Tuple[str, TokenBucket], ...] = ()

    def bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        return dict(self.tenants).get(tenant, self.default)


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    """The typed outcome of one admission check."""

    admitted: bool
    tenant: str
    tokens: float                   # remaining AFTER this decision
    retry_after_s: Optional[float] = None
    reason: Optional[str] = None    # "tokens" | "shed" on rejection


class AdmissionController:
    """Continuous-refill token buckets on an injected clock.

    Not thread-safe on its own - the service calls it under its lock.
    State per tenant is ``(tokens, last_refill_t)``; refill is
    ``min(burst, tokens + dt * rate)`` so a quiet tenant banks at most
    one burst.
    """

    def __init__(self, config: AdmissionConfig):
        self.config = config
        # built once: bucket_for runs on every submit
        self._buckets: Dict[str, TokenBucket] = dict(config.tenants)
        self._state: Dict[str, Tuple[float, float]] = {}

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        return self._buckets.get(tenant, self.config.default)

    def _refill(self, tenant: str, bucket: TokenBucket,
                now: float) -> float:
        tokens, last = self._state.get(tenant, (float(bucket.burst),
                                                now))
        tokens = min(float(bucket.burst),
                     tokens + max(now - last, 0.0) * bucket.rate)
        self._state[tenant] = (tokens, now)
        return tokens

    def tokens(self, tenant: str, now: float) -> Optional[float]:
        """Current balance (refilled to ``now``); None = unmetered."""
        bucket = self._bucket_for(tenant)
        if bucket is None:
            return None
        return self._refill(tenant, bucket, now)

    def admit(self, tenant: str, now: float) -> AdmissionDecision:
        bucket = self._bucket_for(tenant)
        if bucket is None:
            return AdmissionDecision(admitted=True, tenant=tenant,
                                     tokens=math.inf)
        tokens = self._refill(tenant, bucket, now)
        if tokens >= 1.0:
            self._state[tenant] = (tokens - 1.0, now)
            return AdmissionDecision(admitted=True, tenant=tenant,
                                     tokens=tokens - 1.0)
        return AdmissionDecision(
            admitted=False, tenant=tenant, tokens=tokens,
            retry_after_s=(1.0 - tokens) / bucket.rate,
            reason="tokens")


@dataclasses.dataclass(frozen=True)
class ShedConfig:
    """Ladder thresholds as queue depths (see module docstring).

    A rung with depth 0 is OFF unless ``auto=True``, in which case its
    depth derives from the measured capacity estimate:
    ``degrade = ceil(capacity * horizon_s)``, ``defer = 2x``,
    ``reject = 4x`` (explicit nonzero depths always win over the
    derivation).  With no capacity measured yet the auto rungs stay
    off - the ladder never fires on a guess.
    """

    degrade_depth: int = 0
    defer_depth: int = 0
    reject_depth: int = 0
    auto: bool = False
    horizon_s: float = 0.25
    #: a level exits when depth falls to <= enter_threshold x this
    exit_fraction: float = 0.5

    def __post_init__(self):
        for name in ("degrade_depth", "defer_depth", "reject_depth"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, got "
                                 f"{getattr(self, name)}")
        if not 0.0 < self.exit_fraction <= 1.0:
            raise ValueError(f"exit_fraction must be in (0, 1], got "
                             f"{self.exit_fraction}")
        if self.horizon_s <= 0:
            raise ValueError(f"horizon_s must be > 0, got "
                             f"{self.horizon_s}")
        # a rung that fires earlier than the one below it would make
        # the ladder fire out of order - refuse at construction
        depths = [d for d in (self.degrade_depth, self.defer_depth,
                              self.reject_depth) if d > 0]
        if depths != sorted(depths):
            raise ValueError(
                f"ladder depths must be non-decreasing "
                f"(degrade <= defer <= reject), got "
                f"{self.degrade_depth}/{self.defer_depth}/"
                f"{self.reject_depth}")

    def thresholds(self, capacity_rhs_per_s: Optional[float]
                   ) -> Tuple[Optional[int], Optional[int],
                              Optional[int]]:
        """(degrade, defer, reject) depths; None = rung off."""
        out = []
        auto_base = None
        if self.auto and capacity_rhs_per_s is not None \
                and capacity_rhs_per_s > 0:
            auto_base = max(1, int(math.ceil(
                capacity_rhs_per_s * self.horizon_s)))
        for depth, mult in ((self.degrade_depth, 1),
                            (self.defer_depth, 2),
                            (self.reject_depth, 4)):
            if depth > 0:
                out.append(depth)
            elif auto_base is not None:
                out.append(auto_base * mult)
            else:
                out.append(None)
        return tuple(out)


class ShedLadder:
    """Current ladder level with hysteresis; the service owns one and
    calls :meth:`evaluate` under its lock on every submit and pass."""

    #: level -> name (level 0 is healthy)
    LEVELS = ("ok", "degrade", "defer", "reject")

    def __init__(self, config: ShedConfig):
        self.config = config
        self.level = 0
        self.transitions = 0

    def evaluate(self, depth: int,
                 capacity_rhs_per_s: Optional[float] = None) -> bool:
        """Re-derive the level from ``depth``; True when it changed."""
        thresholds = self.config.thresholds(capacity_rhs_per_s)
        target = 0
        for lvl, thr in enumerate(thresholds, start=1):
            if thr is not None and depth >= thr:
                target = lvl
        if target < self.level:
            # hysteretic descent: only drop below a held level once
            # the depth clears its entry threshold by exit_fraction
            held = self.level
            while held > target:
                thr = thresholds[held - 1]
                if thr is not None and depth > \
                        thr * self.config.exit_fraction:
                    break
                held -= 1
            target = max(target, held)
        if target != self.level:
            self.level = target
            self.transitions += 1
            return True
        return False

    @property
    def name(self) -> str:
        return self.LEVELS[self.level]
