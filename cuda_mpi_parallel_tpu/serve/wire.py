"""Versioned wire format for the serve-tier network data plane.

Everything that crosses the socket is a typed JSON envelope with a
``"wire"`` version field; vectors ride inside it as base64 of their
raw **little-endian** bytes plus ``dtype``/``shape``, so a round trip
is **bit-exact** - the decoded array reproduces every byte of the
original, including NaN payloads and signed zeros.  That is what makes
the data plane's correctness contract checkable: a loopback network
replay must produce per-request ``(status, iterations,
max_abs_error)`` exactly equal to the in-process replay, which only
means anything if the transport itself never perturbs a bit.

Layered deliberately below ``serve.net``/``serve.client``: this module
knows numpy and JSON, nothing about HTTP or sockets, so both ends (and
tests) share one codec definition.

Status -> HTTP mapping (:func:`status_to_http`) keeps backpressure
honest instead of collapsing everything to 500:

========================  ====  =======================================
terminal status           HTTP  notes
========================  ====  =======================================
``ADMISSION_REJECTED``    429   ``Retry-After`` from ``retry_after_s``
``REFUSED`` (breaker)     503   also ``QueueFull`` / closed service
``ERROR`` (engine)        500   still a typed result body, never a
                                raw traceback
everything else           200   ``CONVERGED``/``MAXITER``/``TIMEOUT``
                                /... - the solve RAN; the body's
                                ``status`` is the verdict
========================  ====  =======================================
"""
from __future__ import annotations

import base64
import binascii
import json
import math
from typing import Any, Optional, Tuple

import numpy as np

__all__ = [
    "WIRE_VERSION",
    "WireError",
    "decode_array",
    "encode_array",
    "parse_submit",
    "result_envelope",
    "result_from_json",
    "status_to_http",
    "submit_envelope",
]

#: bump on any incompatible envelope change; both ends check it
WIRE_VERSION = 1

#: dtypes the plane accepts - the solver tier is f32/f64 real CG
_ALLOWED_DTYPES = ("float32", "float64")


class WireError(Exception):
    """A malformed envelope (the network plane maps it to HTTP 400).
    ``code`` is a machine-readable reason for the JSON error body."""

    def __init__(self, message: str, *, code: str = "bad_request"):
        super().__init__(message)
        self.code = str(code)


# ---------------------------------------------------------------------------
# bit-exact vector codec
# ---------------------------------------------------------------------------

def encode_array(arr: np.ndarray) -> dict:
    """``{"dtype", "shape", "data"}`` with ``data`` = base64 of the
    array's raw bytes in little-endian order.  Byte-reinterpreting
    (never value-converting), so NaN payloads and signed zeros
    survive."""
    arr = np.asarray(arr)
    if arr.dtype.name not in _ALLOWED_DTYPES:
        raise WireError(
            f"cannot encode dtype {arr.dtype.name!r} "
            f"(wire allows {_ALLOWED_DTYPES})", code="bad_dtype")
    le = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
    return {
        "dtype": arr.dtype.name,
        "shape": [int(d) for d in arr.shape],
        "data": base64.b64encode(np.ascontiguousarray(le).tobytes()
                                 ).decode("ascii"),
    }


def decode_array(obj: Any) -> np.ndarray:
    """Inverse of :func:`encode_array`; returns a native-endian array
    whose bytes (reinterpreted LE) equal exactly what was encoded.
    Raises :class:`WireError` on any malformation - wrong dtype name,
    byte count that disagrees with dtype*shape, bad base64."""
    if not isinstance(obj, dict):
        raise WireError("vector payload must be an object with "
                        "dtype/shape/data", code="bad_vector")
    dtype_name = obj.get("dtype")
    if dtype_name not in _ALLOWED_DTYPES:
        raise WireError(f"vector dtype must be one of "
                        f"{_ALLOWED_DTYPES}, got {dtype_name!r}",
                        code="bad_dtype")
    shape = obj.get("shape")
    if not isinstance(shape, list) \
            or not all(isinstance(d, int) and d >= 0 for d in shape):
        raise WireError("vector shape must be a list of non-negative "
                        "ints", code="bad_vector")
    try:
        raw = base64.b64decode(obj.get("data", ""), validate=True)
    except (binascii.Error, TypeError, ValueError) as e:
        raise WireError(f"vector data is not valid base64: {e}",
                        code="bad_vector")
    le_dtype = np.dtype(dtype_name).newbyteorder("<")
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if len(raw) != count * le_dtype.itemsize:
        raise WireError(
            f"vector byte count {len(raw)} does not match "
            f"dtype {dtype_name} x shape {shape}", code="bad_vector")
    flat = np.frombuffer(raw, dtype=le_dtype)
    return flat.astype(np.dtype(dtype_name), copy=True
                       ).reshape(shape)


# ---------------------------------------------------------------------------
# submit envelope
# ---------------------------------------------------------------------------

def submit_envelope(handle_key: str, b: np.ndarray, *,
                    tol: float = 1e-7,
                    deadline_s: Optional[float] = None,
                    tenant: Optional[str] = None,
                    slo_class: Optional[str] = None,
                    tag: Optional[str] = None) -> dict:
    """Client side: the ``POST /v1/submit`` body.  ``tenant`` is
    OPTIONAL and only ever a cross-check - the server derives the real
    tenant from the bearer token (a mismatch is a 403, see
    ``serve.auth``)."""
    env: dict = {
        "wire": WIRE_VERSION,
        "handle": str(handle_key),
        "b": encode_array(b),
        "tol": float(tol),
    }
    if deadline_s is not None:
        env["deadline_s"] = float(deadline_s)
    if tenant is not None:
        env["tenant"] = str(tenant)
    if slo_class is not None:
        env["slo_class"] = str(slo_class)
    if tag is not None:
        env["tag"] = str(tag)
    return env


def parse_submit(body: bytes) -> dict:
    """Server side: validate a submit body into
    ``{handle, b, tol, deadline_s, tenant, slo_class, tag}`` (absent
    optionals -> None).  Any malformation is a typed
    :class:`WireError`, which the plane maps to 400 - never a
    traceback."""
    try:
        env = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"submit body is not valid JSON: {e}",
                        code="bad_json")
    if not isinstance(env, dict):
        raise WireError("submit body must be a JSON object",
                        code="bad_request")
    if env.get("wire") != WIRE_VERSION:
        raise WireError(
            f"unsupported wire version {env.get('wire')!r} "
            f"(this server speaks {WIRE_VERSION})",
            code="bad_wire_version")
    handle = env.get("handle")
    if not isinstance(handle, str) or not handle:
        raise WireError("submit requires a 'handle' key naming a "
                        "registered operator", code="bad_handle")
    b = decode_array(env.get("b"))
    if b.ndim != 1:
        raise WireError(f"right-hand side must be a 1-D vector, got "
                        f"shape {list(b.shape)}", code="bad_vector")
    tol = env.get("tol", 1e-7)
    if not isinstance(tol, (int, float)) or not (float(tol) > 0.0):
        raise WireError(f"tol must be a positive number, got {tol!r}",
                        code="bad_request")
    deadline_s = env.get("deadline_s")
    if deadline_s is not None and (
            not isinstance(deadline_s, (int, float))
            or not (float(deadline_s) > 0.0)):
        raise WireError(f"deadline_s must be a positive number, got "
                        f"{deadline_s!r}", code="bad_request")
    out = {
        "handle": handle,
        "b": b,
        "tol": float(tol),
        "deadline_s": float(deadline_s) if deadline_s is not None
        else None,
    }
    for key in ("tenant", "slo_class", "tag"):
        val = env.get(key)
        if val is not None and not isinstance(val, str):
            raise WireError(f"{key} must be a string, got {val!r}",
                            code="bad_request")
        out[key] = val
    return out


# ---------------------------------------------------------------------------
# result envelope
# ---------------------------------------------------------------------------

def status_to_http(status: str) -> Tuple[int, Optional[str]]:
    """``(http_status, retry_semantics)`` for a terminal result status.
    ``retry_semantics`` is ``"retry_after"`` when the response should
    carry a ``Retry-After`` header sourced from the result's
    ``retry_after_s``."""
    if status == "ADMISSION_REJECTED":
        return 429, "retry_after"
    if status == "REFUSED":
        return 503, None
    if status == "ERROR":
        return 500, None
    return 200, None


def _finite_or_none(v) -> Optional[float]:
    v = float(v)
    return v if math.isfinite(v) else None


def result_envelope(result, *, request_id: Optional[str] = None,
                    include_x: bool = True) -> dict:
    """A terminal ``RequestResult`` as its wire envelope.  ``x`` rides
    bit-exact via :func:`encode_array` (or ``None`` for refusals);
    ``request_id`` - the plane's public id - may differ from the
    service-internal ``result.request_id``, which is preserved as
    ``service_request_id`` so wire results join against traces and
    usage exports."""
    env = {
        "wire": WIRE_VERSION,
        "kind": "result",
        "request_id": str(request_id if request_id is not None
                          else result.request_id),
        "service_request_id": result.request_id,
        "status": result.status,
        "converged": bool(result.converged),
        "timed_out": bool(result.timed_out),
        "iterations": int(result.iterations),
        # JSON has no spelling for NaN/inf (and the plane encodes with
        # allow_nan=False); a rejected result's residual_norm is NaN,
        # so non-finite scalars ride as null and decode back to NaN
        "residual_norm": _finite_or_none(result.residual_norm),
        "wait_s": float(result.wait_s),
        "solve_s": float(result.solve_s),
        "latency_s": float(result.latency_s),
        "bucket": int(result.bucket),
        "occupancy": float(result.occupancy),
        "solve_id": result.solve_id,
        "attempts": int(result.attempts),
        "degraded": bool(result.degraded),
        "tenant": result.tenant,
        "slo_class": result.slo_class,
        "retry_after_s": (float(result.retry_after_s)
                          if result.retry_after_s is not None
                          else None),
        "x": (encode_array(result.x)
              if include_x and result.x is not None else None),
    }
    return env


def result_from_json(env: Any) -> "Any":
    """Client side: a result envelope back into a ``RequestResult``
    (imported lazily - the codec stays importable without the service
    tier).  The reconstructed ``x`` is bit-exact."""
    from .service import RequestResult
    if not isinstance(env, dict) or env.get("kind") != "result":
        raise WireError("not a result envelope", code="bad_result")
    if env.get("wire") != WIRE_VERSION:
        raise WireError(
            f"unsupported wire version {env.get('wire')!r} "
            f"(this client speaks {WIRE_VERSION})",
            code="bad_wire_version")
    try:
        x = decode_array(env["x"]) if env.get("x") is not None \
            else None
        return RequestResult(
            request_id=str(env["request_id"]),
            status=str(env["status"]),
            converged=bool(env["converged"]),
            timed_out=bool(env["timed_out"]),
            x=x,
            iterations=int(env["iterations"]),
            residual_norm=(float(env["residual_norm"])
                           if env.get("residual_norm") is not None
                           else float("nan")),
            wait_s=float(env["wait_s"]),
            solve_s=float(env["solve_s"]),
            latency_s=float(env["latency_s"]),
            bucket=int(env["bucket"]),
            occupancy=float(env["occupancy"]),
            solve_id=env.get("solve_id"),
            attempts=int(env.get("attempts", 1)),
            degraded=bool(env.get("degraded", False)),
            tenant=str(env.get("tenant", "default")),
            slo_class=str(env.get("slo_class", "silver")),
            retry_after_s=(float(env["retry_after_s"])
                           if env.get("retry_after_s") is not None
                           else None),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed result envelope: {e}",
                        code="bad_result")


def error_envelope(message: str, *, code: str) -> dict:
    """The uniform JSON error body every non-2xx data-plane response
    carries - typed, token-free, never a traceback."""
    return {"wire": WIRE_VERSION, "kind": "error", "code": str(code),
            "error": str(message)}


__all__.append("error_envelope")
