"""``python -m cuda_mpi_parallel_tpu.cli serve`` - workload replay.

Runs a replayed (or synthesized Poisson) workload of
``(arrival_t, seed)`` requests through one registered operator and
prints the throughput / latency / occupancy report the service's
telemetry produces.  Every request's right-hand side is
``A @ x_true(seed)`` (``serve.workload.rhs_for``), so the replay
verifies each answer against a known solution - the lint gate's
acceptance surface.

Examples::

    python -m cuda_mpi_parallel_tpu.cli serve --problem poisson2d \
        --n 32 --requests 32 --rate 2000 --max-batch 8
    python -m cuda_mpi_parallel_tpu.cli serve --problem mm \
        --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
        --requests 32 --rate 2000 --trace-events trace.jsonl --json

``--listen`` turns the process into the network data plane instead
(serve.net): requests arrive over HTTP as ``serve.wire`` envelopes,
authenticated against a bearer-token keyring whose entries DERIVE the
tenant tags::

    python -m cuda_mpi_parallel_tpu.cli serve --problem poisson2d \
        --n 32 --listen --net-port 8780 --net-tokens tok1:acme
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

__all__ = ["build_serve_parser", "main"]


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_parallel_tpu serve",
        description="microbatching solver-service workload replay")
    p.add_argument("--problem", default="poisson2d",
                   choices=["poisson2d", "mm"],
                   help="operator family to register (assembled CSR)")
    p.add_argument("--n", type=int, default=32,
                   help="grid extent per axis (poisson2d)")
    p.add_argument("--file", default=None,
                   help="Matrix Market path (--problem mm)")
    p.add_argument("--mesh", type=int, default=1,
                   help="devices for the distributed batched solve "
                        "(1 = single device)")
    p.add_argument("--dtype", default="auto",
                   choices=["auto", "float32", "float64"],
                   help="solve dtype (auto: float32 on TPU, float64 "
                        "elsewhere - the main CLI's rule)")
    p.add_argument("--requests", type=int, default=32,
                   help="synthetic workload length (ignored with "
                        "--workload)")
    p.add_argument("--rate", type=float, default=2000.0,
                   help="synthetic Poisson arrival rate, requests/s")
    p.add_argument("--workload", default=None, metavar="PATH",
                   help="replay a saved workload file instead of "
                        "synthesizing one")
    p.add_argument("--save-workload", default=None, metavar="PATH",
                   dest="save_workload",
                   help="write the (synthesized) workload to PATH "
                        "before replaying - the reproducibility "
                        "artifact")
    p.add_argument("--seed", type=int, default=0,
                   help="workload synthesis seed")
    p.add_argument("--max-batch", type=int, default=8,
                   dest="max_batch",
                   help="microbatch lane cap; compiled buckets are "
                        "powers of two up to this")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   dest="max_wait_ms",
                   help="dispatch a partial batch once its oldest "
                        "request has waited this long")
    p.add_argument("--queue-limit", type=int, default=256,
                   dest="queue_limit",
                   help="bounded-queue backpressure limit (pending "
                        "requests)")
    p.add_argument("--tol", type=float, default=1e-7,
                   help="default absolute tolerance per request")
    p.add_argument("--maxiter", type=int, default=2000)
    p.add_argument("--check-every", type=int, default=1,
                   dest="check_every")
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="per-request deadline in seconds (expired "
                        "requests get typed TIMEOUT results)")
    p.add_argument("--workers", type=int, default=1,
                   help="dispatch workers sharing the compiled-solver "
                        "cache (0 = auto-size from the calibrated "
                        "machine model)")
    p.add_argument("--slo-class", default="silver", dest="slo_class",
                   choices=["gold", "silver", "bulk"],
                   help="SLO class for requests the workload does not "
                        "tag (weighted-fair dispatch at 8:4:1; gold "
                        "is never degraded or deferred)")
    p.add_argument("--admit-rate", type=float, default=None,
                   dest="admit_rate", metavar="R",
                   help="per-tenant token-bucket admission rate, "
                        "requests/s (over-rate submits resolve to "
                        "typed ADMISSION_REJECTED results with a "
                        "retry_after_s hint)")
    p.add_argument("--admit-burst", type=float, default=None,
                   dest="admit_burst", metavar="B",
                   help="token-bucket burst size (default: 2x the "
                        "admission rate)")
    p.add_argument("--shed", default=None, metavar="D1,D2,D3",
                   help="shed-ladder queue depths "
                        "degrade,defer,reject (0 disables a rung); "
                        "'auto' derives them from the measured "
                        "capacity estimate")
    p.add_argument("--precond", default="none",
                   choices=["none", "jacobi"],
                   help="batched-tier preconditioner")
    p.add_argument("--method", default="batched",
                   choices=["batched", "block"],
                   help="batched recurrence (solver.many)")
    p.add_argument("--exchange", default=None,
                   choices=["auto", "gather", "allgather"],
                   help="distributed halo wire (--mesh > 1)")
    p.add_argument("--plan", default="even", metavar="auto|even",
                   help="partition planning for --mesh > 1: 'auto' "
                        "runs balance.plan_partition ONCE at "
                        "registration, 'even' (default) keeps the "
                        "uniform split")
    p.add_argument("--recycle", nargs="?", const=0, default=None,
                   type=int, metavar="K",
                   help="Krylov-subspace recycling (solver.recycle): "
                        "harvest a K-dimensional Ritz space from early "
                        "dispatches of each handle and deflate later "
                        "ones - repeat traffic gets measurably faster "
                        "every solve (bare flag: K=8).  Needs --method "
                        "batched")
    p.add_argument("--phase-profile", nargs="?", const=0, default=None,
                   type=int, metavar="R", dest="phase_profile",
                   help="measure the registered operator's phase "
                        "profile at warmup (telemetry.phasetrace: "
                        "halo / per-shard spmv / reduction walls, R "
                        "chained reps per phase - default "
                        "phasetrace.DEFAULT_REPEATS) and report it; "
                        "needs --mesh > 1.  Profiling runs once at "
                        "registration, never inside request latency")
    p.add_argument("--trace-events", default=None, metavar="PATH",
                   dest="trace_events",
                   help="append the service + solve event stream "
                        "(request_enqueued/batch_dispatch/"
                        "request_done/...) to PATH")
    p.add_argument("--usage", default=None, metavar="PATH",
                   help="meter per-tenant usage (device-seconds, wire "
                        "bytes, batch iterations; serve.usage) and "
                        "export the ledger as JSONL to PATH after the "
                        "replay (tools/usage_report.py renders and "
                        "cross-checks it)")
    p.add_argument("--metrics", action="store_true",
                   help="print the metrics registry (Prometheus text, "
                        "incl. serve_* gauges and latency "
                        "percentiles) after the replay")
    p.add_argument("--ops-port", type=int, default=None,
                   dest="ops_port", metavar="PORT",
                   help="serve the read-only HTTP ops plane "
                        "(/metrics, /healthz, /readyz, /stats, "
                        "/usage, /traces/<id>, /events) on PORT for "
                        "the duration of the replay (0 = ephemeral; "
                        "the bound URL is announced on stderr)")
    p.add_argument("--ops-token", default=None, dest="ops_token",
                   metavar="TOKEN",
                   help="static bearer token gating every ops route "
                        "(401 without it)")
    p.add_argument("--listen", action="store_true",
                   help="serve the authenticated network data plane "
                        "(serve.net: POST /v1/submit, /v1/solve, "
                        "GET /v1/result/<id>, /v1/stream SSE, "
                        "/v1/handles) over the registered operator "
                        "instead of replaying a workload locally; "
                        "runs until SIGTERM/SIGINT or "
                        "--listen-duration.  Requires --net-tokens or "
                        "--net-keyring")
    p.add_argument("--net-port", type=int, default=0, dest="net_port",
                   metavar="PORT",
                   help="data-plane port (--listen; 0 = ephemeral; "
                        "the bound URL is announced on stderr)")
    p.add_argument("--net-host", default="127.0.0.1", dest="net_host",
                   metavar="HOST", help="data-plane bind host")
    p.add_argument("--net-tokens", default=None, dest="net_tokens",
                   metavar="SPEC",
                   help="inline bearer keyring: "
                        "'token:tenant[:class+class...]' entries, "
                        "comma-separated (serve.auth.TokenKeyring."
                        "from_spec).  Tenant tags are DERIVED from "
                        "these tokens - a submit claiming another "
                        "tenant is a typed 403")
    p.add_argument("--net-keyring", default=None, dest="net_keyring",
                   metavar="PATH",
                   help="JSON keyring file (serve.auth.TokenKeyring."
                        "from_file) - the non-inline spelling of "
                        "--net-tokens")
    p.add_argument("--listen-duration", type=float, default=None,
                   dest="listen_duration", metavar="S",
                   help="exit the data plane after S seconds "
                        "(default: run until SIGTERM/SIGINT)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON record instead of text")
    p.add_argument("--report", nargs="?", const="-", default=None,
                   metavar="PATH",
                   help="emit the service replay report (the solver "
                        "service section of telemetry.report); PATH "
                        "writes it, bare --report prints it (or, with "
                        "--json, attaches it as report_text)")
    return p


def _build_operator(args):
    import jax.numpy as jnp

    from ..models import mmio, poisson

    dtype = jnp.dtype(args.dtype)
    if args.problem == "mm":
        if not args.file:
            raise SystemExit("--problem mm requires --file")
        a = mmio.load_matrix_market(args.file, dtype=dtype)
        return a, f"MatrixMarket {args.file}"
    n = args.n
    return poisson.poisson_2d_csr(n, n, dtype=dtype), \
        f"2D Poisson {n}x{n}"


def main(argv=None) -> int:
    args = build_serve_parser().parse_args(argv)
    if args.mesh > 1:
        from ..cli import _ensure_virtual_devices

        _ensure_virtual_devices(args.mesh)
    if args.requests < 1:
        raise SystemExit(f"--requests must be >= 1, got {args.requests}")
    if args.max_batch < 1:
        raise SystemExit(f"--max-batch must be >= 1, got "
                         f"{args.max_batch}")
    if args.max_wait_ms < 0:
        raise SystemExit(f"--max-wait-ms must be >= 0, got "
                         f"{args.max_wait_ms}")
    if args.mesh <= 1 and args.exchange is not None:
        raise SystemExit("--exchange needs --mesh > 1")
    if args.phase_profile is not None:
        if args.mesh <= 1:
            raise SystemExit("--phase-profile needs --mesh > 1 (the "
                             "profiler times the distributed halo/"
                             "spmv/reduction phases)")
        if args.phase_profile < 0:
            raise SystemExit(f"--phase-profile reps must be >= 0, got "
                             f"{args.phase_profile} (0/bare flag = the "
                             f"default rep count)")
    if args.recycle is not None:
        if args.recycle < 0:
            raise SystemExit(f"--recycle K must be >= 0, got "
                             f"{args.recycle} (0/bare flag = the "
                             f"default space dimension)")
        if args.method != "batched":
            raise SystemExit(
                "--recycle needs --method batched (block-CG deflates "
                "rank collapse in-lane and carries no per-lane "
                "Lanczos harvest)")
    if args.mesh <= 1 and args.plan != "even":
        raise SystemExit("--plan needs --mesh > 1")
    if args.plan not in ("even", "auto"):
        raise SystemExit(f"--plan must be 'even' or 'auto', got "
                         f"{args.plan!r}")
    keyring = None
    if args.listen:
        from .auth import TokenKeyring

        if args.net_tokens and args.net_keyring:
            raise SystemExit("--net-tokens and --net-keyring are "
                             "mutually exclusive")
        try:
            if args.net_tokens:
                keyring = TokenKeyring.from_spec(args.net_tokens)
            elif args.net_keyring:
                keyring = TokenKeyring.from_file(args.net_keyring)
        except (OSError, ValueError) as e:
            raise SystemExit(f"keyring: {e}")
        if keyring is None:
            raise SystemExit(
                "--listen requires --net-tokens or --net-keyring "
                "(an unauthenticated data plane would take tenant "
                "tags on trust)")
    elif args.net_tokens or args.net_keyring \
            or args.listen_duration is not None:
        raise SystemExit("--net-tokens/--net-keyring/"
                         "--listen-duration need --listen")

    from .. import telemetry

    if args.trace_events:
        telemetry.configure(args.trace_events)
    if args.metrics or args.report is not None:
        telemetry.force_active(True)

    import jax

    if args.dtype == "auto":
        args.dtype = ("float32"
                      if jax.default_backend() == "tpu" else "float64")
    if args.dtype == "float64":
        jax.config.update("jax_enable_x64", True)

    from ..telemetry import report as treport
    from ..telemetry.registry import REGISTRY
    from ..utils.logging import emit_json, sanitize
    from . import workload as wl
    from .service import ServiceConfig, SolverService

    a, desc = _build_operator(args)

    if args.workload:
        requests = wl.load_workload(args.workload)
    else:
        requests = wl.synthetic_poisson(
            args.requests, args.rate, seed=args.seed, tol=None,
            deadline_s=None)
    if args.save_workload:
        wl.save_workload(args.save_workload, requests)

    precond = None if args.precond == "none" else args.precond
    recycle_policy = None
    if args.recycle is not None:
        from .service import RecyclePolicy
        from ..solver.recycle import DEFAULT_K

        recycle_policy = RecyclePolicy(k=args.recycle or DEFAULT_K)
    admission = None
    if args.admit_rate is not None:
        from .admission import AdmissionConfig, TokenBucket

        if args.admit_rate <= 0:
            raise SystemExit(f"--admit-rate must be > 0, got "
                             f"{args.admit_rate}")
        burst = args.admit_burst if args.admit_burst is not None \
            else max(2.0 * args.admit_rate, 1.0)
        admission = AdmissionConfig(
            default=TokenBucket(rate=args.admit_rate, burst=burst))
    elif args.admit_burst is not None:
        raise SystemExit("--admit-burst needs --admit-rate")
    shed = None
    if args.shed is not None:
        from .admission import ShedConfig

        if args.shed == "auto":
            shed = ShedConfig(auto=True)
        else:
            try:
                d1, d2, d3 = (int(v) for v in args.shed.split(","))
            except ValueError:
                raise SystemExit(
                    f"--shed expects D1,D2,D3 depths or 'auto', got "
                    f"{args.shed!r}")
            try:
                shed = ShedConfig(degrade_depth=d1, defer_depth=d2,
                                  reject_depth=d3)
            except ValueError as e:
                raise SystemExit(f"--shed: {e}")
    if args.workers < 0:
        raise SystemExit(f"--workers must be >= 0, got {args.workers}")
    service = SolverService(ServiceConfig(
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1e3,
        queue_limit=args.queue_limit, maxiter=args.maxiter,
        check_every=args.check_every, recycle=recycle_policy,
        admission=admission, shed=shed, workers=args.workers,
        usage=args.usage is not None,
        ops_port=args.ops_port, ops_token=args.ops_token))
    if service.ops_server() is not None:
        # stderr: --json owns stdout, and scrapers need the bound
        # port BEFORE the replay finishes (0 = ephemeral)
        print(f"ops plane: {service.ops_server().url}",
              file=sys.stderr, flush=True)
    mesh = None
    if args.mesh > 1:
        from ..parallel import make_mesh

        mesh = make_mesh(args.mesh)
    profile_reps = 0
    if args.phase_profile is not None:
        from ..telemetry.phasetrace import DEFAULT_REPEATS

        profile_reps = args.phase_profile or DEFAULT_REPEATS
        if args.trace_events is None:
            # the profile event/gauges are the point of profiling a
            # registration; without a sink the gauges still need the
            # derived-work opt-in
            telemetry.force_active(True)
    handle = service.register(
        a, mesh=mesh,
        plan="auto" if args.plan == "auto" else None,
        exchange=args.exchange, precond=precond,
        method=args.method, phase_profile=profile_reps)

    if args.listen:
        # --listen: the process IS the server.  The plane starts only
        # after registration (a client never sees an empty handle
        # list), the bound URL is announced on stderr (--json owns
        # stdout), and SIGTERM/SIGINT/--listen-duration shuts down
        # gracefully: stop accepting, drain in-flight work, exit 0.
        import signal
        import threading

        net = service.serve_net(args.net_port, host=args.net_host,
                                keyring=keyring)
        print(f"data plane: {net.url}", file=sys.stderr, flush=True)
        stop = threading.Event()

        def _graceful(signum, frame):
            stop.set()

        old_term = signal.signal(signal.SIGTERM, _graceful)
        old_int = signal.signal(signal.SIGINT, _graceful)
        try:
            stop.wait(timeout=args.listen_duration)
        finally:
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)
        served = net.request_count()
        service.close()
        if args.usage is not None:
            service.usage_ledger().export_jsonl(args.usage)
        if args.json:
            emit_json(sanitize({
                "mode": "serve-listen", "problem": desc,
                "n": int(a.shape[0]), "mesh": args.mesh,
                "dtype": args.dtype, "handle": handle.key,
                "tenants": list(keyring.tenants()),
                "http_requests": served,
                "stats": service.stats(),
            }))
        else:
            print(f"data plane served {served} HTTP request(s)",
                  file=sys.stderr, flush=True)
        return 0

    # pre-build every request's (b, x_true) so the replay loop does
    # nothing but sleep and submit - RHS construction must not distort
    # the arrival process
    prepared = []
    for r in requests:
        b, x_true = wl.rhs_for(a, r.seed, dtype=np.dtype(args.dtype))
        prepared.append((r, b, x_true))

    from .queue import QueueFull

    t0 = time.monotonic()
    futures = []
    rejected = 0
    for r, b, _ in prepared:
        delay = (t0 + r.t) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            futures.append(service.submit(
                handle, b,
                tol=r.tol if r.tol is not None else args.tol,
                deadline_s=(r.deadline_s if r.deadline_s is not None
                            else args.deadline),
                tenant=r.tenant or "default",
                slo_class=r.slo_class or args.slo_class))
        except QueueFull:
            # backpressure: the offered load beat the queue bound -
            # count the shed request and keep replaying (an aborted
            # replay would lose every resolved result and the
            # report).  Shed requests still fail the replay's
            # converged_all / exit-code verdict below: the workload
            # was NOT fully solved, and a green exit must not say it
            # was.
            rejected += 1
            futures.append(None)
    service.drain()
    window_s = time.monotonic() - t0
    service.close()

    per_request = []
    worst_err = 0.0
    all_ok = True
    for (r, _, x_true), fut in zip(prepared, futures):
        if fut is None:
            per_request.append({
                "arrival_t": r.t, "seed": r.seed,
                "status": "REJECTED", "converged": False,
                "timed_out": False})
            all_ok = False
            continue
        res = fut.result()
        entry = {
            "request_id": res.request_id, "arrival_t": r.t,
            "seed": r.seed, "status": res.status,
            "converged": res.converged, "timed_out": res.timed_out,
            "iterations": res.iterations,
            "residual_norm": res.residual_norm,
            "wait_s": res.wait_s, "solve_s": res.solve_s,
            "latency_s": res.latency_s, "bucket": res.bucket,
            "occupancy": res.occupancy, "solve_id": res.solve_id,
            "tenant": res.tenant, "slo_class": res.slo_class,
        }
        if res.retry_after_s is not None:
            entry["retry_after_s"] = res.retry_after_s
        if res.x is not None:
            err = float(np.max(np.abs(res.x - x_true)))
            entry["max_abs_error"] = err
            worst_err = max(worst_err, err)
        if not res.timed_out and not res.converged:
            all_ok = False
        per_request.append(entry)

    stats = service.stats()
    if args.usage is not None:
        service.usage_ledger().export_jsonl(args.usage)
    solved = sum(1 for e in per_request
                 if e["converged"] and not e["timed_out"])
    stats["solved_rhs_per_sec"] = solved / max(window_s, 1e-9)
    stats["replay_window_s"] = window_s
    stats["rejected"] = rejected
    if args.mesh > 1:
        # the zero-retrace proof: every post-warmup dispatch must hit
        # the compiled-solver cache (phase-labeled counters split the
        # registration warmup from live traffic)
        stats["dist_cache_misses_postwarm"] = \
            REGISTRY.counter("dist_solver_cache_misses_total",
                             labelnames=("phase",)).value(phase="solve")

    record = sanitize({
        "mode": "serve",
        "problem": desc,
        "n": int(a.shape[0]),
        "mesh": args.mesh,
        "dtype": args.dtype,
        "handle": handle.key,
        "max_batch": args.max_batch,
        "max_wait_s": args.max_wait_ms / 1e3,
        "workers": args.workers,
        "slo_class_default": args.slo_class,
        "admission": ({"rate": args.admit_rate,
                       "burst": (args.admit_burst
                                 if args.admit_burst is not None
                                 else max(2.0 * args.admit_rate, 1.0))}
                      if args.admit_rate is not None else None),
        "shed": args.shed,
        "method": args.method,
        "precond": args.precond,
        "plan": (handle.plan.label if handle.plan is not None
                 else "even"),
        # the lane the solve ACTUALLY ran (the main CLI's
        # priced-honestly convention), beside the requested flag
        "exchange": (handle.dispatcher.resolved_exchange
                     if handle.dispatcher is not None else None),
        "exchange_requested": args.exchange,
        "stats": stats,
        **({"recycle": stats.get("recycle")}
           if args.recycle is not None else {}),
        "requests": per_request,
        "max_abs_error": worst_err,
        "converged_all": all_ok,
        "batches": service.batch_log(),
        **({"phase_profile": handle.phase_profile.to_json()}
           if handle.phase_profile is not None else {}),
    })
    if args.metrics and args.json:
        record["metrics"] = REGISTRY.snapshot()

    report_text = (f"== solver service replay: {desc} "
                   f"(mesh={args.mesh}, {args.dtype}) ==\n"
                   + "\n".join(treport.service_lines(stats)) + "\n"
                   + f"accuracy: max request error {worst_err:.3e}\n")
    ustats = stats.get("usage")
    if ustats is not None:
        tot = ustats["totals"]
        report_text += (
            f"usage   : {tot['batches']} batch(es), "
            f"{tot['device_seconds']:.6f} device-s, "
            f"{tot['wire_bytes']:.3e} wire bytes, reconcile "
            f"{ustats['reconcile_max_rel_err']:.2e} "
            f"-> {args.usage}\n")
    rstats = stats.get("recycle")
    if rstats is not None:
        first = rstats.get("first_solve_iterations")
        last = rstats.get("last_solve_iterations")
        report_text += (
            f"recycle : {rstats['harvests']} harvest(s), "
            f"{rstats['applied']} deflated dispatch(es), iters/solve "
            f"{first if first is not None else '?'} -> "
            f"{last if last is not None else '?'}\n")
    if handle.phase_profile is not None:
        report_text += ("-- phase profile (measured at warmup) --\n"
                        + "\n".join(treport.phase_lines(
                            handle.phase_profile.to_json())) + "\n")
    if args.report is not None and args.report != "-":
        with open(args.report, "w", encoding="utf-8") as f:
            f.write(report_text)
    if args.json:
        if args.report == "-":
            # bare --report with --json: stdout is the JSON record, so
            # the requested report rides it (same pattern as the main
            # CLI's record["solve_report"]) instead of being dropped
            record["report_text"] = report_text
        emit_json(record)
    else:
        print(report_text, end="")
        if args.metrics:
            # THE ops-plane formatter (serve.ops.prometheus_exposition):
            # the one-shot dump is byte-identical to a /metrics scrape
            from .ops import prometheus_exposition

            print("--- metrics (prometheus text) ---")
            print(prometheus_exposition(), end="")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
