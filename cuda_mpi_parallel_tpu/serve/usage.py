"""Metered per-tenant usage attribution for the solver service.

The serve tier batches many tenants' requests into one solve - which
is the whole value, and also why nobody can answer "what did tenant X
cost us this hour?".  This module meters each dispatched batch and
apportions it across the lanes that shared it:

* **device-seconds** = solve wall x mesh size (a 4-shard mesh burns
  four device-seconds per wall second whether or not every lane
  needed them);
* **batch iterations** = the iterations the batch actually ran (the
  max over live lanes - batched CG runs every column until the last
  one is done, so a lane occupies its column for the full sweep);
* **wire bytes** = the solve's measured per-iteration communication
  volume (``dist_cg.last_comm_cost``'s jaxpr-derived totals) x batch
  iterations.

Apportionment is an equal split across the live lanes with the
remainder assigned to the last lane, so the accounting identity holds
to float round-off: summed per-tenant device-seconds and wire bytes
reconcile with the batch-level totals (``reconcile()``, gated at
1e-9 in tools/lint.sh).  Equal split is the honest cost model here -
a lane that converged early still occupied its batch column for the
whole sweep, and padding lanes are overhead amortized over the real
requests that caused the batch.

Host-side bookkeeping only (plain Python floats, post-solve): with
``ServiceConfig(usage=False)`` (the default) no ledger exists and the
solve body is jaxpr-bit-identical - same contract as tracing.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry import events
from ..utils.logging import sanitize

__all__ = ["UsageLedger"]


class UsageLedger:
    """Thread-safe per-tenant usage meter; one per SolverService.

    ``note_batch`` is called once per dispatched batch from the
    service's post-solve bookkeeping (success AND error paths - a
    failed batch burned real device-seconds and somebody caused it).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._batches: List[Dict[str, Any]] = []
        self._requests: List[Dict[str, Any]] = []

    # -- metering ------------------------------------------------------

    def note_batch(self, *, solve_id: Optional[str], handle: str,
                   solve_s: float, mesh_size: int,
                   batch_iterations: int,
                   wire_bytes_per_iteration: float,
                   lanes: Sequence[Dict[str, Any]]) -> None:
        """Meter one dispatched batch and apportion it across lanes.

        ``lanes`` carries one dict per LIVE request in the batch
        (padding columns excluded): ``request_id``, ``tenant``,
        ``slo_class``, ``iterations`` (that lane's own count),
        ``trace_id`` (None untraced).  Totals are computed here so the
        caller cannot hand in an inconsistent split.
        """
        m = len(lanes)
        if m == 0:
            return
        device_seconds = float(solve_s) * max(int(mesh_size), 1)
        wire_bytes = float(wire_bytes_per_iteration) \
            * max(int(batch_iterations), 0)
        shares = _apportion(device_seconds, m)
        wire_shares = _apportion(wire_bytes, m)
        iter_shares = _apportion(float(batch_iterations), m)
        batch_rec = {
            "solve_id": solve_id, "handle": handle,
            "n_requests": m,
            "solve_s": float(solve_s),
            "mesh_size": max(int(mesh_size), 1),
            "batch_iterations": int(batch_iterations),
            "device_seconds": device_seconds,
            "wire_bytes": wire_bytes,
        }
        request_recs = []
        per_tenant_shares: Dict[str, float] = {}
        for j, lane in enumerate(lanes):
            tenant = str(lane.get("tenant", "default"))
            rec = {
                "request_id": lane.get("request_id"),
                "tenant": tenant,
                "slo_class": str(lane.get("slo_class", "silver")),
                "solve_id": solve_id,
                "handle": handle,
                "trace_id": lane.get("trace_id"),
                "iterations": int(lane.get("iterations", 0)),
                "batch_iterations_share": iter_shares[j],
                "device_seconds": shares[j],
                "wire_bytes": wire_shares[j],
                "batch_n_requests": m,
            }
            request_recs.append(rec)
            per_tenant_shares[tenant] = \
                per_tenant_shares.get(tenant, 0.0) + shares[j]
        with self._lock:
            self._batches.append(batch_rec)
            self._requests.extend(request_recs)
        events.emit(
            "usage", solve_id=solve_id, handle=handle, n_requests=m,
            device_seconds=device_seconds, wire_bytes=wire_bytes,
            batch_iterations=int(batch_iterations),
            mesh_size=batch_rec["mesh_size"],
            per_tenant_device_seconds={
                t: round(v, 9) for t, v in
                sorted(per_tenant_shares.items())})

    # -- readout -------------------------------------------------------

    def per_tenant(self) -> Dict[str, Dict[str, float]]:
        """Accumulated usage keyed by tenant (fsum'd, so the identity
        against :meth:`batch_totals` holds to double round-off)."""
        with self._lock:
            requests = list(self._requests)
        acc: Dict[str, Dict[str, List[float]]] = {}
        for rec in requests:
            t = acc.setdefault(rec["tenant"], {
                "requests": [], "device_seconds": [],
                "wire_bytes": [], "batch_iterations_share": []})
            t["requests"].append(1.0)
            t["device_seconds"].append(rec["device_seconds"])
            t["wire_bytes"].append(rec["wire_bytes"])
            t["batch_iterations_share"].append(
                rec["batch_iterations_share"])
        return {
            tenant: {
                "requests": int(math.fsum(v["requests"])),
                "device_seconds": math.fsum(v["device_seconds"]),
                "wire_bytes": math.fsum(v["wire_bytes"]),
                "batch_iterations_share": math.fsum(
                    v["batch_iterations_share"]),
            }
            for tenant, v in sorted(acc.items())
        }

    def batch_totals(self) -> Dict[str, float]:
        """Ground truth the per-tenant sums must reconcile against."""
        with self._lock:
            batches = list(self._batches)
        return {
            "batches": len(batches),
            "requests": int(math.fsum(b["n_requests"]
                                      for b in batches)),
            "device_seconds": math.fsum(b["device_seconds"]
                                        for b in batches),
            "wire_bytes": math.fsum(b["wire_bytes"] for b in batches),
            "batch_iterations": int(math.fsum(b["batch_iterations"]
                                              for b in batches)),
        }

    def reconcile(self) -> float:
        """Max relative mismatch between summed per-tenant usage and
        the batch-level totals, over device-seconds and wire bytes.
        The accounting identity: this is ~1e-16 territory, gated at
        1e-9 by tools/lint.sh."""
        tenants = self.per_tenant()
        totals = self.batch_totals()
        worst = 0.0
        for field in ("device_seconds", "wire_bytes"):
            total = totals[field]
            summed = math.fsum(v[field] for v in tenants.values())
            scale = max(abs(total), 1.0)
            worst = max(worst, abs(summed - total) / scale)
        return worst

    def records(self) -> List[Dict[str, Any]]:
        """The per-request usage records (copies)."""
        with self._lock:
            return [dict(r) for r in self._requests]

    def snapshot(self) -> Dict[str, Any]:
        """The stats() section: totals + per-tenant roll-up + the
        reconciliation residual."""
        return {
            "totals": self.batch_totals(),
            "per_tenant": self.per_tenant(),
            "reconcile_max_rel_err": self.reconcile(),
        }

    def export_jsonl(self, path: str) -> int:
        """Write the ledger as strict JSONL: one ``kind="request"``
        line per metered request, one ``kind="batch"`` line per batch,
        and a final ``kind="summary"`` roll-up (what
        ``tools/usage_report.py`` re-derives and cross-checks).
        Returns the number of lines written.
        """
        with self._lock:
            requests = [dict(r) for r in self._requests]
            batches = [dict(b) for b in self._batches]
        lines = 0
        with open(path, "w", encoding="utf-8") as f:
            for rec in requests:
                f.write(json.dumps(sanitize({"kind": "request", **rec}),
                                   allow_nan=False, sort_keys=True)
                        + "\n")
                lines += 1
            for rec in batches:
                f.write(json.dumps(sanitize({"kind": "batch", **rec}),
                                   allow_nan=False, sort_keys=True)
                        + "\n")
                lines += 1
            summary = {"kind": "summary",
                       "totals": self.batch_totals(),
                       "per_tenant": self.per_tenant(),
                       "reconcile_max_rel_err": self.reconcile()}
            f.write(json.dumps(sanitize(summary), allow_nan=False,
                               sort_keys=True) + "\n")
            lines += 1
        return lines


def _apportion(total: float, m: int) -> List[float]:
    """Equal split of ``total`` over ``m`` lanes, remainder to the
    last lane so ``fsum(shares) == total`` to double round-off."""
    if m == 1:
        return [float(total)]
    share = float(total) / m
    head = [share] * (m - 1)
    return head + [float(total) - math.fsum(head)]
