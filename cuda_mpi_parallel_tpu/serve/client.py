"""Stdlib client for the serve-tier network data plane.

``urllib.request`` only - the client side of ``serve.net`` with the
same zero-dependency rule as the server.  :class:`NetClient` speaks
the ``serve.wire`` envelopes, maps the plane's honest backpressure
back into typed results, and retries 429 by HONORING the server's
``Retry-After`` (capped exponential backoff only when the server did
not say; a client that ignores the hint re-creates the thundering
herd that admission control exists to break up).

``sleep`` is injectable so tests can record the backoff schedule with
a fake instead of actually waiting.

:meth:`NetClient.replay_workload` is the end-to-end correctness
instrument: it replays a saved workload OVER THE WIRE and classifies
the outcomes through the same ``serve.workload.summarize_replay`` the
in-process replay uses, so a loopback replay's per-request
``(status, iterations, max_abs_error)`` can be compared exactly
against the no-network replay of the same file.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence, Union

import numpy as np

from . import wire

__all__ = ["NetClient", "NetError"]


class NetError(Exception):
    """A typed client-side failure: transport trouble, an error
    envelope the retry policy cannot absorb, or retries exhausted.
    ``status`` is the last HTTP status (0 = no response at all)."""

    def __init__(self, message: str, *, status: int = 0,
                 code: str = "net_error"):
        super().__init__(message)
        self.status = int(status)
        self.code = str(code)


class _Response:
    """One decoded HTTP exchange (status + parsed JSON body +
    headers), whether urllib surfaced it as a return or an
    HTTPError."""

    __slots__ = ("status", "body", "headers")

    def __init__(self, status: int, body, headers):
        self.status = int(status)
        self.body = body
        self.headers = headers

    def retry_after_s(self) -> Optional[float]:
        val = self.headers.get("Retry-After") if self.headers \
            else None
        if val is None:
            return None
        try:
            return max(float(val), 0.0)
        except (TypeError, ValueError):
            return None


class NetClient:
    """A connection to one data plane: base URL + the caller's bearer
    token.  Thread-compatible (no shared mutable state beyond config);
    every method raises :class:`NetError` on transport failure and
    returns typed values otherwise.
    """

    def __init__(self, base_url: str, token: str, *,
                 timeout_s: float = 60.0,
                 max_retries: int = 5,
                 backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 sleep=time.sleep):
        self.base_url = str(base_url).rstrip("/")
        self._token = str(token)
        self.timeout_s = float(timeout_s)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._sleep = sleep

    # -- transport -----------------------------------------------------

    def _request(self, method: str, path: str,
                 payload: Optional[dict] = None,
                 timeout_s: Optional[float] = None) -> _Response:
        data = None
        headers = {"Authorization": f"Bearer {self._token}",
                   "Accept": "application/json"}
        if payload is not None:
            data = json.dumps(payload, allow_nan=False
                              ).encode("utf-8")
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self.base_url + path, data=data, headers=headers,
            method=method)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_s if timeout_s is not None
                    else self.timeout_s) as resp:
                return _Response(resp.status, self._decode(resp),
                                 resp.headers)
        except urllib.error.HTTPError as e:
            # non-2xx: still a typed envelope, not an exception - the
            # caller decides what the status means
            return _Response(e.code, self._decode(e), e.headers)
        except urllib.error.URLError as e:
            raise NetError(f"cannot reach {self.base_url}: "
                           f"{e.reason}", code="unreachable")

    @staticmethod
    def _decode(resp):
        raw = resp.read()
        if not raw:
            return None
        try:
            return json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None

    def _backoff(self, attempt: int,
                 hint: Optional[float]) -> float:
        """Server hint verbatim when given; otherwise capped
        exponential."""
        if hint is not None:
            return hint
        return min(self.backoff_s * (2.0 ** attempt),
                   self.max_backoff_s)

    @staticmethod
    def _error_of(resp: _Response) -> str:
        body = resp.body if isinstance(resp.body, dict) else {}
        return str(body.get("error", f"HTTP {resp.status}"))

    # -- the API -------------------------------------------------------

    def handles(self) -> List[dict]:
        """The operators this plane serves (``GET /v1/handles``)."""
        resp = self._request("GET", "/v1/handles")
        if resp.status != 200 or not isinstance(resp.body, dict):
            raise NetError(self._error_of(resp), status=resp.status,
                           code="handles_failed")
        return list(resp.body.get("handles", ()))

    def submit(self, handle_key: str, b: np.ndarray, *,
               tol: float = 1e-7,
               deadline_s: Optional[float] = None,
               slo_class: Optional[str] = None,
               tenant: Optional[str] = None,
               retry: bool = True) -> Union[str, "object"]:
        """``POST /v1/submit``: a pending request's net id (str), or
        the terminal ``RequestResult`` when the service answered at
        the door (admission 429 / breaker 503 / a synchronously
        resolved request).

        429 with ``retry=True`` sleeps per ``Retry-After`` and
        retries up to ``max_retries`` times; the LAST rejection comes
        back as its typed ``ADMISSION_REJECTED`` result rather than
        raising - the same contract as the in-process future.  A 503
        whose body is a typed result envelope (breaker ``REFUSED``)
        is returned as that result; a 503 error envelope (queue full,
        service closed) raises :class:`NetError` with the server's
        ``code`` - the wire spelling of the exceptions
        ``service.submit()`` raises in-process.
        """
        payload = wire.submit_envelope(
            handle_key, b, tol=tol, deadline_s=deadline_s,
            tenant=tenant, slo_class=slo_class)
        attempts = 0
        while True:
            resp = self._request("POST", "/v1/submit", payload)
            body = resp.body if isinstance(resp.body, dict) else {}
            if resp.status == 202 and body.get("kind") == "pending":
                return str(body["request_id"])
            if body.get("kind") == "result":
                result = wire.result_from_json(body)
                if resp.status == 429 and retry \
                        and attempts < self.max_retries:
                    self._sleep(self._backoff(
                        attempts,
                        resp.retry_after_s()
                        if resp.retry_after_s() is not None
                        else result.retry_after_s))
                    attempts += 1
                    continue
                return result
            raise NetError(self._error_of(resp), status=resp.status,
                           code=str(body.get("code", "submit_failed")))

    def result(self, request_id: str, *,
               timeout_s: Optional[float] = None,
               poll_s: float = 30.0):
        """Long-poll ``GET /v1/result/<id>`` until terminal; raises
        :class:`NetError` on 404/403 or when ``timeout_s`` elapses
        (``None`` = wait forever)."""
        deadline = (time.monotonic() + float(timeout_s)
                    if timeout_s is not None else None)
        while True:
            wait = float(poll_s)
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise NetError(
                        f"result {request_id} still pending after "
                        f"{timeout_s}s", code="poll_timeout")
                wait = min(wait, left)
            resp = self._request(
                "GET",
                f"/v1/result/{request_id}?timeout_s={wait:.3f}",
                timeout_s=max(self.timeout_s, wait + 10.0))
            body = resp.body if isinstance(resp.body, dict) else {}
            if body.get("kind") == "result":
                return wire.result_from_json(body)
            if resp.status == 202:
                continue
            raise NetError(self._error_of(resp), status=resp.status,
                           code=str(body.get("code", "result_failed")))

    def solve(self, handle_key: str, b: np.ndarray, *,
              tol: float = 1e-7,
              deadline_s: Optional[float] = None,
              slo_class: Optional[str] = None,
              timeout_s: Optional[float] = None):
        """Synchronous convenience: submit (with 429 backoff) and wait
        for the terminal ``RequestResult``."""
        out = self.submit(handle_key, b, tol=tol,
                          deadline_s=deadline_s, slo_class=slo_class)
        if isinstance(out, str):
            return self.result(out, timeout_s=timeout_s)
        return out

    def stream(self, ids: Optional[Sequence[str]] = None,
               timeout_s: Optional[float] = None) -> Iterator[object]:
        """``GET /v1/stream``: yield terminal ``RequestResult``s for
        this client's tenant as the server pushes them (bounded by
        ``ids`` when given - the iterator ends once all are seen)."""
        path = "/v1/stream"
        if ids:
            path += "?ids=" + ",".join(str(i) for i in ids)
        req = urllib.request.Request(
            self.base_url + path,
            headers={"Authorization": f"Bearer {self._token}",
                     "Accept": "text/event-stream"})
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout_s if timeout_s is not None
                else self.timeout_s)
        except urllib.error.HTTPError as e:
            body = self._decode(e)
            raise NetError(
                str((body or {}).get("error", f"HTTP {e.code}")),
                status=e.code, code="stream_failed")
        except urllib.error.URLError as e:
            raise NetError(f"cannot reach {self.base_url}: "
                           f"{e.reason}", code="unreachable")
        want = {str(i) for i in ids} if ids else None
        seen = set()
        with resp:
            data_lines: List[str] = []
            for raw in resp:
                line = raw.decode("utf-8").rstrip("\n").rstrip("\r")
                if line.startswith(":"):
                    continue                      # keepalive
                if line.startswith("data:"):
                    data_lines.append(line[5:].lstrip())
                    continue
                if line == "" and data_lines:
                    env = json.loads("\n".join(data_lines))
                    data_lines = []
                    result = wire.result_from_json(env)
                    yield result
                    seen.add(env["request_id"])
                    if want is not None and seen >= want:
                        return

    # -- the end-to-end instrument -------------------------------------

    def replay_workload(self, handle_key: str, requests,
                        prepared_b, *, tol: float = 1e-7,
                        deadline_s: Optional[float] = None,
                        classes=None):
        """Open-loop replay of a saved workload OVER THE WIRE,
        classified by the same ``serve.workload.summarize_replay`` the
        in-process replay uses.

        Submits each request at its arrival offset on the real clock
        (NO 429 retry - an admission rejection is an outcome to count,
        exactly as in-process), then collects every pending result.
        A 503 queue-full maps to a ``None`` entry, the in-process
        spelling of a hard backpressure shed.  Returns the same
        ``ReplaySummary`` shape, so `(status, iterations)` tuples are
        directly comparable."""
        from .workload import summarize_replay

        t0 = time.monotonic()
        outcomes: List[object] = []    # str net_id | result | None
        for r, b in zip(requests, prepared_b):
            delay = (t0 + r.t) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                outcomes.append(self.submit(
                    handle_key, b,
                    tol=r.tol if r.tol is not None else tol,
                    deadline_s=(r.deadline_s
                                if r.deadline_s is not None
                                else deadline_s),
                    slo_class=r.slo_class,
                    retry=False))
            except NetError as e:
                if e.code == "queue_full":
                    outcomes.append(None)     # hard backpressure shed
                else:
                    raise
        results = [self.result(o) if isinstance(o, str) else o
                   for o in outcomes]
        window_s = time.monotonic() - t0
        return summarize_replay(requests, results, window_s,
                                classes=classes)
