"""Microbatching policy: per-key FIFO queues cut into lane buckets.

This module is the pure, host-only half of the solver service - no
jax, no threads, no wall clock of its own.  Every method takes ``now``
explicitly, so the policy is deterministic under a fake clock (the
test harness) and the service's worker threads are just drivers that
feed it real time.

Policy (ROADMAP item 1b):

* requests queue per ``(handle, tenant, slo-class, dtype, tol-class)``
  - only columns that can ride ONE compiled batched solve share a
  queue, and a batch never mixes tenants or SLO classes (the
  weighted-fair dispatcher's flow is the key's first three fields);
* a queue dispatches when it holds ``max_batch`` requests (reason
  ``"full"``) OR when its oldest request has waited ``max_wait_s``
  (reason ``"max_wait"``) - the classic latency/occupancy knob pair;
* WHICH dispatchable queue goes next is the scheduler's call:
  :meth:`MicroBatchQueue.pop_next` asks the deficit-round-robin
  scheduler (``serve.sched``) to pick a flow by weight and priced
  solve cost; the legacy PR 10 order (oldest queue first, each queue
  drained fully - :meth:`pop_ready`) remains as the ``fair=False``
  reference and the drain path's workhorse;
* a cut batch is padded up to the smallest LANE BUCKET that fits
  (powers of two up to ``max_batch``, :func:`bucket_sizes`), so the
  set of compiled batch shapes is bounded and every post-warmup
  dispatch is a solver-cache hit by construction.  Pad lanes carry
  ``b = 0`` and freeze at iteration 0 (``solver.many.stack_columns``);
* per-request deadlines: an expired request is failed LOUDLY with a
  typed TIMEOUT result at the next pump, never silently dropped and
  never dispatched into a solve whose answer nobody wants
  (:meth:`take_expired` sweeps them - deferred queues included, a
  shed ladder must never hide an expiry);
* backpressure: the total pending count is bounded
  (``queue_limit``) - :meth:`MicroBatchQueue.push` raises
  :class:`QueueFull` rather than buffering unboundedly.  Admission
  control (``serve.admission``) is the polite front door BEFORE this
  hard bound.
"""
from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict, deque
from typing import Deque, Dict, FrozenSet, List, Optional, Tuple

__all__ = [
    "Batch",
    "MicroBatchQueue",
    "QueueFull",
    "QueuedRequest",
    "bucket_for",
    "bucket_sizes",
    "tol_class",
]


class QueueFull(RuntimeError):
    """The service's bounded queue is at ``queue_limit`` - the caller
    must shed load (retry later / reject upstream), not buffer more."""


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """The compiled lane buckets: powers of two up to ``max_batch``,
    plus ``max_batch`` itself when it is not one.  Bounded and known
    at registration time, so a service can warm every shape it will
    ever dispatch."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes: List[int] = []
    k = 1
    while k < max_batch:
        sizes.append(k)
        k *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def bucket_for(n_requests: int, max_batch: int) -> int:
    """The smallest lane bucket holding ``n_requests`` columns."""
    if n_requests < 1:
        raise ValueError(f"a batch needs >= 1 request, got {n_requests}")
    for k in bucket_sizes(max_batch):
        if k >= n_requests:
            return k
    raise ValueError(
        f"{n_requests} requests exceed max_batch={max_batch}")


def tol_class(tol: float) -> str:
    """The decade class of an absolute tolerance - the queue-key
    component that keeps wildly different convergence bars out of one
    batch.  Correctness never depends on it: each lane always solves
    to its OWN ``tol`` (per-lane tolerance arrays), the class only
    groups requests whose iteration counts will be comparable, so a
    loose request is not held hostage by a tight lane."""
    if tol <= 0.0:
        return "exact"
    return f"1e{int(math.floor(math.log10(tol) + 0.5))}"


@dataclasses.dataclass
class QueuedRequest:
    """One pending right-hand side (host arrays + bookkeeping only)."""

    request_id: str
    handle_key: str
    b: object                      # 1-D numpy array
    dtype: str                     # numpy dtype name of b
    tol: float
    enqueue_t: float               # service-clock seconds
    deadline_t: Optional[float]    # absolute service-clock, or None
    future: object                 # concurrent.futures.Future
    handle: object = None          # serve.service.OperatorHandle
    #: retry bookkeeping (serve retry policy): dispatch attempts so
    #: far, and the backoff gate - a request with ``ready_t`` in the
    #: future is parked (not cut into a batch, not driving the
    #: max_wait clock) until the clock reaches it
    attempts: int = 0
    ready_t: Optional[float] = None
    #: tolerance-class degradation marked this request (queue-pressure
    #: load shedding); surfaced on its RequestResult
    degraded: bool = False
    #: multi-tenant scheduling (serve.admission / serve.sched): the
    #: submitting tenant and the SLO class its latency is accounted
    #: against - together with the handle they name the weighted-fair
    #: dispatcher's flow
    tenant: str = "default"
    slo_class: str = "silver"
    #: causal request trace (telemetry.tracing.RequestTrace) minted by
    #: submit() when an event sink is live; None otherwise - the
    #: tracing-off path carries no trace state at all
    trace: object = None

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def ready(self, now: float) -> bool:
        return self.ready_t is None or self.ready_t <= now


@dataclasses.dataclass
class Batch:
    """A cut microbatch, ready to dispatch onto one batched solve."""

    #: (handle_key, tenant, slo_class, dtype, tol_class)
    key: Tuple[str, str, str, str, str]
    requests: List[QueuedRequest]
    bucket: int                    # padded lane count (compiled shape)
    reason: str                    # "full" | "max_wait" | "drain"

    @property
    def occupancy(self) -> float:
        return len(self.requests) / self.bucket

    @property
    def padding_fraction(self) -> float:
        return (self.bucket - len(self.requests)) / self.bucket

    @property
    def tenant(self) -> str:
        return self.key[1]

    @property
    def slo_class(self) -> str:
        return self.key[2]

    @property
    def flow(self) -> Tuple[str, str, str]:
        return self.key[:3]


class MicroBatchQueue:
    """The dispatch policy over per-``(handle, tenant, slo-class,
    dtype, tol-class)`` FIFOs.  Not thread-safe on its own - the
    service serializes access under its lock.

    ``sched`` is an optional ``serve.sched.WeightedFairScheduler``
    consulted by :meth:`pop_next`; without one, pop order is the
    legacy insertion order."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.002,
                 queue_limit: int = 256, sched=None, cost_fn=None):
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {queue_limit}")
        self.max_batch = int(max_batch)
        self.buckets = bucket_sizes(self.max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.sched = sched
        #: prices one dispatch of a queue's handle for the scheduler
        #: (seconds estimate; only relative values matter) - default
        #: uniform
        self.cost_fn = cost_fn or (lambda handle: 1.0)
        self._queues: "OrderedDict[Tuple, Deque[QueuedRequest]]" = \
            OrderedDict()
        self._depth = 0
        # incremental per-tenant / per-class pending counts: submit-
        # path gauges and the defer-release check read these instead
        # of scanning every flow's queue
        self._tenant_depth: Dict[str, int] = {}
        self._class_depth: Dict[str, int] = {}

    def _count(self, req: QueuedRequest, delta: int) -> None:
        for table, key in ((self._tenant_depth, req.tenant),
                           (self._class_depth, req.slo_class)):
            n = table.get(key, 0) + delta
            if n:
                table[key] = n
            else:
                table.pop(key, None)
        self._depth += delta

    def depth(self) -> int:
        """Total pending requests across every queue."""
        return self._depth

    def depth_by_tenant(self) -> Dict[str, int]:
        """Pending requests per tenant (the per-tenant depth gauge)."""
        return dict(self._tenant_depth)

    def depth_by_class(self) -> Dict[str, int]:
        """Pending requests per SLO class (the defer-release check)."""
        return dict(self._class_depth)

    def pending_requests(self, handle_key: Optional[str] = None
                         ) -> List[QueuedRequest]:
        """Every queued request (optionally one handle's), in queue
        order.  Caller holds the service lock; used by migrate() to
        stamp ``migration`` spans into the traces of the requests the
        mesh swap affects."""
        out: List[QueuedRequest] = []
        for key, q in self._queues.items():
            if handle_key is not None and key[0] != handle_key:
                continue
            out.extend(q)
        return out

    def key_for(self, req: QueuedRequest
                ) -> Tuple[str, str, str, str, str]:
        return (req.handle_key, req.tenant, req.slo_class, req.dtype,
                tol_class(req.tol))

    def push(self, req: QueuedRequest) -> int:
        """Enqueue; returns the new total depth.  Raises
        :class:`QueueFull` at ``queue_limit`` (backpressure is the
        caller's signal to shed load)."""
        if self._depth >= self.queue_limit:
            raise QueueFull(
                f"solver service queue is full ({self._depth} pending, "
                f"limit {self.queue_limit}); shed load or raise "
                f"queue_limit")
        self._queues.setdefault(self.key_for(req), deque()).append(req)
        self._count(req, +1)
        return self._depth

    # -- expiry sweep ----------------------------------------------------

    def take_expired(self, now: float) -> List[QueuedRequest]:
        """Remove and return every expired-deadline request.  Runs
        over EVERY queue - deferred classes included: the shed ladder
        may hold a queue's dispatches, never its expiries (the caller
        owes each removed request a typed TIMEOUT result)."""
        out: List[QueuedRequest] = []
        for key in list(self._queues):
            q = self._queues[key]
            live: Deque[QueuedRequest] = deque()
            for req in q:
                if req.expired(now):
                    out.append(req)
                    self._count(req, -1)
                else:
                    live.append(req)
            if live:
                self._queues[key] = live
            else:
                del self._queues[key]
        return out

    # -- dispatchability -------------------------------------------------

    def _dispatchable(self, now: float, drain: bool,
                      defer: FrozenSet[str]
                      ) -> "OrderedDict[Tuple, str]":
        """Queues the policy would cut a batch from right now
        (key -> reason), in queue-insertion order.  ``defer`` names
        SLO classes the shed ladder is holding (ignored on drain -
        close() must terminate)."""
        out: "OrderedDict[Tuple, str]" = OrderedDict()
        for key, q in self._queues.items():
            if not drain and key[2] in defer:
                continue
            ready = [r for r in q if not r.expired(now)
                     and (drain or r.ready(now))]
            if not ready:
                continue
            if len(ready) >= self.max_batch:
                out[key] = "full"
            elif drain:
                out[key] = "drain"
            elif now - ready[0].enqueue_t >= self.max_wait_s:
                out[key] = "max_wait"
        return out

    def deferred_ready(self, now: float, defer: FrozenSet[str]
                       ) -> List[Tuple]:
        """Queues that WOULD dispatch right now but for the shed
        ladder's defer rung - what the service's ``sched_dispatch``
        decision="defer" events report."""
        if not defer:
            return []
        held = self._dispatchable(now, False, frozenset())
        live = self._dispatchable(now, False, defer)
        return [k for k in held if k not in live]

    def _cut(self, key: Tuple, now: float, reason: str) -> Batch:
        """Cut one batch from ``key``'s queue: the first (oldest)
        dispatchable requests in order, capped at ``max_batch``.
        Expired/parked requests keep their positions for the sweeps
        that own them."""
        drain = reason == "drain"
        q = self._queues[key]
        cut: List[QueuedRequest] = []
        rest: Deque[QueuedRequest] = deque()
        for r in q:
            if len(cut) < self.max_batch and not r.expired(now) \
                    and (drain or r.ready(now)):
                cut.append(r)
                self._count(r, -1)
            else:
                rest.append(r)
        if rest:
            self._queues[key] = rest
        else:
            del self._queues[key]
        return Batch(key=key, requests=cut,
                     bucket=bucket_for(len(cut), self.max_batch),
                     reason=reason)

    def pop_next(self, now: float, drain: bool = False,
                 defer: FrozenSet[str] = frozenset()
                 ) -> Optional[Batch]:
        """Cut the ONE batch the scheduler says goes next (or ``None``
        when nothing is dispatchable at ``now``).  The dispatch loop
        calls this repeatedly - each worker takes one batch at a time,
        so deficit-round-robin interleaves flows even within a single
        policy pass."""
        cands = self._dispatchable(now, drain, defer)
        if not cands:
            return None
        if self.sched is None:
            key = next(iter(cands))        # legacy insertion order
        else:
            # group candidate keys by flow (first key per flow wins -
            # insertion order within a flow, the PR 10 behavior)
            flows: "OrderedDict[Tuple, Tuple]" = OrderedDict()
            costs: Dict[Tuple, float] = {}
            for key in cands:
                flow = key[:3]
                if flow not in flows:
                    flows[flow] = key
                    head = self._queues[key][0]
                    costs[flow] = float(self.cost_fn(head.handle))
            key = flows[self.sched.pick(costs)]
        return self._cut(key, now, cands[key])

    # -- legacy pop (PR 10 order; drain + fair=False reference) ----------

    def pop_ready(self, now: float, drain: bool = False
                  ) -> Tuple[List[Batch], List[QueuedRequest]]:
        """Cut everything the policy says is dispatchable at ``now``
        in the PR 10 order: oldest queue first, each queue's full
        batches then its aged partial.

        Returns ``(batches, timeouts)``; ``timeouts`` are the
        expired-deadline requests removed from the queues - the
        caller owes each a typed TIMEOUT result.
        """
        batches: List[Batch] = []
        timeouts: List[QueuedRequest] = []
        for key in list(self._queues):
            q = self._queues[key]
            # expired deadlines leave the queue first: they must not
            # occupy a lane (their answer is already too late) and
            # must not hold the max_wait clock of younger requests
            live = deque()
            for req in q:
                if req.expired(now):
                    timeouts.append(req)
                    self._count(req, -1)
                else:
                    live.append(req)
            # backoff-parked retries are not dispatchable yet and do
            # not drive the max_wait clock; a drain flushes them too
            # (their backoff is advisory, close() must terminate)
            ready = deque(r for r in live
                          if drain or r.ready(now))
            delayed = [r for r in live
                       if not (drain or r.ready(now))]
            while len(ready) >= self.max_batch:
                cut = [ready.popleft() for _ in range(self.max_batch)]
                for r in cut:
                    self._count(r, -1)
                batches.append(Batch(key=key, requests=cut,
                                     bucket=self.max_batch,
                                     reason="full"))
            if ready and (drain
                          or now - ready[0].enqueue_t >= self.max_wait_s):
                cut = list(ready)
                ready.clear()
                for r in cut:
                    self._count(r, -1)
                batches.append(Batch(
                    key=key, requests=cut,
                    bucket=bucket_for(len(cut), self.max_batch),
                    reason="drain" if drain else "max_wait"))
            q = self._queues[key] = deque(list(ready) + delayed)
            if not q:
                del self._queues[key]
        return batches, timeouts

    def next_wake(self, now: float,
                  defer: FrozenSet[str] = frozenset()
                  ) -> Optional[float]:
        """The earliest absolute time any policy clause can fire (a
        max-wait expiry, a request deadline, a backoff-parked retry's
        ``ready_t``, or NOW when a queue is already full), or ``None``
        when the queues are empty.  The worker threads sleep exactly
        until this - the full-queue clause matters because a submit's
        notify is lost while a worker is mid-solve (not waiting):
        without it, a queue that filled during the solve would sleep
        out max_wait before its "dispatch on full" batch went.

        ``defer`` names the SLO classes the shed ladder is holding:
        their queues contribute deadlines (a deferred expiry must
        still be swept into its typed TIMEOUT on time) and parked
        ``ready_t``s, but not dispatch wakes - a held queue cannot
        dispatch, so waking for its max_wait would be a busy-loop."""
        wake: Optional[float] = None

        def consider(t: Optional[float]):
            nonlocal wake
            if t is not None:
                wake = t if wake is None else min(wake, t)

        for key, q in self._queues.items():
            if not q:
                continue
            deferred = key[2] in defer
            ready = [r for r in q if r.ready(now)]
            if not deferred and len(ready) >= self.max_batch:
                return now
            for r in q:
                consider(r.deadline_t)
                # a backoff-parked retry becomes actionable at ready_t
                # (the PR 12 fold this module's regression test pins:
                # without it an idle worker oversleeps the backoff
                # until the next unrelated submit)
                if r.ready_t is not None and not r.ready(now):
                    consider(r.ready_t)
            if ready and not deferred:
                consider(ready[0].enqueue_t + self.max_wait_s)
        return wake
