"""Microbatching policy: per-key FIFO queues cut into lane buckets.

This module is the pure, host-only half of the solver service - no
jax, no threads, no wall clock of its own.  Every method takes ``now``
explicitly, so the policy is deterministic under a fake clock (the
test harness) and the service's worker thread is just a driver that
feeds it real time.

Policy (ROADMAP item 1b):

* requests queue per ``(handle, dtype, tol-class)`` - only columns
  that can ride ONE compiled batched solve share a queue;
* a queue dispatches when it holds ``max_batch`` requests (reason
  ``"full"``) OR when its oldest request has waited ``max_wait_s``
  (reason ``"max_wait"``) - the classic latency/occupancy knob pair;
* a cut batch is padded up to the smallest LANE BUCKET that fits
  (powers of two up to ``max_batch``, :func:`bucket_sizes`), so the
  set of compiled batch shapes is bounded and every post-warmup
  dispatch is a solver-cache hit by construction.  Pad lanes carry
  ``b = 0`` and freeze at iteration 0 (``solver.many.stack_columns``);
* per-request deadlines: an expired request is failed LOUDLY with a
  typed TIMEOUT result at the next pump, never silently dropped and
  never dispatched into a solve whose answer nobody wants;
* backpressure: the total pending count is bounded
  (``queue_limit``) - :meth:`MicroBatchQueue.push` raises
  :class:`QueueFull` rather than buffering unboundedly.
"""
from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict, deque
from typing import Deque, List, Optional, Tuple

__all__ = [
    "Batch",
    "MicroBatchQueue",
    "QueueFull",
    "QueuedRequest",
    "bucket_for",
    "bucket_sizes",
    "tol_class",
]


class QueueFull(RuntimeError):
    """The service's bounded queue is at ``queue_limit`` - the caller
    must shed load (retry later / reject upstream), not buffer more."""


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """The compiled lane buckets: powers of two up to ``max_batch``,
    plus ``max_batch`` itself when it is not one.  Bounded and known
    at registration time, so a service can warm every shape it will
    ever dispatch."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes: List[int] = []
    k = 1
    while k < max_batch:
        sizes.append(k)
        k *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def bucket_for(n_requests: int, max_batch: int) -> int:
    """The smallest lane bucket holding ``n_requests`` columns."""
    if n_requests < 1:
        raise ValueError(f"a batch needs >= 1 request, got {n_requests}")
    for k in bucket_sizes(max_batch):
        if k >= n_requests:
            return k
    raise ValueError(
        f"{n_requests} requests exceed max_batch={max_batch}")


def tol_class(tol: float) -> str:
    """The decade class of an absolute tolerance - the queue-key
    component that keeps wildly different convergence bars out of one
    batch.  Correctness never depends on it: each lane always solves
    to its OWN ``tol`` (per-lane tolerance arrays), the class only
    groups requests whose iteration counts will be comparable, so a
    loose request is not held hostage by a tight lane."""
    if tol <= 0.0:
        return "exact"
    return f"1e{int(math.floor(math.log10(tol) + 0.5))}"


@dataclasses.dataclass
class QueuedRequest:
    """One pending right-hand side (host arrays + bookkeeping only)."""

    request_id: str
    handle_key: str
    b: object                      # 1-D numpy array
    dtype: str                     # numpy dtype name of b
    tol: float
    enqueue_t: float               # service-clock seconds
    deadline_t: Optional[float]    # absolute service-clock, or None
    future: object                 # concurrent.futures.Future
    handle: object = None          # serve.service.OperatorHandle
    #: retry bookkeeping (serve retry policy): dispatch attempts so
    #: far, and the backoff gate - a request with ``ready_t`` in the
    #: future is parked (not cut into a batch, not driving the
    #: max_wait clock) until the clock reaches it
    attempts: int = 0
    ready_t: Optional[float] = None
    #: tolerance-class degradation marked this request (queue-pressure
    #: load shedding); surfaced on its RequestResult
    degraded: bool = False

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def ready(self, now: float) -> bool:
        return self.ready_t is None or self.ready_t <= now


@dataclasses.dataclass
class Batch:
    """A cut microbatch, ready to dispatch onto one batched solve."""

    key: Tuple[str, str, str]      # (handle_key, dtype, tol_class)
    requests: List[QueuedRequest]
    bucket: int                    # padded lane count (compiled shape)
    reason: str                    # "full" | "max_wait" | "drain"

    @property
    def occupancy(self) -> float:
        return len(self.requests) / self.bucket

    @property
    def padding_fraction(self) -> float:
        return (self.bucket - len(self.requests)) / self.bucket


class MicroBatchQueue:
    """The dispatch policy over per-``(handle, dtype, tol-class)``
    FIFOs.  Not thread-safe on its own - the service serializes access
    under its lock."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.002,
                 queue_limit: int = 256):
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {queue_limit}")
        self.max_batch = int(max_batch)
        self.buckets = bucket_sizes(self.max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self._queues: "OrderedDict[Tuple, Deque[QueuedRequest]]" = \
            OrderedDict()
        self._depth = 0

    def depth(self) -> int:
        """Total pending requests across every queue."""
        return self._depth

    def key_for(self, req: QueuedRequest) -> Tuple[str, str, str]:
        return (req.handle_key, req.dtype, tol_class(req.tol))

    def push(self, req: QueuedRequest) -> int:
        """Enqueue; returns the new total depth.  Raises
        :class:`QueueFull` at ``queue_limit`` (backpressure is the
        caller's signal to shed load)."""
        if self._depth >= self.queue_limit:
            raise QueueFull(
                f"solver service queue is full ({self._depth} pending, "
                f"limit {self.queue_limit}); shed load or raise "
                f"queue_limit")
        self._queues.setdefault(self.key_for(req), deque()).append(req)
        self._depth += 1
        return self._depth

    def pop_ready(self, now: float, drain: bool = False
                  ) -> Tuple[List[Batch], List[QueuedRequest]]:
        """Cut everything the policy says is dispatchable at ``now``.

        Returns ``(batches, timeouts)``: full batches first (oldest
        queue first), then max-wait expiries (with ``drain=True``,
        every remaining request regardless of age).  ``timeouts`` are
        the expired-deadline requests removed from the queues - the
        caller owes each a typed TIMEOUT result.
        """
        batches: List[Batch] = []
        timeouts: List[QueuedRequest] = []
        for key in list(self._queues):
            q = self._queues[key]
            # expired deadlines leave the queue first: they must not
            # occupy a lane (their answer is already too late) and
            # must not hold the max_wait clock of younger requests
            live = deque()
            for req in q:
                (timeouts if req.expired(now) else live).append(req)
            self._depth -= len(q) - len(live)
            # backoff-parked retries are not dispatchable yet and do
            # not drive the max_wait clock; a drain flushes them too
            # (their backoff is advisory, close() must terminate)
            ready = deque(r for r in live
                          if drain or r.ready(now))
            delayed = [r for r in live
                       if not (drain or r.ready(now))]
            while len(ready) >= self.max_batch:
                cut = [ready.popleft() for _ in range(self.max_batch)]
                self._depth -= len(cut)
                batches.append(Batch(key=key, requests=cut,
                                     bucket=self.max_batch,
                                     reason="full"))
            if ready and (drain
                          or now - ready[0].enqueue_t >= self.max_wait_s):
                cut = list(ready)
                ready.clear()
                self._depth -= len(cut)
                batches.append(Batch(
                    key=key, requests=cut,
                    bucket=bucket_for(len(cut), self.max_batch),
                    reason="drain" if drain else "max_wait"))
            q = self._queues[key] = deque(list(ready) + delayed)
            if not q:
                del self._queues[key]
        return batches, timeouts

    def next_wake(self, now: float) -> Optional[float]:
        """The earliest absolute time any policy clause can fire (a
        max-wait expiry, a request deadline, or NOW when a queue is
        already full), or ``None`` when the queues are empty.  The
        worker thread sleeps exactly until this - the full-queue
        clause matters because a submit's notify is lost while the
        worker is mid-solve (not waiting): without it, a queue that
        filled during the solve would sleep out max_wait before its
        "dispatch on full" batch went."""
        wake: Optional[float] = None
        for q in self._queues.values():
            if not q:
                continue
            ready = [r for r in q if r.ready(now)]
            if len(ready) >= self.max_batch:
                return now
            candidates = [r.deadline_t for r in q
                          if r.deadline_t is not None]
            if ready:
                candidates.append(ready[0].enqueue_t + self.max_wait_s)
            # a backoff-parked retry becomes actionable at its ready_t
            candidates += [r.ready_t for r in q
                           if r.ready_t is not None and not r.ready(now)]
            if not candidates:
                continue
            t = min(candidates)
            wake = t if wake is None else min(wake, t)
        return wake
